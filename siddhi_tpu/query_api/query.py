"""Query object model: input streams, pattern state elements, selectors, outputs.

TPU-native counterpart of reference modules/siddhi-query-api/.../execution/**:
  - Query, OnDemandQuery/StoreQuery     (execution/query/Query.java, StoreQuery.java)
  - SingleInputStream / JoinInputStream / StateInputStream
        (execution/query/input/stream/*.java)
  - StateElement tree (pattern IR)      (execution/query/input/state/*.java)
  - Selector / OutputAttribute          (execution/query/selection/*)
  - OutputStream actions + rate limiting (execution/query/output/**)
  - Partition IR                        (execution/partition/*)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import List, Optional, Tuple, Union

from .annotation import Annotation
from .expression import Expression, Variable


# ---------------------------------------------------------------- handlers

@dataclass
class StreamHandler:
    """A step in a single-stream handler chain: filter, window or stream function."""


@dataclass
class Filter(StreamHandler):
    expr: Expression


@dataclass
class WindowHandler(StreamHandler):
    """``#window.length(5)`` — name + args."""
    namespace: Optional[str]
    name: str
    params: List[Expression] = field(default_factory=list)


@dataclass
class StreamFunctionHandler(StreamHandler):
    """``#str:tokenize(...)`` style per-event stream functions."""
    namespace: Optional[str]
    name: str
    params: List[Expression] = field(default_factory=list)


# ---------------------------------------------------------------- input streams

@dataclass
class InputStream:
    pass


@dataclass
class SingleInputStream(InputStream):
    stream_id: str
    stream_ref: Optional[str] = None          # `as e1` alias
    handlers: List[StreamHandler] = field(default_factory=list)
    is_inner: bool = False                    # '#InnerStream' inside partitions
    is_fault: bool = False                    # '!FaultStream'

    def filter(self, expr: Expression) -> "SingleInputStream":
        self.handlers.append(Filter(expr))
        return self

    def window(self, name: str, *params: Expression,
               namespace: Optional[str] = None) -> "SingleInputStream":
        self.handlers.append(WindowHandler(namespace, name, list(params)))
        return self

    def function(self, name: str, *params: Expression,
                 namespace: Optional[str] = None) -> "SingleInputStream":
        self.handlers.append(StreamFunctionHandler(namespace, name, list(params)))
        return self

    @property
    def window_handler(self) -> Optional[WindowHandler]:
        for h in self.handlers:
            if isinstance(h, WindowHandler):
                return h
        return None


class JoinType(Enum):
    JOIN = "join"               # inner
    LEFT_OUTER = "left outer"
    RIGHT_OUTER = "right outer"
    FULL_OUTER = "full outer"


class EventTrigger(Enum):
    """Which side's arrivals trigger join output (`unidirectional`)."""
    LEFT = "left"
    RIGHT = "right"
    ALL = "all"


@dataclass
class JoinInputStream(InputStream):
    left: SingleInputStream
    join_type: JoinType
    right: SingleInputStream
    on: Optional[Expression] = None
    trigger: EventTrigger = EventTrigger.ALL
    within: Optional[Expression] = None
    per: Optional[Expression] = None


class StateType(Enum):
    PATTERN = "pattern"
    SEQUENCE = "sequence"


# ---------------------------------------------------------------- state elements
# (pattern IR — reference execution/query/input/state/*.java, 8 classes)

@dataclass
class StateElement:
    within_ms: Optional[int] = None


@dataclass
class StreamStateElement(StateElement):
    """A single condition: ``e1=StreamA[filter]``."""
    stream: SingleInputStream = None


@dataclass
class AbsentStreamStateElement(StreamStateElement):
    """``not StreamA[filter] for 5 sec`` (waiting_time_ms) or logical-not partner."""
    waiting_time_ms: Optional[int] = None


@dataclass
class NextStateElement(StateElement):
    """``A -> B`` (pattern) or ``A, B`` (sequence strict next)."""
    state: StateElement = None
    next: StateElement = None


@dataclass
class EveryStateElement(StateElement):
    """``every (...)`` — re-arm on each match start.  within_ms is the
    group-scoped ``every (...) within t`` bound (SiddhiQL.g4: EVERY
    '(' chain ')' within_time?)."""
    state: StateElement = None
    within_ms: Optional[int] = None


class LogicalOp(Enum):
    AND = "and"
    OR = "or"


@dataclass
class LogicalStateElement(StateElement):
    state1: StreamStateElement = None
    op: LogicalOp = LogicalOp.AND
    state2: StreamStateElement = None


@dataclass
class CountStateElement(StateElement):
    """``A<m:n>`` / ``A+``(1:ANY) / ``A*``(0:ANY) / ``A?``(0:1)."""
    ANY = -1
    state: StreamStateElement = None
    min_count: int = 1
    max_count: int = 1


@dataclass
class StateInputStream(InputStream):
    state_type: StateType = StateType.PATTERN
    state: StateElement = None
    within_ms: Optional[int] = None

    def all_stream_ids(self) -> List[str]:
        out: List[str] = []

        def rec(el: StateElement):
            if isinstance(el, StreamStateElement):
                out.append(el.stream.stream_id)
            elif isinstance(el, NextStateElement):
                rec(el.state)
                rec(el.next)
            elif isinstance(el, EveryStateElement):
                rec(el.state)
            elif isinstance(el, LogicalStateElement):
                rec(el.state1)
                rec(el.state2)
            elif isinstance(el, CountStateElement):
                rec(el.state)
        rec(self.state)
        return out


# ---------------------------------------------------------------- selection

@dataclass
class OutputAttribute:
    rename: str
    expr: Expression


@dataclass
class OrderByAttribute:
    variable: Variable
    ascending: bool = True


@dataclass
class Selector:
    select_all: bool = False                      # `select *`
    attributes: List[OutputAttribute] = field(default_factory=list)
    group_by: List[Variable] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by: List[OrderByAttribute] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None

    def select(self, rename: str, expr: Expression) -> "Selector":
        self.attributes.append(OutputAttribute(rename, expr))
        return self


# ---------------------------------------------------------------- output

class OutputEventsFor(Enum):
    CURRENT = "current"
    EXPIRED = "expired"
    ALL = "all"


@dataclass
class OutputStream:
    target_id: str = ""
    events_for: OutputEventsFor = OutputEventsFor.CURRENT
    is_inner: bool = False
    is_fault: bool = False


@dataclass
class InsertIntoStream(OutputStream):
    pass


@dataclass
class ReturnStream(OutputStream):
    """Query with no `insert into` — results go to the query callback only."""


@dataclass
class DeleteStream(OutputStream):
    on: Expression = None


@dataclass
class UpdateSetAssignment:
    table_variable: Variable = None
    value: Expression = None


@dataclass
class UpdateStream(OutputStream):
    on: Expression = None
    set_assignments: List[UpdateSetAssignment] = field(default_factory=list)


@dataclass
class UpdateOrInsertStream(UpdateStream):
    pass


# ---------------------------------------------------------------- rate limiting

class OutputRateType(Enum):
    ALL = "all"
    FIRST = "first"
    LAST = "last"
    SNAPSHOT = "snapshot"


@dataclass
class OutputRate:
    type: OutputRateType = OutputRateType.ALL
    every_events: Optional[int] = None
    every_ms: Optional[int] = None


# ---------------------------------------------------------------- query

@dataclass
class Query:
    input_stream: InputStream = None
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = field(default_factory=ReturnStream)
    output_rate: Optional[OutputRate] = None
    annotations: List[Annotation] = field(default_factory=list)

    @staticmethod
    def query() -> "Query":
        return Query()

    def from_(self, input_stream: InputStream) -> "Query":
        self.input_stream = input_stream
        return self

    def select(self, selector: Selector) -> "Query":
        self.selector = selector
        return self

    def insert_into(self, stream_id: str,
                    events_for: OutputEventsFor = OutputEventsFor.CURRENT) -> "Query":
        self.output_stream = InsertIntoStream(stream_id, events_for)
        return self

    def annotation(self, ann: Annotation) -> "Query":
        self.annotations.append(ann)
        return self

    @property
    def name(self) -> Optional[str]:
        for a in self.annotations:
            if a.name.lower() == "info":
                return a.get("name")
        return None


# ---------------------------------------------------------------- partition

@dataclass
class RangePartitionProperty:
    partition_key: str       # label routed to
    condition: Expression = None


@dataclass
class PartitionType:
    stream_id: str = ""


@dataclass
class ValuePartitionType(PartitionType):
    expression: Expression = None


@dataclass
class RangePartitionType(PartitionType):
    ranges: List[RangePartitionProperty] = field(default_factory=list)


@dataclass
class Partition:
    partition_types: List[PartitionType] = field(default_factory=list)
    queries: List[Query] = field(default_factory=list)
    annotations: List[Annotation] = field(default_factory=list)

    def with_(self, pt: PartitionType) -> "Partition":
        self.partition_types.append(pt)
        return self

    def add_query(self, q: Query) -> "Partition":
        self.queries.append(q)
        return self


# ---------------------------------------------------------------- store (on-demand) query

class StoreQueryType(Enum):
    FIND = "find"
    INSERT = "insert"
    DELETE = "delete"
    UPDATE = "update"
    UPDATE_OR_INSERT = "update_or_insert"


@dataclass
class InputStore:
    store_id: str
    store_ref: Optional[str] = None
    on: Optional[Expression] = None
    within: Optional[Tuple[Expression, Expression]] = None   # aggregation within
    per: Optional[Expression] = None


@dataclass
class StoreQuery:
    type: StoreQueryType = StoreQueryType.FIND
    input_store: Optional[InputStore] = None
    selector: Selector = field(default_factory=Selector)
    output_stream: Optional[OutputStream] = None
    select_values: List[Expression] = field(default_factory=list)  # insert payload


ExecutionElement = Union[Query, Partition]
