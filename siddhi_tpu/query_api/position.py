"""Source-position threading for query_api nodes.

The tokenizer records (line, col, offset) on every token, but the object
model the parser emits historically dropped them — so anything diagnosed
after parse (semantic analysis, planner rejections) could only say *what*
was wrong, never *where*.  This module threads positions through without
touching dataclass signatures: a node's position lives in a side attribute
(``_pos``) set via :func:`set_pos`, which works uniformly for mutable
dataclasses (Query, StateElement, ...) and frozen ones (the Expression
tree) alike.

Positions are advisory: any node may lack one (fluent-API construction,
``dataclasses.replace`` copies), and consumers must degrade gracefully —
:func:`pos_of` returns ``None`` in that case, and
:func:`nearest_pos` walks an expression tree for the first positioned
node so a diagnostic can anchor to a parent when the exact node is bare.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

_POS_ATTR = "_pos"


@dataclass(frozen=True)
class SourcePos:
    """1-based line/column plus absolute offset into the app source."""
    line: int
    col: int
    offset: int = -1

    def __str__(self) -> str:
        return f"line {self.line}, col {self.col}"


def set_pos(node: Any, pos: "SourcePos | None") -> Any:
    """Attach a source position to any query_api node; returns the node.

    Uses ``object.__setattr__`` so frozen Expression dataclasses accept it
    too.  Silently no-ops for nodes that cannot carry attributes (slots)."""
    if pos is None or node is None:
        return node
    try:
        object.__setattr__(node, _POS_ATTR, pos)
    except (AttributeError, TypeError):
        pass
    return node


def pos_of(node: Any) -> Optional[SourcePos]:
    """The position attached to *node*, or None."""
    return getattr(node, _POS_ATTR, None)


def pos_from_token(tok: Any) -> SourcePos:
    """Build a SourcePos from a compiler token (duck-typed: line/col/pos)."""
    return SourcePos(tok.line, tok.col, getattr(tok, "pos", -1))


def nearest_pos(node: Any) -> Optional[SourcePos]:
    """Position of *node*, else the first positioned descendant (pre-order
    over dataclass fields) — lets diagnostics anchor composite expressions
    whose inner tokens carried the position."""
    seen = set()
    stack = [node]
    while stack:
        n = stack.pop(0)
        if n is None or id(n) in seen:
            continue
        seen.add(id(n))
        p = pos_of(n)
        if p is not None:
            return p
        for f in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, f, None)
            vs = v if isinstance(v, (list, tuple)) else [v]
            stack.extend(x for x in vs
                         if hasattr(x, "__dataclass_fields__"))
    return None
