"""Annotation tree: ``@name(key='value', 'positional', @nested(...))``.

(reference: modules/siddhi-query-api/.../annotation/{Annotation,Element}.java)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Element:
    key: Optional[str]
    value: str


@dataclass
class Annotation:
    name: str
    elements: List[Element] = field(default_factory=list)
    annotations: List["Annotation"] = field(default_factory=list)

    def element(self, key: Optional[str], value: str) -> "Annotation":
        self.elements.append(Element(key, value))
        return self

    def get(self, key: Optional[str], default: Optional[str] = None) -> Optional[str]:
        for e in self.elements:
            if e.key == key:
                return e.value
        return default

    def positional(self) -> List[str]:
        return [e.value for e in self.elements if e.key is None]

    def as_dict(self) -> dict:
        return {e.key: e.value for e in self.elements if e.key is not None}


def find_annotation(annotations: List[Annotation], name: str) -> Optional[Annotation]:
    for a in annotations:
        if a.name.lower() == name.lower():
            return a
    return None


def find_all(annotations: List[Annotation], name: str) -> List[Annotation]:
    return [a for a in annotations if a.name.lower() == name.lower()]
