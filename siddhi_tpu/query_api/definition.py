"""Definitions: streams, tables, windows, triggers, functions, aggregations.

TPU-native counterpart of reference modules/siddhi-query-api/.../definition/*.java
(8 files).  An `Attribute` carries a Siddhi type which maps onto a columnar
dtype for the device arrays (see siddhi_tpu/core/event.py):

    int    -> int32      long  -> int64
    float  -> float32    double-> float64
    bool   -> bool_      string-> host object column (dict-encoded on device)
    object -> host object column (never shipped to device)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, List, Optional

from .annotation import Annotation
from .expression import Expression


class AttrType(Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    @staticmethod
    def of(name: str) -> "AttrType":
        try:
            return AttrType(name.lower())
        except ValueError:
            from ..utils.errors import SiddhiParserException
            raise SiddhiParserException(
                f"Invalid attribute type {name!r}") from None


@dataclass
class Attribute:
    name: str
    type: AttrType


@dataclass
class AbstractDefinition:
    id: str
    attributes: List[Attribute] = field(default_factory=list)
    annotations: List[Annotation] = field(default_factory=list)

    def attribute(self, name: str, type: "AttrType | str") -> "AbstractDefinition":
        if isinstance(type, str):
            type = AttrType.of(type)
        if any(a.name == name for a in self.attributes):
            from ..utils.errors import DuplicateAttributeError
            raise DuplicateAttributeError(
                f"'{name}' is already defined for {self.id}")
        self.attributes.append(Attribute(name, type))
        return self

    def annotation(self, ann: Annotation) -> "AbstractDefinition":
        self.annotations.append(ann)
        return self

    @property
    def attribute_names(self) -> List[str]:
        return [a.name for a in self.attributes]

    def attribute_type(self, name: str) -> AttrType:
        for a in self.attributes:
            if a.name == name:
                return a.type
        from ..utils.errors import AttributeNotExistError
        raise AttributeNotExistError(f"No attribute '{name}' in '{self.id}'")

    def index_of(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        return -1


@dataclass
class StreamDefinition(AbstractDefinition):
    @staticmethod
    def id_(stream_id: str) -> "StreamDefinition":
        return StreamDefinition(stream_id)


@dataclass
class TableDefinition(AbstractDefinition):
    @staticmethod
    def id_(table_id: str) -> "TableDefinition":
        return TableDefinition(table_id)


@dataclass
class WindowDefinition(AbstractDefinition):
    """Named window: ``define window W (a int) length(5) output all events``.
    (reference definition/WindowDefinition.java)"""
    window_name: Optional[str] = None
    window_namespace: Optional[str] = None
    window_params: List[Expression] = field(default_factory=list)
    output_event_type: str = "all"  # current | expired | all

    @staticmethod
    def id_(window_id: str) -> "WindowDefinition":
        return WindowDefinition(window_id)


@dataclass
class TriggerDefinition:
    """``define trigger T at {'start' | every <time> | '<cron>'}``
    (reference definition/TriggerDefinition.java).  Trigger streams carry a
    single long attribute ``triggered_time``."""
    id: str
    at_start: bool = False
    at_every_ms: Optional[int] = None
    at_cron: Optional[str] = None
    annotations: List[Annotation] = field(default_factory=list)


@dataclass
class FunctionDefinition:
    """``define function F[lang] return type { body }`` — script functions.
    Language for this framework is python (reference supported JS/scala via JSR-223;
    definition/FunctionDefinition.java)."""
    id: str
    language: str = "python"
    return_type: Optional[AttrType] = None
    body: str = ""


@dataclass
class AggregationDefinition:
    """``define aggregation A from S select ... group by ... aggregate [by attr]
    every sec...year`` — incremental aggregation (reference
    definition/AggregationDefinition.java + aggregation/TimePeriod.java)."""
    id: str
    basic_single_input_stream: Any = None     # SingleInputStream
    selector: Any = None                      # Selector
    aggregate_attribute: Optional[str] = None  # timestamp attribute (external time)
    time_periods: List[str] = field(default_factory=list)  # ['sec','min',...]
    annotations: List[Annotation] = field(default_factory=list)


DURATION_ORDER = ["sec", "min", "hour", "day", "month", "year"]
DURATION_MS = {
    "sec": 1_000,
    "min": 60_000,
    "hour": 3_600_000,
    "day": 86_400_000,
    # month/year are calendar durations; fixed sizes used for bucketing
    "month": 2_592_000_000,   # 30 days
    "year": 31_536_000_000,   # 365 days
}
