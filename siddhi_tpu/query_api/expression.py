"""Expression tree — the typed IR for all scalar computation in queries.

TPU-native counterpart of the reference's expression object model
(reference: modules/siddhi-query-api/src/main/java/io/siddhi/query/api/expression/**,
~20 files: math Add..Mod, conditions And/Or/Not/Compare/In/IsNull, constants,
Variable, AttributeFunction).  Unlike the reference — where each node is later
interpreted per event by an ExpressionExecutor object tree — these nodes are
*compiled once* into vectorised column programs (see siddhi_tpu/plan/expr_compiler.py)
that evaluate a whole event micro-batch with one fused XLA computation.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, List, Optional, Tuple


class CompareOp(Enum):
    LT = "<"
    GT = ">"
    LTE = "<="
    GTE = ">="
    EQ = "=="
    NEQ = "!="


class MathOp(Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"


@dataclass(frozen=True)
class Expression:
    """Base class.  Fluent constructors mirror the reference's static factory
    API (Expression.value/variable/add/compare/... in
    reference expression/Expression.java) so the framework is usable without
    the SiddhiQL text front end."""

    # ---- fluent factories (query-api parity) ----
    @staticmethod
    def value(v: Any) -> "Constant":
        return Constant(v)

    @staticmethod
    def variable(name: str) -> "Variable":
        return Variable(name)

    @staticmethod
    def add(l: "Expression", r: "Expression") -> "MathExpr":
        return MathExpr(MathOp.ADD, l, r)

    @staticmethod
    def subtract(l: "Expression", r: "Expression") -> "MathExpr":
        return MathExpr(MathOp.SUB, l, r)

    @staticmethod
    def multiply(l: "Expression", r: "Expression") -> "MathExpr":
        return MathExpr(MathOp.MUL, l, r)

    @staticmethod
    def divide(l: "Expression", r: "Expression") -> "MathExpr":
        return MathExpr(MathOp.DIV, l, r)

    @staticmethod
    def mod(l: "Expression", r: "Expression") -> "MathExpr":
        return MathExpr(MathOp.MOD, l, r)

    @staticmethod
    def compare(l: "Expression", op: CompareOp, r: "Expression") -> "Compare":
        return Compare(l, op, r)

    @staticmethod
    def and_(l: "Expression", r: "Expression") -> "And":
        return And(l, r)

    @staticmethod
    def or_(l: "Expression", r: "Expression") -> "Or":
        return Or(l, r)

    @staticmethod
    def not_(e: "Expression") -> "Not":
        return Not(e)

    @staticmethod
    def is_null(e: "Expression") -> "IsNull":
        return IsNull(e)

    @staticmethod
    def in_(e: "Expression", source_id: str) -> "In":
        return In(e, source_id)

    @staticmethod
    def function(name: str, *args: "Expression", namespace: Optional[str] = None) -> "AttributeFunction":
        return AttributeFunction(namespace, name, tuple(args))

    @staticmethod
    def time_sec(v: float) -> "TimeConstant":
        return TimeConstant(int(v * 1000))

    @staticmethod
    def time_millisec(v: int) -> "TimeConstant":
        return TimeConstant(int(v))

    @staticmethod
    def time_minute(v: float) -> "TimeConstant":
        return TimeConstant(int(v * 60_000))

    @staticmethod
    def time_hour(v: float) -> "TimeConstant":
        return TimeConstant(int(v * 3_600_000))


@dataclass(frozen=True)
class Constant(Expression):
    value: Any
    # optional explicit siddhi type tag ('int','long','float','double','string','bool')
    type_hint: Optional[str] = None


@dataclass(frozen=True)
class TimeConstant(Constant):
    """A duration literal (`5 sec`, `1 min`...) normalised to milliseconds.
    (reference: expression/constant/TimeConstant.java)"""
    value: int = 0
    type_hint: Optional[str] = "long"

    @property
    def millis(self) -> int:
        return self.value


@dataclass(frozen=True)
class Variable(Expression):
    """Attribute reference, optionally qualified: ``[stream_id.]attribute`` with an
    optional pattern-event index: ``e1[2].price``, ``e1[last].price``.
    (reference: expression/Variable.java)"""
    attribute: str = ""
    stream_id: Optional[str] = None
    # index within a pattern's captured event chain; None = default,
    # -1 encodes LAST (reference StateEvent LAST addressing, state/StateEvent.java:138-182)
    stream_index: Optional[int] = None

    def of_stream(self, stream_id: str) -> "Variable":
        return dataclasses.replace(self, stream_id=stream_id)


LAST_INDEX = -1  # Variable.stream_index value meaning e[last]


@dataclass(frozen=True)
class MathExpr(Expression):
    op: MathOp = MathOp.ADD
    left: Expression = None
    right: Expression = None


@dataclass(frozen=True)
class Compare(Expression):
    left: Expression = None
    op: CompareOp = CompareOp.EQ
    right: Expression = None


@dataclass(frozen=True)
class And(Expression):
    left: Expression = None
    right: Expression = None


@dataclass(frozen=True)
class Or(Expression):
    left: Expression = None
    right: Expression = None


@dataclass(frozen=True)
class Not(Expression):
    expr: Expression = None


@dataclass(frozen=True)
class IsNull(Expression):
    expr: Optional[Expression] = None
    # `e1 is null` inside patterns refers to a stream state, not an attribute
    stream_id: Optional[str] = None
    stream_index: Optional[int] = None


@dataclass(frozen=True)
class In(Expression):
    """``expr in TableName`` membership test against a table.
    (reference: expression/condition/In.java)"""
    expr: Expression = None
    source_id: str = ""


@dataclass(frozen=True)
class AttributeFunction(Expression):
    """Function call ``ns:name(args...)`` — built-ins (coalesce, cast, convert,
    ifThenElse, ...) or extension functions resolved through the extension
    registry.  (reference: expression/AttributeFunction.java + executor/function/**)"""
    namespace: Optional[str] = None
    name: str = ""
    args: Tuple[Expression, ...] = ()


def walk(expr: Expression):
    """Yield every node of an expression tree (pre-order)."""
    if expr is None:
        return
    yield expr
    if isinstance(expr, MathExpr):
        yield from walk(expr.left)
        yield from walk(expr.right)
    elif isinstance(expr, Compare):
        yield from walk(expr.left)
        yield from walk(expr.right)
    elif isinstance(expr, (And, Or)):
        yield from walk(expr.left)
        yield from walk(expr.right)
    elif isinstance(expr, Not):
        yield from walk(expr.expr)
    elif isinstance(expr, IsNull):
        if expr.expr is not None:
            yield from walk(expr.expr)
    elif isinstance(expr, In):
        yield from walk(expr.expr)
    elif isinstance(expr, AttributeFunction):
        for a in expr.args:
            yield from walk(a)


def variables_of(expr: Expression) -> List[Variable]:
    return [n for n in walk(expr) if isinstance(n, Variable)]


def expr_children(e):
    """Dataclass-field children of an expression node — list AND tuple
    fields (AttributeFunction.args is a Tuple; a list-only walk silently
    skips nodes nested in function arguments)."""
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            if hasattr(x, "__dataclass_fields__"):
                yield x
