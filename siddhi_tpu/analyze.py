"""``python -m siddhi_tpu.analyze`` — compile-time analysis CLI.

Usage:
    python -m siddhi_tpu.analyze app.siddhi            # pretty output
    python -m siddhi_tpu.analyze app.siddhi --json     # machine-readable
    python -m siddhi_tpu.analyze app.siddhi --strict   # warnings = errors
    python -m siddhi_tpu.analyze app.siddhi --plan     # plan-level verify
    python -m siddhi_tpu.analyze - < app.siddhi        # read stdin
    python -m siddhi_tpu.analyze --catalog             # list every code
    python -m siddhi_tpu.analyze --catalog-md          # docs/analysis.md
                                                       # catalog section
    python -m siddhi_tpu.analyze --engine              # engine
                                                       # self-analysis
                                                       # (CE/LW audit)
    python -m siddhi_tpu.analyze app.siddhi --schema   # static persistent-
                                                       # state schema dump
    python -m siddhi_tpu.analyze --schema              # declaration
                                                       # registry + SC002
                                                       # audit
    python -m siddhi_tpu.analyze app.siddhi --numeric  # numeric-safety
                                                       # verifier (NS0xx
                                                       # value ranges)

Exit codes: 0 clean (infos allowed), 1 errors (or warnings under
--strict), 2 usage error.

The DEFAULT path imports no jax — this command runs fine on a machine
with no accelerator stack (tests/test_analysis.py asserts jax stays out
of sys.modules).  ``--plan`` is the explicit opt-in that builds the
runtime, extracts the Plan-IR, runs the automaton verifier + jaxpr
kernel sanitizer + static cost model (PV0xx/PC0xx codes), and therefore
lazily imports the jax-backed planner.
"""
from __future__ import annotations

import argparse
import json
import sys


def _print_catalog() -> None:
    from .analysis import CATALOG
    for code in sorted(CATALOG):
        e = CATALOG[code]
        print(f"{code}  {e.severity.value:<7}  {e.title}")
        print(f"       {e.meaning}")
        print(f"       fix: {e.fix}")


def _plan_result(text: str, engine, hbm_budget):
    """--plan: build the app (lazy jax import via the planner), attach
    the plan-level verification (with the jaxpr sanitizer on) and return
    the merged AnalysisResult."""
    from .analysis.plan_verify import attach_plan_analysis
    from .core.runtime import SiddhiManager
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(text)
    try:
        attach_plan_analysis(rt, hbm_budget_mb=hbm_budget, jaxpr=True)
        return rt.analysis
    finally:
        rt.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.analyze",
        description="Static semantic analysis for SiddhiQL apps: type "
                    "checking, unbounded-state, retrace-hazard, "
                    "partition-safety and host-fallback diagnostics; "
                    "--plan adds compiled-plan verification (automaton "
                    "reachability, jaxpr sanitation, HBM/FLOP cost).")
    ap.add_argument("app", nargs="?",
                    help="path to a .siddhi app file, or '-' for stdin")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as a JSON array")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--engine", nargs="?", const="self",
                    choices=("auto", "device", "host", "self"),
                    help="with a value (auto/device/host): override the "
                         "engine mode assumed by the SP0xx performance "
                         "passes.  Bare --engine (no value): run the "
                         "engine self-analysis instead — the CE0xx "
                         "lock-order/blocking audit and CE1xx hot-path "
                         "lint over siddhi_tpu's own source (no app "
                         "argument, no jax import).  Note: bare --engine "
                         "greedily consumes a following app path; use "
                         "--engine=auto etc. when combining with an app.")
    ap.add_argument("--plan", action="store_true",
                    help="build the runtime and run the plan-level "
                         "verifier + cost model (imports jax)")
    ap.add_argument("--hbm-budget", type=float, metavar="MB",
                    help="with --plan: emit PC002 when the predicted "
                         "persistent HBM footprint exceeds this budget")
    ap.add_argument("--schema", action="store_true",
                    help="with an app: dump its static persistent-state "
                         "schema (element ids, governing declarations, "
                         "engine routing, layout digests) — no jax "
                         "import.  Without an app: print every "
                         "@persistent_schema declaration in the engine "
                         "source and run the SC002 audit")
    ap.add_argument("--numeric", action="store_true",
                    help="run only the numeric-safety verifier: the "
                         "NS0xx value-range / precision pass seeded "
                         "from @attr:range and @app:rate declarations "
                         "— no jax import; exits 1 on warning-level "
                         "findings")
    ap.add_argument("--catalog", action="store_true",
                    help="print the diagnostic catalog and exit")
    ap.add_argument("--catalog-md", action="store_true",
                    help="print the generated docs/analysis.md catalog "
                         "section and exit")
    args = ap.parse_args(argv)

    if args.catalog:
        _print_catalog()
        return 0
    if args.catalog_md:
        from .analysis import catalog_markdown
        print(catalog_markdown())
        return 0
    if args.engine == "self":
        from .analysis.engine import analyze_engine
        report = analyze_engine()
        if args.json:
            print(json.dumps({"ok": report.ok,
                              "engine_audit": report.as_dicts()},
                             indent=1))
        else:
            print(report.render())
        if report.errors or report.stale_allowlist \
                or (args.strict and report.warnings):
            return 1
        return 0
    if args.schema and not args.app:
        # declaration registry + SC002 audit over the engine source —
        # static, jax-free, no app needed
        from .analysis.state_schema import (audit_declarations,
                                            static_declarations)
        decls = static_declarations()
        findings = audit_declarations()
        if args.json:
            print(json.dumps(
                {"ok": not findings,
                 "declarations": {k: d.as_dict()
                                  for k, d in sorted(decls.items())},
                 "findings": [{"code": c, "message": m}
                              for c, m in findings]}, indent=1))
        else:
            for k in sorted(decls):
                d = decls[k]
                print(f"{d.name:<22} v{d.version}  {d.digest()}  {k}")
            for c, m in findings:
                print(f"{c}: {m}")
            print(f"{len(decls)} declaration(s), "
                  f"{len(findings)} audit finding(s)")
        return 1 if findings else 0
    if not args.app:
        ap.print_usage(sys.stderr)
        return 2
    if args.app == "-":
        text = sys.stdin.read()
        name = "<stdin>"
    else:
        try:
            with open(args.app) as f:
                text = f.read()
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        name = args.app

    if args.numeric:
        from .analysis.ranges import analyze_numeric
        try:
            report = analyze_numeric(
                text, engine=None if args.engine in (None, "self")
                else args.engine)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"error: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(report.as_dict(), indent=1))
        else:
            print(report.dump(), end="")
        bad = [d for d in report.findings
               if d.severity.value != "info" or args.strict]
        return 1 if bad else 0

    if args.schema:
        from .analysis.state_schema import extract_app_schema
        try:
            schema = extract_app_schema(
                text, engine=None if args.engine in (None, "self")
                else args.engine)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"error: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(schema.as_dict(), indent=1))
        else:
            print(schema.dump(), end="")
        return 1 if schema.findings else 0

    if args.plan:
        try:
            result = _plan_result(text, args.engine, args.hbm_budget)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"error: plan build failed: {e}", file=sys.stderr)
            return 1
    else:
        from .analysis import analyze
        result = analyze(text, engine=args.engine)

    if args.json:
        doc = {"app": result.app_name,
               "ok": result.ok,
               "diagnostics": result.as_dicts()}
        plan = getattr(result, "plan", None)
        if plan is not None:
            doc["plan"] = plan.as_dict()
        print(json.dumps(doc, indent=1))
    else:
        print(result.render(name))
        plan = getattr(result, "plan", None)
        if plan is not None:
            c = plan.cost
            print(f"plan: {len(plan.plan.automata)} automaton/automata, "
                  f"{len(plan.plan.programs)} program(s), "
                  f"{plan.pruned_states} state(s) pruned, "
                  f"predicted HBM {c.total_hbm_bytes} B, "
                  f"~{c.total_flops_per_event} FLOPs/event")

    if result.errors or (args.strict and result.warnings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
