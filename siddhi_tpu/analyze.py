"""``python -m siddhi_tpu.analyze`` — compile-time analysis CLI.

Usage:
    python -m siddhi_tpu.analyze app.siddhi            # pretty output
    python -m siddhi_tpu.analyze app.siddhi --json     # machine-readable
    python -m siddhi_tpu.analyze app.siddhi --strict   # warnings = errors
    python -m siddhi_tpu.analyze - < app.siddhi        # read stdin
    python -m siddhi_tpu.analyze --catalog             # list every code

Exit codes: 0 clean (infos allowed), 1 errors (or warnings under
--strict), 2 usage error.  The analyzer itself imports no jax — this
command runs fine on a machine with no accelerator stack.
"""
from __future__ import annotations

import argparse
import json
import sys


def _print_catalog() -> None:
    from .analysis import CATALOG
    for code in sorted(CATALOG):
        e = CATALOG[code]
        print(f"{code}  {e.severity.value:<7}  {e.title}")
        print(f"       {e.meaning}")
        print(f"       fix: {e.fix}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.analyze",
        description="Static semantic analysis for SiddhiQL apps: type "
                    "checking, unbounded-state, retrace-hazard, "
                    "partition-safety and host-fallback diagnostics.")
    ap.add_argument("app", nargs="?",
                    help="path to a .siddhi app file, or '-' for stdin")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as a JSON array")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--engine", choices=("auto", "device", "host"),
                    help="override the engine mode assumed by the SP0xx "
                         "performance passes")
    ap.add_argument("--catalog", action="store_true",
                    help="print the diagnostic catalog and exit")
    args = ap.parse_args(argv)

    if args.catalog:
        _print_catalog()
        return 0
    if not args.app:
        ap.print_usage(sys.stderr)
        return 2
    if args.app == "-":
        text = sys.stdin.read()
        name = "<stdin>"
    else:
        try:
            with open(args.app) as f:
                text = f.read()
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        name = args.app

    from .analysis import analyze
    result = analyze(text, engine=args.engine)

    if args.json:
        print(json.dumps({"app": result.app_name,
                          "ok": result.ok,
                          "diagnostics": result.as_dicts()}, indent=1))
    else:
        print(result.render(name))

    if result.errors or (args.strict and result.warnings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
