"""Partition-axis shard-out: consistent key→shard routing + per-shard
engine clones pinned to their own devices (round 15, ROADMAP item 2).

The paper's thesis is thousands of partitions' NFA states stepped as one
batched kernel; production means *millions* of keys.  One monolithic
``[P, ...]`` slab tops out at a single device's HBM and re-keys the
whole slab on growth.  This module supplies the scale-out mechanics the
keyed device runtimes (plan/planner.py) compose:

  * **Canonical FNV-1a** over ``str(key)`` UTF-8 bytes — scalar and
    NumPy-vectorized forms that agree bit-for-bit, shared by the shard
    router here and the multi-host process router
    (parallel/multihost.owner_of).  The assignment is part of the
    checkpoint contract (a restored per-shard snapshot only makes sense
    if every key still routes to the same shard), so
    tests/test_shards.py pins literal hash vectors: any change to this
    function is a breaking format change, not a refactor.
  * **One hash pass per batch, not per event**: ``split_rows`` routes
    via ``np.unique(return_inverse=True)`` — FNV runs over the DISTINCT
    keys only and the inverse scatter fans the shard ids back out.
  * **Per-shard elastic state** (:class:`EngineShard`): each shard owns
    an engine clone, its own key→lane map, its own in-flight queue and
    grow-and-replay bookkeeping.  A hot shard overflowing its lane
    capacity grows and replays AT SHARD GRANULARITY — siblings' carries
    are never touched (tests assert object identity).

Shard-local dispatch means NO collectives on the hot path: every
shard's jitted step runs on committed operands pinned to that shard's
device, so XLA dispatches device-locally.  Statistics aggregation
(``shard_stats`` rows summed into rt.statistics) is the one allowed
reduction, and it is a host-side sum over tiny counters.

Kill switch: ``SIDDHI_TPU_SHARDS=N`` (N >= 2) enables sharded keyed
runtimes; unset/``0``/``off`` keeps the single-slab path byte-identical
to previous rounds.
"""
from __future__ import annotations

import os
from collections import deque
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

SHARDS_ENV = "SIDDHI_TPU_SHARDS"

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_FNV_MASK = (1 << 64) - 1

_U64_OFFSET = np.uint64(_FNV_OFFSET)
_U64_PRIME = np.uint64(_FNV_PRIME)


def resolve_shards(n: Optional[int] = None) -> int:
    """Requested shard count: explicit arg wins, else ``SIDDHI_TPU_SHARDS``.
    Returns 0 (disabled) unless the resolved value is >= 2 — one shard IS
    the monolithic slab, so it routes through the unsharded path."""
    if n is None:
        raw = os.environ.get(SHARDS_ENV, "").strip().lower()
        if raw in ("", "0", "off", "false", "no"):
            return 0
        try:
            n = int(raw)
        except ValueError:
            return 0
    return int(n) if int(n) >= 2 else 0


# ===================================================================
# canonical FNV-1a (scalar + vectorized, bit-identical)
# ===================================================================

def fnv1a(key: Any) -> int:
    """64-bit FNV-1a over the canonical ``str(key)`` UTF-8 bytes.

    ``str()`` (not ``repr()``) is the canonical form: ``repr`` of numpy
    scalars changed across numpy majors (``repr(np.str_('a'))`` is
    ``"np.str_('a')"`` on numpy 2), which would silently re-route every
    key.  ``str(np.str_('a')) == 'a'`` and ``str(np.int64(5)) == '5'``
    are stable, and match the vectorized form's ``astype('U')``."""
    h = _FNV_OFFSET
    for b in str(key).encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _FNV_MASK
    return h


def fnv1a_vec(keys: Sequence[Any]) -> np.ndarray:
    """Vectorized :func:`fnv1a`: uint64 hash per key, one fused pass over
    the character columns instead of a Python loop per byte.  Agrees
    bit-for-bit with the scalar form for str/int keys (pinned by
    tests/test_shards.py).  Keys with embedded NUL bytes have no stable
    fixed-width representation and take the scalar fallback upstream."""
    arr = np.asarray(keys)
    if arr.dtype.kind != "U":
        arr = arr.astype("U")           # canonical str() form
    n = arr.shape[0]
    if n == 0:
        return np.empty(0, np.uint64)
    enc = np.char.encode(arr, "utf-8")  # S<w>, NUL-padded
    w = enc.dtype.itemsize
    h = np.full(n, _U64_OFFSET, np.uint64)
    if w == 0:                          # all-empty keys hash to the basis
        return h
    u8 = np.ascontiguousarray(enc).view(np.uint8).reshape(n, w)
    live = np.ones(n, bool)
    for i in range(w):
        byte = u8[:, i]
        live &= byte != 0               # NUL padding = end of string
        if not live.any():
            break
        mixed = (h ^ byte.astype(np.uint64)) * _U64_PRIME   # wraps mod 2^64
        h = np.where(live, mixed, h)
    return h


def owner_ids(keys: Sequence[Any], n_owners: int) -> np.ndarray:
    """Per-row owner index (shard or process) for a key column — one
    vectorized hash pass over the batch's DISTINCT keys.  Arrays whose
    elements do not sort (mixed-type object columns) fall back to the
    scalar hash per distinct key; the assignment is identical."""
    arr = np.asarray(keys)
    if arr.shape[0] == 0:
        return np.empty(0, np.int64)
    try:
        uniq, inv = np.unique(arr, return_inverse=True)
        owners_u = (fnv1a_vec(uniq) % np.uint64(n_owners)).astype(np.int64)
    except TypeError:                   # unsortable object column
        seen = {}
        owners = np.empty(arr.shape[0], np.int64)
        for i, k in enumerate(arr.tolist()):
            o = seen.get(k)
            if o is None:
                o = fnv1a(k) % n_owners
                seen[k] = o
            owners[i] = o
        return owners
    return owners_u[inv.reshape(-1)]


def split_rows(keys: Sequence[Any],
               n_shards: int) -> List[Tuple[int, np.ndarray]]:
    """Route a batch: ``[(shard_id, row_indices), ...]`` for the
    NON-EMPTY shards, in shard order.  Row indices are ascending, so
    per-key event order is preserved inside each shard's sub-block."""
    sids = owner_ids(keys, n_shards)
    order = np.argsort(sids, kind="stable")
    sorted_sids = sids[order]
    bounds = np.searchsorted(sorted_sids,
                             np.arange(n_shards + 1, dtype=np.int64))
    out = []
    for s in range(n_shards):
        lo, hi = bounds[s], bounds[s + 1]
        if hi > lo:
            out.append((s, np.sort(order[lo:hi])))
    return out


# ===================================================================
# shard set construction
# ===================================================================

def shard_devices(n_shards: int) -> List[Any]:
    """Round-robin device pinning: shard i lives on
    ``jax.devices()[i % ndev]``.  On the 8-virtual-device tier-1 CPU
    mesh this spreads 8 shards across all 8 devices; with fewer devices
    shards share (still shard-local dispatch, just co-resident)."""
    import jax
    devs = jax.devices()
    return [devs[i % len(devs)] for i in range(n_shards)]


class EngineShard:
    """One shard of a keyed device runtime: an engine clone pinned to a
    device, plus ALL the per-shard mutable state (key→lane map, in-flight
    queue, grow-and-replay bookkeeping, stats counters).  The runtime
    never mixes state across EngineShards — that isolation is what makes
    growth and checkpointing shard-granular."""

    __slots__ = ("idx", "engine", "device", "key_lanes", "inflight",
                 "dropped_seen", "events", "dispatches", "grows")

    def __init__(self, idx: int, engine: Any, device: Any,
                 key_lanes: Optional[dict] = None):
        self.idx = idx
        self.engine = engine
        self.device = device
        self.key_lanes = key_lanes if key_lanes is not None else {}
        self.inflight: deque = deque()
        self.dropped_seen = 0
        self.events = 0
        self.dispatches = 0
        self.grows = 0

    def stats_row(self) -> dict:
        cap = getattr(self.engine, "n_partitions",
                      getattr(self.engine, "n_lanes", 1))
        return {"shard": self.idx, "device": str(self.device),
                "keys": len(self.key_lanes), "capacity": int(cap),
                "events": self.events, "dispatches": self.dispatches,
                "grows": self.grows}


def build_shards(template: Any, n_shards: int) -> List[EngineShard]:
    """Template engine → N EngineShards.  Shard 0 adopts the template
    itself (re-pinned to device 0); shards 1..N-1 are fresh-state clones
    via the engine's ``clone_for_shard(device)``.  Clones share the
    compiled jitted step (one XLA trace cache across the shard set) but
    own their carry, dictionaries and growth axes."""
    devs = shard_devices(n_shards)
    template.pin_to_device(devs[0])
    shards = [EngineShard(0, template, devs[0])]
    for i in range(1, n_shards):
        shards.append(EngineShard(i, template.clone_for_shard(devs[i]),
                                  devs[i]))
    return shards


def routing_digest(n_owners: int = 8, n_keys: int = 64) -> str:
    """Stable fingerprint of the key→owner assignment over a fixed probe
    vector — carried in tools/t1_report.py round artifacts so `--compare`
    flags any silent routing shift (which would orphan every per-shard
    checkpoint) as a regression."""
    import hashlib
    probe = [f"key-{i}" for i in range(n_keys)] + \
        [str(i) for i in range(n_keys)]
    owners = owner_ids(np.asarray(probe), n_owners)
    return hashlib.sha256(owners.tobytes()).hexdigest()[:16]
