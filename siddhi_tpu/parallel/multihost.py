"""Multi-host execution of SiddhiManager apps (round 5, VERDICT r4 #5).

Design: SHARED-NOTHING key sharding — the reference's own distributed
model (its distributed sinks ship events between engines with a
``@distribution(strategy='partitioned')`` policy, core/source_sink.py;
the JVM engine itself is single-node, SURVEY §5.8).  Every process runs
the SAME ``@app:engine('device')`` partitioned app through the public
SiddhiManager API under ``jax.distributed``; a hash of the app's
partition key routes each event to exactly one owning process, so the
planner-built KEYED device runtime — key→lane mapping, @Async flush
barriers, pipelined ingest, grow-and-replay — executes with
``jax.process_count() > 1`` on every host, over that host's LOCAL
devices.

Why shared-nothing rather than one global-mesh program: a global mesh
requires LOCK-STEP dispatch (every process must issue the identical jit
call sequence, so one busy key range would stall the cluster), and slot
growth would need a collective re-shard.  With host-local engines,
growth is a local matter (each process grows its own slab — no
collective, no rejection), ingest cadence is independent per host, and
the only cross-host traffic is the fused stats all-reduce below plus
whatever a fronting router moves.  The raw global-mesh SPMD path remains
available as ``parallel.distributed.DistributedPatternBank``.

Cross-host collective: ``global_stats()`` all-reduces per-host counters
over DCN through one tiny jitted psum on the GLOBAL mesh — the same
collective the bank path fuses into its step.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .distributed import init_distributed, process_info
from .shards import fnv1a, owner_ids


def partition_key_attrs(app) -> Dict[str, str]:
    """stream id → partition key attribute (``partition with (attr of
    Stream)``) — the router's shard key.  Two partitions keying the SAME
    stream on DIFFERENT attributes cannot share one shard route: every
    process would need every event, defeating the shared-nothing split —
    reject loudly instead of silently dropping matches."""
    from ..query_api.query import Partition, ValuePartitionType
    from ..query_api.expression import Variable
    from ..utils.errors import SiddhiAppCreationError
    out: Dict[str, str] = {}
    for el in app.execution_elements:
        if not isinstance(el, Partition):
            continue
        for pt in el.partition_types:
            if isinstance(pt, ValuePartitionType) and \
                    isinstance(pt.expression, Variable):
                attr = pt.expression.attribute
                prev = out.get(pt.stream_id)
                if prev is not None and prev != attr:
                    raise SiddhiAppCreationError(
                        f"multi-host routing: stream '{pt.stream_id}' is "
                        f"partitioned by both '{prev}' and '{attr}' — "
                        "one shard key per stream is required")
                out[pt.stream_id] = attr
    return out


def owner_of(key, num_processes: int) -> int:
    """Stable key → owning process: the CANONICAL FNV-1a over
    ``str(key)`` UTF-8 bytes (parallel/shards.fnv1a), so every host
    computes the same answer with no coordination — and the same hash
    the partition shard router uses, so a fronting router can compute
    both process and shard from one pass.  Round 15 moved the byte
    source from ``repr(key)`` (numpy-major-unstable) to ``str(key)``;
    tests/test_shards.py pins literal vectors so the assignment can
    never silently shift again."""
    return fnv1a(key) % num_processes


class MultiHostAppRuntime:
    """One process's slice of a multi-host SiddhiManager deployment.

    ``send_batch`` accepts the GLOBAL stream (as a router would see it)
    and forwards only the rows whose partition key this process owns —
    asserting that the union of all processes' outputs equals a
    single-process run is the cross-host parity contract
    (tests/test_multihost.py)."""

    def __init__(self, app_string: str,
                 coordinator: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None):
        from ..compiler import SiddhiCompiler
        from ..core.runtime import SiddhiManager
        init_distributed(coordinator, num_processes, process_id)
        self.pid, self.nproc = process_info()
        self.app = SiddhiCompiler.parse(app_string)
        self.key_attrs = partition_key_attrs(self.app)
        self.manager = SiddhiManager()
        self.runtime = self.manager.create_siddhi_app_runtime(app_string)
        self._stats_jit = None

    # ------------------------------------------------------------ routing

    def owns(self, key) -> bool:
        return owner_of(key, self.nproc) == self.pid

    def send_batch(self, stream_id: str, columns: Dict[str, np.ndarray],
                   timestamps: np.ndarray) -> int:
        """Route the global batch: keep only this process's keys; returns
        the number of rows ingested locally."""
        key_attr = self.key_attrs.get(stream_id)
        if key_attr is None:
            keep = np.ones(len(timestamps), bool)     # broadcast stream
        else:
            # vectorized routing (round 15): one FNV pass over the
            # batch's DISTINCT keys (np.unique + inverse scatter) instead
            # of a pure-Python hash loop per ROW — shared with the
            # partition shard router (parallel/shards.py)
            keys = columns[key_attr]
            keep = owner_ids(keys, self.nproc) == self.pid
        n = int(keep.sum())
        if n:
            self.runtime.get_input_handler(stream_id).send_batch(
                {k: np.asarray(v)[keep] for k, v in columns.items()},
                timestamps=np.asarray(timestamps)[keep])
        return n

    # ------------------------------------------------------------ control

    def start(self):
        self.runtime.start()

    def flush(self):
        self.runtime.flush()

    def shutdown(self):
        self.runtime.shutdown()

    def add_callback(self, target: str, cb):
        self.runtime.add_callback(target, cb)

    # ------------------------------------------------------------ stats

    _DIGIT = 1 << 20        # 3 base-2^20 digits: int32 lanes stay exact
    #                         (digit sums < 2^20 * hosts) without x64 —
    #                         JAX canonicalizes i64→i32 by default, so a
    #                         single int lane would wrap past 2^31

    def global_stats(self, **local_counters: int) -> Dict[str, int]:
        """All-reduce per-host counters over the GLOBAL device set — the
        framework's cross-host collective (XLA lowers the sum over the
        process-sharded axis to an all-reduce over DCN).  Exact for
        counters below 2^60 on up to 2^11 hosts (three base-2^20 digits
        summed in int32)."""
        import jax

        names = sorted(local_counters)
        if self._stats_jit is None:
            import jax.numpy as jnp
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)
            mesh = Mesh(np.asarray(jax.devices()), ("h",))
            self._stats_sh = NamedSharding(mesh, P("h"))
            self._stats_jit = jax.jit(
                lambda v: jnp.sum(v, axis=0),
                out_shardings=NamedSharding(mesh, P()))
        n_local = len(jax.local_devices())
        D = self._DIGIT
        # [n_local, n_names, 3] — device 0's row carries the digits, the
        # rest zeros → global sum == sum over hosts
        vec = np.zeros((n_local, len(names), 3), np.int32)
        for j, n in enumerate(names):
            v = int(local_counters[n])
            vec[0, j] = [v % D, (v // D) % D, v // (D * D)]
        g = jax.make_array_from_process_local_data(self._stats_sh, vec)
        digits = np.asarray(self._stats_jit(g))
        return {n: int(digits[j, 0]) + int(digits[j, 1]) * D +
                int(digits[j, 2]) * D * D
                for j, n in enumerate(names)}

    def global_statistics(self) -> Dict[str, int]:
        """Cluster-wide engine statistics: every host's StatisticsManager
        counters (junction throughput counts, query latency event counts,
        @Async queue depths) summed over the SAME fused DCN all-reduce
        ``global_stats`` uses — COLLECTIVE, so every process must call it
        at the same point.  Keys keep the reference metric naming; with
        one process this degrades to the local snapshot's counters."""
        sm = self.runtime.app_ctx.statistics_manager
        counters: Dict[str, int] = {}
        if sm is not None:
            for k, t in sm.throughput.items():
                counters[k + ".count"] = t.count
            for k, t in sm.latency.items():
                counters[k + ".count"] = t.count
            for k, b in sm.buffered.items():
                counters[k + ".buffered"] = b.buffered
        if not counters or self.nproc <= 1:
            return counters
        return self.global_stats(**counters)
