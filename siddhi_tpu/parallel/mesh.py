"""Partition-axis sharding over a TPU device mesh.

The reference scales by cloning per-key processor graphs inside one JVM
(partition/PartitionRuntime.java:255-308) and has no distributed backend
(SURVEY.md §2.8/§5.8).  Here the partition axis of the NFA state tensors
([P, K] slots, [P, K, S, C] captures) and the [P, T] event lanes shard over
an ICI mesh: every device steps its own partition shard, no collectives on
the hot path.  The optional fused stats reduction (jit_engine_step
stats=True, used by parallel/distributed.DistributedPatternBank) is the one
collective — XLA lowers the sum over the sharded axis to an all-reduce over
ICI/DCN.  Multi-host scale-out uses the same program under jax.distributed
over DCN.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nfa import NfaSpec, build_block_step, make_carry


def partition_mesh(devices: Optional[Sequence] = None,
                   axis: str = "p") -> Mesh:
    """1-D mesh over all (or given) devices; the partition axis maps onto it."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


def auto_mesh(axis: str = "p") -> Optional[Mesh]:
    """The engine-default mesh: all local devices when there is more than
    one, else None (single-chip execution needs no sharding machinery).
    The planner-built device runtimes (plan/planner.py) call this so a
    SiddhiManager user gets ICI-sharded execution wherever the hardware
    has it — the engine-integrated replacement for the reference's per-key
    clone scaling (partition/PartitionRuntime.java:255-308).

    `SIDDHI_TPU_MESH=off` forces single-device (operator escape hatch).

    Under jax.distributed (multi-host), the engine-default mesh is the
    LOCAL device set: SiddhiManager engines are shared-nothing per host
    (parallel/multihost.py routes keys between them), and a global mesh
    would demand lock-step dispatch across processes.  Explicit global
    meshes remain available (parallel/distributed.py)."""
    import os
    if os.environ.get("SIDDHI_TPU_MESH", "auto").lower() == "off":
        return None
    devs = jax.local_devices() if jax.process_count() > 1 \
        else jax.devices()
    if len(devs) <= 1:
        return None
    return partition_mesh(devs, axis)


def round_up_partitions(n_partitions: int, mesh: Optional[Mesh]) -> int:
    """Smallest lane count >= n_partitions divisible by the mesh size (the
    leading axis shards evenly; surplus lanes stay empty)."""
    if mesh is None:
        return n_partitions
    nd = int(mesh.devices.size)
    return -(-n_partitions // nd) * nd


def jit_engine_step(spec: NfaSpec, mesh: Mesh, axis: str = "p",
                    stats: bool = False, donate: bool = True):
    """jit of the raw NFA block step (ops/nfa.build_block_step) with the
    partition axis of carry, event block and match outputs sharded over
    `mesh` — the engine-integrated sharded hot path.  Partition lanes are
    fully independent, so the step itself has ZERO collectives.

    stats=True additionally returns {"matches", "dropped"} global sums
    FUSED into the same executable (one dispatch per block; the reduction
    over the sharded axis is the one collective) — the multi-host path
    (DistributedPatternBank) uses this so each block costs a single
    dispatch."""
    step = build_block_step(spec)

    def stepped(carry, block):
        new_carry, matches = step(carry, block)
        st = {"matches": jnp.sum(matches[0].astype(jnp.int32)),
              "dropped": jnp.sum(new_carry["dropped"])}
        return new_carry, matches, st

    proto_carry = make_carry(spec, 1)
    carry_sh = jax.tree_util.tree_map(
        lambda v: lead_axis_sharding(mesh, v, axis), proto_carry)
    block_sh = {name: NamedSharding(mesh, P(axis, None))
                for name in list(spec.attr_names) +
                ["__ts", "__stream", "__valid"]}

    def lead(nd):
        return NamedSharding(mesh, P(axis, *([None] * (nd - 1))))
    matches_sh = (lead(3), lead(5), lead(3), lead(3), lead(3))
    if not stats:
        return jax.jit(step, in_shardings=(carry_sh, block_sh),
                       out_shardings=(carry_sh, matches_sh),
                       donate_argnums=(0,) if donate else ())
    replicated = NamedSharding(mesh, P())
    stats_sh = {"matches": replicated, "dropped": replicated}
    return jax.jit(stepped, in_shardings=(carry_sh, block_sh),
                   out_shardings=(carry_sh, matches_sh, stats_sh),
                   donate_argnums=0)


def lead_axis_sharding(mesh: Mesh, v, axis: str = "p") -> NamedSharding:
    """Leading-dim-on-`axis` sharding for an array(-like) leaf."""
    return NamedSharding(mesh, P(axis, *([None] * (jnp.ndim(v) - 1))))


def shard_carry(carry: Dict[str, jnp.ndarray], mesh: Mesh,
                axis: str = "p") -> Dict[str, jnp.ndarray]:
    """Place NFA carry tensors with their leading partition dim sharded."""
    return {k: jax.device_put(v, lead_axis_sharding(mesh, v, axis))
            for k, v in carry.items()}


