"""Partition-axis sharding over a TPU device mesh.

The reference scales by cloning per-key processor graphs inside one JVM
(partition/PartitionRuntime.java:255-308) and has no distributed backend
(SURVEY.md §2.8/§5.8).  Here the partition axis of the NFA state tensors
([P, K] slots, [P, K, S, C] captures) and the [P, T] event lanes shard over
an ICI mesh: every device steps its own partition shard, no collectives on
the hot path; global statistics (match counts, dropped counters) reduce with
one psum at block end.  Multi-host scale-out uses the same program under
jax.distributed over DCN.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nfa import NfaSpec, build_block_step, make_carry


def partition_mesh(devices: Optional[Sequence] = None,
                   axis: str = "p") -> Mesh:
    """1-D mesh over all (or given) devices; the partition axis maps onto it."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


def lead_axis_sharding(mesh: Mesh, v, axis: str = "p") -> NamedSharding:
    """Leading-dim-on-`axis` sharding for an array(-like) leaf."""
    return NamedSharding(mesh, P(axis, *([None] * (jnp.ndim(v) - 1))))


def shard_carry(carry: Dict[str, jnp.ndarray], mesh: Mesh,
                axis: str = "p") -> Dict[str, jnp.ndarray]:
    """Place NFA carry tensors with their leading partition dim sharded."""
    return {k: jax.device_put(v, lead_axis_sharding(mesh, v, axis))
            for k, v in carry.items()}


def build_sharded_step(spec: NfaSpec, mesh: Mesh, axis: str = "p"):
    """jit-compiled block step with explicit partition-sharded in/out
    shardings and a summed per-block stats reduction (the only collective —
    with the leading axis sharded XLA lowers it to an all-reduce over ICI)."""
    step = build_block_step(spec)

    def stepped(carry, block):
        new_carry, (mask, caps, ts, _enter, _seq) = step(carry, block)
        stats = {
            "matches": jnp.sum(mask.astype(jnp.int32)),
            "dropped": jnp.sum(new_carry["dropped"]),
        }
        return new_carry, (mask, caps, ts), stats

    replicated = NamedSharding(mesh, P())
    # carry tree structure is fixed by the spec — probe it at P=1
    proto_carry = make_carry(spec, 1)
    carry_sh = jax.tree_util.tree_map(
        lambda v: lead_axis_sharding(mesh, v, axis), proto_carry)
    block_sh = {name: NamedSharding(mesh, P(axis, None))
                for name in list(spec.attr_names) +
                ["__ts", "__stream", "__valid"]}
    matches_sh = (NamedSharding(mesh, P(axis, None, None)),          # mask
                  NamedSharding(mesh, P(axis, *([None] * 4))),       # caps
                  NamedSharding(mesh, P(axis, None, None)))          # ts
    stats_sh = {"matches": replicated, "dropped": replicated}
    return jax.jit(stepped,
                   in_shardings=(carry_sh, block_sh),
                   out_shardings=(carry_sh, matches_sh, stats_sh))


def make_sharded_carry(spec: NfaSpec, n_partitions: int, mesh: Mesh,
                       axis: str = "p") -> Dict[str, jnp.ndarray]:
    return shard_carry(make_carry(spec, n_partitions), mesh, axis)
