"""Partition-axis sharding over a TPU device mesh.

The reference scales by cloning per-key processor graphs inside one JVM
(partition/PartitionRuntime.java:255-308) and has no distributed backend
(SURVEY.md §2.8/§5.8).  Here the partition axis of the NFA state tensors
([P, K] slots, [P, K, S, C] captures) and the [P, T] event lanes shard over
an ICI mesh: every device steps its own partition shard, no collectives on
the hot path; global statistics (match counts, dropped counters) reduce with
one psum at block end.  Multi-host scale-out uses the same program under
jax.distributed over DCN.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nfa import NfaSpec, build_block_step, make_carry


def partition_mesh(devices: Optional[Sequence] = None,
                   axis: str = "p") -> Mesh:
    """1-D mesh over all (or given) devices; the partition axis maps onto it."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


def shard_carry(carry: Dict[str, jnp.ndarray], mesh: Mesh,
                axis: str = "p") -> Dict[str, jnp.ndarray]:
    """Place NFA carry tensors with their leading partition dim sharded."""
    out = {}
    for k, v in carry.items():
        spec = P(axis, *([None] * (v.ndim - 1)))
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def build_sharded_step(spec: NfaSpec, mesh: Mesh, axis: str = "p"):
    """jit-compiled block step with partition-sharded inputs/outputs and a
    psum'd per-block stats reduction (the only collective)."""
    step = build_block_step(spec)

    def stepped(carry, block):
        new_carry, (mask, caps, ts) = step(carry, block)
        # global per-block stats ride one reduction; with the leading axis
        # sharded XLA lowers this to an all-reduce over ICI
        stats = {
            "matches": jnp.sum(mask.astype(jnp.int32)),
            "dropped": jnp.sum(new_carry["dropped"]),
        }
        return new_carry, (mask, caps, ts), stats

    def in_spec(v):
        return NamedSharding(mesh, P(axis, *([None] * (v.ndim - 1))))

    def shardings_like(tree):
        return jax.tree_util.tree_map(in_spec, tree)

    return jax.jit(stepped)


def make_sharded_carry(spec: NfaSpec, n_partitions: int, mesh: Mesh,
                       axis: str = "p") -> Dict[str, jnp.ndarray]:
    return shard_carry(make_carry(spec, n_partitions), mesh, axis)
