"""Multi-host scale-out: the distributed communication backend.

The reference is a single-JVM engine — its deepest "transport" is the LMAX
Disruptor ring and in-memory pub/sub (stream/StreamJunction.java:280-316,
util/transport/InMemoryBroker.java; SURVEY.md §5.8).  The TPU-native
equivalent is a single sharded program spanning hosts: every host runs this
same code under `jax.distributed`, the partition axis of the NFA/aggregation
state shards over the GLOBAL device set (ICI within a slice, DCN across
hosts), and XLA's collectives carry the only cross-host traffic on the hot
path (the per-block stats psum in parallel/mesh.py).

Host-side dataflow:
  - each host ingests the events whose partition keys it OWNS
    (`host_for_partition`: contiguous range split, so key→host routing is a
    single integer divide a fronting load balancer can compute);
  - per-host blocks assemble into one global sharded array with
    `jax.make_array_from_process_local_data` — no host ever materialises
    another host's events;
  - the jitted sharded step runs SPMD on all hosts; each host reads back
    only its own shard of the match outputs (`addressable_shards`), so
    alert egress is host-local too.

Single-host (and the CI virtual-device mesh) is the num_processes=1 special
case of the same code path.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

COORD_ENV = "SIDDHI_TPU_COORDINATOR"        # host:port of process 0
NPROC_ENV = "SIDDHI_TPU_NUM_PROCESSES"
PID_ENV = "SIDDHI_TPU_PROCESS_ID"


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Join (or bootstrap) the multi-host cluster via jax.distributed.

    Reads SIDDHI_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID when
    arguments are omitted.  Returns True if a multi-process runtime was
    initialised, False for the single-process fallback (no env, no args) —
    the rest of the module works identically either way.
    """
    import jax
    coordinator = coordinator or os.environ.get(COORD_ENV)
    if coordinator is None:
        return False
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get(NPROC_ENV, "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get(PID_ENV, "0"))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def process_info() -> Tuple[int, int]:
    """(process_id, num_processes) of this host."""
    import jax
    return jax.process_index(), jax.process_count()


def host_partition_range(n_partitions: int,
                         process_id: Optional[int] = None,
                         num_processes: Optional[int] = None
                         ) -> Tuple[int, int]:
    """[start, stop) of the global partition axis this host ingests.

    Contiguous split matching the mesh's leading-axis sharding: host h of H
    owns rows [h*P/H, (h+1)*P/H).  A fronting router sends an event with
    partition lane p to host p * H // P."""
    pid, nproc = process_info()
    if process_id is None:
        process_id = pid
    if num_processes is None:
        num_processes = nproc
    per = n_partitions // num_processes
    assert per * num_processes == n_partitions, \
        f"n_partitions={n_partitions} must divide by hosts={num_processes}"
    return process_id * per, (process_id + 1) * per


def host_for_partition(p: int, n_partitions: int,
                       num_processes: Optional[int] = None) -> int:
    """Owning host of global partition lane p (router-side helper)."""
    if num_processes is None:
        num_processes = process_info()[1]
    return p * num_processes // n_partitions


def global_block(local_block: Dict[str, np.ndarray], mesh,
                 axis: str = "p") -> Dict:
    """Assemble each host's local [P_local, T] lanes into global sharded
    arrays on `mesh` without cross-host data movement
    (jax.make_array_from_process_local_data: every host contributes the
    shard it already holds)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    out = {}
    for k, v in local_block.items():
        sh = NamedSharding(mesh, P(axis, *([None] * (v.ndim - 1))))
        out[k] = jax.make_array_from_process_local_data(sh, v)
    return out


def local_rows(global_array) -> np.ndarray:
    """This host's rows of a partition-sharded output, in global row order
    (host-local alert egress: each host decodes only the matches of the
    partitions it owns)."""
    shards = sorted([s for s in global_array.addressable_shards],
                    key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)


class DistributedPatternBank:
    """Multi-host ingest/egress adapter over the ENGINE's mesh-sharded
    pattern NFA (plan/nfa_compiler.CompiledPatternNFA with a mesh — the
    same object the planner builds for SiddhiManager apps).  This class
    adds only what multi-host needs: per-host block assembly into one
    global sharded array (`global_block`) and host-local match egress
    (`local_rows`), plus a jitted global stats reduction — the framework's
    one hot-path collective, lowered by XLA to an all-reduce over ICI/DCN
    (≙ the reference's per-key clone scaling,
    partition/PartitionRuntime.java:255-308, which has no distributed
    equivalent at all — SURVEY §5.8).
    """

    def __init__(self, app_string: str, n_partitions: int, n_slots: int = 8,
                 mesh=None, axis: str = "p"):
        from .mesh import jit_engine_step, partition_mesh
        from ..plan.nfa_compiler import CompiledPatternNFA

        self.mesh = mesh if mesh is not None else partition_mesh()
        self.axis = axis
        n_dev = len(self.mesh.devices.reshape(-1))
        assert n_partitions % n_dev == 0, \
            f"n_partitions={n_partitions} must divide device count {n_dev}"
        self.nfa = CompiledPatternNFA(app_string, n_partitions=n_partitions,
                                      n_slots=n_slots, mesh=self.mesh)
        self.n_partitions = self.nfa.n_partitions
        self.spec = self.nfa.spec
        self.local_range = host_partition_range(self.n_partitions)
        # the engine step + global stats reduction fused into ONE
        # executable (single dispatch per block); state stays in nfa.carry
        # so snapshot/grow keep working through the engine object
        self._step = jit_engine_step(self.spec, self.mesh, axis,
                                     stats=True)

    @property
    def carry(self):
        return self.nfa.carry

    def step_local(self, local_block: Dict[str, np.ndarray]):
        """Feed this host's [P_local, T] block; returns (local_mask,
        local_ts, stats) — the host's own match rows plus the global stats
        from the single cross-host reduction."""
        gblock = global_block(local_block, self.mesh, self.axis)
        self.nfa.carry, (mask, _caps, ts, _enter, _seq), stats = \
            self._step(self.nfa.carry, gblock)
        return local_rows(mask), local_rows(ts), \
            {k: int(v) for k, v in stats.items()}
