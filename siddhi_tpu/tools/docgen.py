"""Documentation generator: built-in + registered extension reference docs.

(reference: modules/siddhi-doc-gen — maven mojos rendering @Extension
annotation metadata to mkdocs markdown.  Here the metadata sources are the
built-in factories themselves — window registry, aggregator table, expression
compiler builtins — plus any ExtensionRegistry entries; output is one
markdown document.)

CLI: ``python -m siddhi_tpu.tools.docgen [out.md]``
"""
from __future__ import annotations

import inspect
from typing import List, Optional


def _first_line(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    return doc.split("\n")[0] if doc else ""


def generate_markdown(extension_registry=None) -> str:
    from ..core import aggregator, window

    lines: List[str] = ["# siddhi_tpu built-in reference", ""]

    lines += ["## Windows (`#window.<name>(...)`)", ""]
    win = [
        ("length(n)", window.LengthWindowProcessor),
        ("lengthBatch(n)", window.LengthBatchWindowProcessor),
        ("time(t)", window.TimeWindowProcessor),
        ("timeBatch(t[, start])", window.TimeBatchWindowProcessor),
        ("timeLength(t, n)", window.TimeLengthWindowProcessor),
        ("externalTime(tsAttr, t)", window.ExternalTimeWindowProcessor),
        ("externalTimeBatch(tsAttr, t[, start])",
         window.ExternalTimeBatchWindowProcessor),
        ("batch()", window.BatchWindowProcessor),
        ("hoping(t, hop) / hopping", window.HopingWindowProcessor),
        ("session(gap[, key])", window.SessionWindowProcessor),
        ("sort(n, attr [, 'asc'|'desc']...)", window.SortWindowProcessor),
        ("frequent(n[, attrs...])", window.FrequentWindowProcessor),
        ("lossyFrequent(support[, error][, attrs...])",
         window.LossyFrequentWindowProcessor),
        ("delay(t)", window.DelayWindowProcessor),
        ("cron(expr)", window.CronWindowProcessor),
    ]
    for sig, cls in win:
        lines.append(f"- `{sig}` — {_first_line(cls)}")
    lines.append("")

    lines += ["## Attribute aggregators", ""]
    for name, cls in sorted(aggregator.AGGREGATORS.items()):
        lines.append(f"- `{name}(...)` — {_first_line(cls)}")
    lines.append("")

    lines += ["## Built-in scalar functions", "",
              "`coalesce, ifThenElse, cast, convert, instanceOf*, UUID, "
              "currentTimeMillis, eventTimestamp, maximum, minimum, default, "
              "createSet, sizeOfSet`, `math:{abs,ceil,floor,sqrt,log,log10,"
              "exp,sin,cos,tan,round,power}`, `str:{concat,length,upper,"
              "lower,trim,reverse,contains}`", ""]

    lines += ["## Incremental aggregation",
              "",
              "`define aggregation A from S select g, avg(x) as a, ... "
              "group by g aggregate [by tsAttr] every sec ... year;` — "
              "queried with `from A [on cond] within <from>, <to> per "
              "'<duration>'` in store queries and joins.", ""]

    # @extension-decorated classes: full metadata render (≙ the reference
    # doc-gen mojos consuming @Extension/@Parameter/@Example annotations)
    from ..utils.extension import EXTENSION_METADATA
    seen = set()
    metas = list(EXTENSION_METADATA.values())
    if extension_registry is not None:
        for _n, impl in sorted(getattr(extension_registry,
                                       "_by_name", {}).items()):
            m = getattr(impl, "__extension_meta__", None)
            if m is not None and m.key not in EXTENSION_METADATA:
                metas.append(m)
    if metas:
        lines += ["## Registered extensions", ""]
        for m in metas:
            if m.key in seen:
                continue
            seen.add(m.key)
            lines.append(f"### `{m.key}`")
            lines.append("")
            if m.description:
                lines.append(m.description)
                lines.append("")
            if m.parameters:
                lines.append("| parameter | type | description |")
                lines.append("|---|---|---|")
                for pname, ptype, pdesc in m.parameters:
                    lines.append(f"| `{pname}` | {ptype} | {pdesc} |")
                lines.append("")
            if m.returns:
                lines.append(f"**Returns:** `{m.returns}`")
                lines.append("")
            for ex in m.examples:
                lines.append(f"```\n{ex}\n```")
                lines.append("")
    if extension_registry is not None:
        plain = [(n, impl) for n, impl in
                 sorted(getattr(extension_registry, "_by_name", {}).items())
                 if getattr(impl, "__extension_meta__", None) is None]
        if plain:
            lines += ["## Extensions without metadata", ""]
            for n, impl in plain:
                lines.append(f"- `{n}` — {_first_line(impl)}")
            lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None):
    import sys
    argv = argv if argv is not None else sys.argv[1:]
    md = generate_markdown()
    if argv:
        with open(argv[0], "w") as f:
            f.write(md)
    else:
        print(md)


if __name__ == "__main__":
    main()
