"""Tooling: doc generation (reference: modules/siddhi-doc-gen)."""
