"""REST microservice: deploy/undeploy SiddhiQL apps over HTTP.

(reference: modules/siddhi-service — MSF4J service exposing
POST /siddhi/artifact/deploy and GET /siddhi/artifact/undeploy/{app},
SiddhiApi.java:31-62, SiddhiApiServiceImpl.java:42.)

Extras beyond the reference surface (operationally useful for a TPU-backed
deployment): list apps, push events into a stream, run store queries, and
snapshot/restore — all JSON over stdlib http.server (zero dependencies).

Observability surface: ``GET /metrics`` serves the Prometheus/
OpenMetrics text exposition over every deployed app's StatisticsManager
plus the process-global kernel profiler and the opt-in device telemetry
(core/statistics.prometheus_text); ``GET /stats`` serves the same data
as JSON.  Flight-recorder endpoints: ``GET /incidents`` lists incident
summaries, ``GET /incidents/{id}/bundle`` returns a full bundle,
``POST /siddhi/apps/{app}/debug/bundle`` snapshots one on demand, and
``GET /siddhi/apps/{app}/trace`` returns the Chrome trace-event JSON
(rt.dump_trace parity).  All scrape-ready on the zero-dependency server.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..core.runtime import SiddhiManager
from ..core.threads import engine_thread_name


class SiddhiService:
    def __init__(self, host: str = "127.0.0.1", port: int = 9090,
                 manager: Optional[SiddhiManager] = None):
        self.manager = manager or SiddhiManager()
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # quiet
                pass

            def _send(self, code: int, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n).decode() if n else ""

            def do_POST(self):
                try:
                    service._post(self)
                except Exception as e:  # noqa: BLE001 — service boundary
                    self._send(500, {"error": str(e)})

            def do_GET(self):
                try:
                    service._get(self)
                except Exception as e:  # noqa: BLE001 — service boundary
                    self._send(500, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=engine_thread_name("siddhi-rest"))
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.manager.shutdown()

    # ------------------------------------------------------------ routes

    def _post(self, h):
        parts = [p for p in h.path.split("/") if p]
        if parts == ["siddhi", "artifact", "deploy"]:
            rt = self.manager.create_siddhi_app_runtime(h._body())
            rt.start()
            return h._send(200, {"status": "deployed", "app": rt.name})
        if len(parts) == 4 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "query":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            events = rt.query(h._body())
            return h._send(200, {"events": [
                {"timestamp": e.timestamp, "data": e.data}
                for e in (events or [])]})
        if len(parts) == 5 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "streams":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            payload = json.loads(h._body())
            events = payload if isinstance(payload, list) else [payload]
            handler = rt.get_input_handler(parts[4])
            for ev in events:
                handler.send(ev["data"] if isinstance(ev, dict) else ev,
                             timestamp=(ev.get("timestamp")
                                        if isinstance(ev, dict) else None))
            return h._send(200, {"status": "sent", "count": len(events)})
        if len(parts) == 4 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "persist":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            rev = rt.persist()
            return h._send(200, {"revision": rev})
        if len(parts) == 5 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "debug" and parts[4] == "bundle":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            from ..core.flight import flight
            fl = flight()
            if not fl.enabled:
                return h._send(409, {"error": "flight recorder disabled "
                                              "(SIDDHI_TPU_FLIGHT=0)"})
            body = h._body()
            opts = json.loads(body) if body else {}
            bundle = fl.emit("on_demand", app=rt.name,
                             detail={"requested_by": "rest",
                                     "note": opts.get("note", "")},
                             runtime=rt)
            return h._send(200, {"id": bundle["id"],
                                 "kind": bundle["kind"]})
        if len(parts) == 5 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "errors" and parts[4] in ("replay", "purge"):
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            if rt.error_store is None:
                return h._send(409, {"error": "no error store configured"})
            body = h._body()
            opts = json.loads(body) if body else {}
            if parts[4] == "replay":
                n = rt.replay_errors(stream_id=opts.get("stream"),
                                     ids=opts.get("ids"))
                rt.flush()
                return h._send(200, {"replayed": n})
            n = rt.error_store.purge(app_name=rt.name, ids=opts.get("ids"))
            rt.resilience_metrics.errors_purged_total.inc(n)
            return h._send(200, {"purged": n})
        h._send(404, {"error": f"no route {h.path}"})

    def _get(self, h):
        parts = [p for p in h.path.split("/") if p]
        if len(parts) == 4 and parts[:3] == ["siddhi", "artifact",
                                             "undeploy"]:
            rt = self.manager.runtimes.pop(parts[3], None)
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[3]}'"})
            rt.shutdown()
            return h._send(200, {"status": "undeployed", "app": parts[3]})
        if parts == ["siddhi", "apps"]:
            return h._send(200, {"apps": sorted(self.manager.runtimes)})
        if parts == ["health"]:
            return h._send(200, self._health_json())
        if parts == ["metrics"]:
            return self._send_metrics(h)
        if parts == ["stats"]:
            return h._send(200, self._stats_json())
        if parts == ["slo"]:
            return h._send(200, self._slo_json())
        if len(parts) == 4 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "errors":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            if rt.error_store is None:
                return h._send(200, {"errors": [], "store": None})
            return h._send(200, {"errors": [
                e.summary() for e in rt.error_store.list(app_name=rt.name)],
                "store": type(rt.error_store).__name__})
        if len(parts) == 4 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "trace":
            # Chrome trace-event JSON (Perfetto-loadable), parity with
            # rt.dump_trace but without touching the filesystem
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            from ..core.tracing import tracer
            return h._send(200, tracer().to_dict())
        if parts == ["incidents"]:
            from ..core.flight import flight
            return h._send(200, {"incidents": flight().incidents()})
        if len(parts) == 3 and parts[0] == "incidents" and \
                parts[2] == "bundle":
            from ..core.flight import flight
            bundle = flight().bundle(parts[1])
            if bundle is None:
                return h._send(404, {"error": f"no bundle '{parts[1]}' "
                                              "(aged out or unknown)"})
            return h._send(200, bundle)
        h._send(404, {"error": f"no route {h.path}"})

    # ------------------------------------------------------------ health

    def _health_json(self) -> dict:
        """Liveness + per-sink circuit readiness: ``status`` stays "up"
        while the process serves; ``ready`` drops to False when any
        deployed sink's circuit is OPEN (fast-failing).  Overload is
        surfaced here too: ``status`` becomes "degraded" while any
        @Async buffer sits above its high watermark or a dispatch-storm
        watchdog incident (WD0xx) is on record."""
        from ..core.ledger import ledger
        led = ledger()
        apps, ready, degraded = {}, True, False
        for name, rt in self.manager.runtimes.items():
            sinks = {}
            for s in rt.sinks:
                breaker = getattr(s, "breaker", None)
                if breaker is None:
                    continue
                state = breaker.state
                sinks[s.stream_def.id] = {"circuit": state,
                                          "ready": state != "open"}
                if state == "open":
                    ready = False
            doc = {"started": rt._started, "sinks": sinks,
                   "errors_stored": (rt.error_store.count(rt.name)
                                     if rt.error_store is not None
                                     else 0)}
            saturated = [sid for sid, j in rt.junctions.items()
                         if j.saturated()]
            if saturated:
                doc["saturated_streams"] = saturated
                degraded = True
            wd = getattr(rt, "watchdog", None)
            if wd is not None and wd.incidents:
                doc["incidents"] = list(wd.incidents)
                degraded = True
            if led.slo_breached(name):
                # sustained @app:slo breach (core/ledger.py): the SLO001
                # bundle is already on the incident bus; health turns
                # degraded until the burn rate recovers
                doc["slo_breached"] = True
                degraded = True
            apps[name] = doc
        return {"status": "degraded" if degraded else "up",
                "ready": ready, "apps": apps}

    # ------------------------------------------------------------ metrics

    def _send_metrics(self, h):
        from ..core.profiling import profiler
        from ..core.statistics import prometheus_text
        managers = [rt.app_ctx.statistics_manager
                    for rt in self.manager.runtimes.values()
                    if rt.app_ctx.statistics_manager is not None]
        resilience = [rt.resilience_metrics
                      for rt in self.manager.runtimes.values()
                      if getattr(rt, "resilience_metrics", None) is not None]
        ingest = [rt.ingest_metrics
                  for rt in self.manager.runtimes.values()
                  if getattr(rt, "ingest_metrics", None) is not None]
        telemetry = [rt.device_telemetry
                     for rt in self.manager.runtimes.values()
                     if getattr(rt, "device_telemetry", None) is not None]
        from ..core.overload import fair_share
        from ..plan.xtenant import tenant_packer
        body = prometheus_text(managers, profiler(), resilience,
                               ingest, telemetry,
                               tenants=[fair_share(), tenant_packer()]
                               ).encode()
        h.send_response(200)
        h.send_header("Content-Type",
                      "text/plain; version=0.0.4; charset=utf-8")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _stats_json(self) -> dict:
        from ..core.ledger import ledger
        from ..core.profiling import profiler, rim_stats
        apps = {}
        for name, rt in self.manager.runtimes.items():
            if rt.app_ctx.statistics_manager is None:
                continue
            doc = rt.app_ctx.statistics_manager.snapshot()
            # compile-time analyzer findings ride the same surface: an
            # operator scraping /stats sees "this app's pattern has no
            # within bound" next to the runtime counters it explains
            if rt.analysis is not None:
                doc["analysis"] = rt.analysis.as_dicts()
                # plan-level report: automaton shapes, pruned-state
                # counts, predicted HBM/FLOP cost (analysis/plan_verify)
                plan = getattr(rt.analysis, "plan", None)
                if plan is not None:
                    doc["plan"] = plan.as_dict()
                # numeric-safety report: NS0xx value-range verdicts
                # grounded on the compiled plan (analysis/ranges)
                numeric = getattr(rt.analysis, "numeric", None)
                if numeric is not None:
                    doc["numeric"] = numeric.as_dict()
            # persistent-state schema report: which declarations govern
            # each snapshot element, and the app-level layout digest an
            # operator can diff across deploys (analysis/state_schema)
            schema = getattr(rt, "state_schema", None)
            if schema is not None:
                doc["state_schema"] = schema.as_dict()
            # per-query selection routing: whether the having / order-by
            # / limit tail runs in the device egress kernel or on the
            # host QuerySelector (with the blocking reason) — the live
            # counterpart of the T1 artifact's selection section
            selection = {
                qname: route
                for qname, qrt in getattr(rt, "query_runtimes",
                                          {}).items()
                for route in [getattr(qrt, "selection_route", None)]
                if route is not None}
            if selection:
                doc["selection"] = selection
            # live numeric sentinels (SIDDHI_TPU_NUMGUARD): overflow /
            # non-finite trip counters the static verdicts predicted
            from ..core.numguard import numeric_sentinels
            guard = numeric_sentinels(name, create=False)
            if guard is not None:
                doc["numguard"] = guard.snapshot()
            doc["ledger"] = ledger().snapshot(app=name)
            apps[name] = doc
        # process-global surfaces, mirrored from rt.statistics so the
        # three snapshot surfaces (/metrics, rt.statistics, here) agree
        from ..plan.shapes import shape_registry
        return {"apps": apps, "kernels": profiler().snapshot(),
                "rim": rim_stats().snapshot(),
                "shapes": shape_registry().snapshot()}

    def _slo_json(self) -> dict:
        """Per-app SLO posture + stream lag watermarks (the SLO engine's
        dedicated read surface; /metrics carries the same numbers as
        gauges)."""
        from ..core.ledger import ledger
        led = ledger()
        snap = led.snapshot()
        apps = {}
        for name, rt in self.manager.runtimes.items():
            entry = dict(snap["apps"].get(name, {}))
            cfg = getattr(rt, "slo_config", None)
            if cfg is not None and "slo" not in entry:
                entry["slo"] = {"config": cfg.as_dict()}
            apps[name] = entry
        return {"enabled": snap["enabled"], "apps": apps,
                "stage_seconds": snap["stage_seconds"]}
