"""REST microservice: deploy/undeploy SiddhiQL apps over HTTP.

(reference: modules/siddhi-service — MSF4J service exposing
POST /siddhi/artifact/deploy and GET /siddhi/artifact/undeploy/{app},
SiddhiApi.java:31-62, SiddhiApiServiceImpl.java:42.)

Extras beyond the reference surface (operationally useful for a TPU-backed
deployment): list apps, push events into a stream, run store queries, and
snapshot/restore — all JSON over stdlib http.server (zero dependencies).

Observability surface (this PR): ``GET /metrics`` serves the
Prometheus/OpenMetrics text exposition over every deployed app's
StatisticsManager plus the process-global kernel profiler
(core/statistics.prometheus_text); ``GET /stats`` serves the same data
as JSON.  Both are scrape-ready on the zero-dependency server.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..core.runtime import SiddhiManager


class SiddhiService:
    def __init__(self, host: str = "127.0.0.1", port: int = 9090,
                 manager: Optional[SiddhiManager] = None):
        self.manager = manager or SiddhiManager()
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # quiet
                pass

            def _send(self, code: int, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n).decode() if n else ""

            def do_POST(self):
                try:
                    service._post(self)
                except Exception as e:  # noqa: BLE001 — service boundary
                    self._send(500, {"error": str(e)})

            def do_GET(self):
                try:
                    service._get(self)
                except Exception as e:  # noqa: BLE001 — service boundary
                    self._send(500, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.manager.shutdown()

    # ------------------------------------------------------------ routes

    def _post(self, h):
        parts = [p for p in h.path.split("/") if p]
        if parts == ["siddhi", "artifact", "deploy"]:
            rt = self.manager.create_siddhi_app_runtime(h._body())
            rt.start()
            return h._send(200, {"status": "deployed", "app": rt.name})
        if len(parts) == 4 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "query":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            events = rt.query(h._body())
            return h._send(200, {"events": [
                {"timestamp": e.timestamp, "data": e.data}
                for e in (events or [])]})
        if len(parts) == 5 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "streams":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            payload = json.loads(h._body())
            events = payload if isinstance(payload, list) else [payload]
            handler = rt.get_input_handler(parts[4])
            for ev in events:
                handler.send(ev["data"] if isinstance(ev, dict) else ev,
                             timestamp=(ev.get("timestamp")
                                        if isinstance(ev, dict) else None))
            return h._send(200, {"status": "sent", "count": len(events)})
        if len(parts) == 4 and parts[:2] == ["siddhi", "apps"] and \
                parts[3] == "persist":
            rt = self.manager.get_siddhi_app_runtime(parts[2])
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[2]}'"})
            rev = rt.persist()
            return h._send(200, {"revision": rev})
        h._send(404, {"error": f"no route {h.path}"})

    def _get(self, h):
        parts = [p for p in h.path.split("/") if p]
        if len(parts) == 4 and parts[:3] == ["siddhi", "artifact",
                                             "undeploy"]:
            rt = self.manager.runtimes.pop(parts[3], None)
            if rt is None:
                return h._send(404, {"error": f"no app '{parts[3]}'"})
            rt.shutdown()
            return h._send(200, {"status": "undeployed", "app": parts[3]})
        if parts == ["siddhi", "apps"]:
            return h._send(200, {"apps": sorted(self.manager.runtimes)})
        if parts == ["health"]:
            return h._send(200, {"status": "up"})
        if parts == ["metrics"]:
            return self._send_metrics(h)
        if parts == ["stats"]:
            return h._send(200, self._stats_json())
        h._send(404, {"error": f"no route {h.path}"})

    # ------------------------------------------------------------ metrics

    def _send_metrics(self, h):
        from ..core.profiling import profiler
        from ..core.statistics import prometheus_text
        managers = [rt.app_ctx.statistics_manager
                    for rt in self.manager.runtimes.values()
                    if rt.app_ctx.statistics_manager is not None]
        body = prometheus_text(managers, profiler()).encode()
        h.send_response(200)
        h.send_header("Content-Type",
                      "text/plain; version=0.0.4; charset=utf-8")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _stats_json(self) -> dict:
        from ..core.profiling import profiler
        apps = {}
        for name, rt in self.manager.runtimes.items():
            if rt.app_ctx.statistics_manager is None:
                continue
            doc = rt.app_ctx.statistics_manager.snapshot()
            # compile-time analyzer findings ride the same surface: an
            # operator scraping /stats sees "this app's pattern has no
            # within bound" next to the runtime counters it explains
            if rt.analysis is not None:
                doc["analysis"] = rt.analysis.as_dicts()
                # plan-level report: automaton shapes, pruned-state
                # counts, predicted HBM/FLOP cost (analysis/plan_verify)
                plan = getattr(rt.analysis, "plan", None)
                if plan is not None:
                    doc["plan"] = plan.as_dict()
            apps[name] = doc
        return {"apps": apps, "kernels": profiler().snapshot()}
