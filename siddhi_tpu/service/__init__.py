"""REST service layer (reference: modules/siddhi-service)."""
from .rest import SiddhiService

__all__ = ["SiddhiService"]
