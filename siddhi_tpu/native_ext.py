"""ctypes bindings for the native host data path (native/eventpack.cpp).

Everything here has a pure-numpy fallback: the package works without the
compiled .so (`make -C native` builds it).  The native path exists because
per-event Python loops are the one host-side bottleneck between sources and
the [P, T] device lanes — the same role the LMAX Disruptor ring plays in the
reference's @Async junctions (stream/StreamJunction.java:280-316).
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    path = os.path.join(os.path.dirname(__file__), "_native.so")
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.assign_rows.restype = ctypes.c_int64
    lib.assign_rows.argtypes = [i32p, ctypes.c_int64, ctypes.c_int32,
                                i32p, i32p]
    lib.ring_create.restype = ctypes.c_void_p
    lib.ring_create.argtypes = [ctypes.c_int64, ctypes.c_int32]
    lib.ring_destroy.argtypes = [ctypes.c_void_p]
    lib.ring_push.restype = ctypes.c_int64
    lib.ring_push.argtypes = [ctypes.c_void_p, f64p, i64p, i32p, i32p,
                              ctypes.c_int64]
    lib.ring_drain.restype = ctypes.c_int64
    lib.ring_drain.argtypes = [ctypes.c_void_p, f64p, i64p, i32p, i32p,
                               ctypes.c_int64]
    lib.ring_size.restype = ctypes.c_int64
    lib.ring_size.argtypes = [ctypes.c_void_p]
    lib.ring_dropped.restype = ctypes.c_int64
    lib.ring_dropped.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def have_native() -> bool:
    return _load() is not None


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def assign_rows(pids: np.ndarray,
                n_partitions: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Per-partition running row index for [P, T] lane packing.

    Returns (rows [n] int32, counts [P] int32, T)."""
    pids = np.ascontiguousarray(pids, np.int32)
    n = len(pids)
    if n and (pids.min() < 0 or pids.max() >= n_partitions):
        # the native path would heap-write out of bounds and the numpy
        # fallback would silently wrap negatives — reject both up front
        raise ValueError(
            f"partition ids must be in [0, {n_partitions}); got range "
            f"[{int(pids.min())}, {int(pids.max())}]")
    rows = np.empty(n, np.int32)
    counts = np.empty(n_partitions, np.int32)
    lib = _load()
    if lib is not None:
        t = lib.assign_rows(_i32p(pids), n, n_partitions, _i32p(rows),
                            _i32p(counts))
        return rows, counts, max(int(t), 1)
    counts[:] = 0
    for i in range(n):
        p = pids[i]
        rows[i] = counts[p]
        counts[p] += 1
    return rows, counts, max(int(counts.max()) if n else 1, 1)


class ColumnarRing:
    """Multi-producer numeric event ring (native when built, else a locked
    numpy deque).  Rows: (values[n_cols] f64, ts i64, stream i32, part i32)."""

    def __init__(self, capacity: int, n_cols: int):
        self.capacity = capacity
        self.n_cols = n_cols
        lib = _load()
        self._lib = lib
        if lib is not None:
            self._h = lib.ring_create(capacity, n_cols)
            if not self._h:
                raise MemoryError("ring_create failed")
        else:
            import threading
            self._h = None
            self._lock = threading.Lock()
            self._items = []
            self._dropped = 0

    def push(self, values: np.ndarray, ts: np.ndarray,
             stream: np.ndarray, partition: np.ndarray) -> int:
        values = np.ascontiguousarray(values, np.float64).reshape(
            -1, self.n_cols)
        m = len(values)
        ts = np.ascontiguousarray(ts, np.int64)
        stream = np.ascontiguousarray(stream, np.int32)
        partition = np.ascontiguousarray(partition, np.int32)
        if self._lib is not None:
            return int(self._lib.ring_push(
                self._h,
                values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                _i32p(stream), _i32p(partition), m))
        with self._lock:
            space = self.capacity - sum(len(v) for v, *_ in self._items)
            take = min(m, max(space, 0))
            if take:
                self._items.append((values[:take].copy(), ts[:take].copy(),
                                    stream[:take].copy(),
                                    partition[:take].copy()))
            self._dropped += m - take
            return take

    def drain(self, max_rows: int):
        """→ (values [m, n_cols], ts [m], stream [m], partition [m])."""
        if self._lib is not None:
            out_v = np.empty((max_rows, self.n_cols), np.float64)
            out_t = np.empty(max_rows, np.int64)
            out_s = np.empty(max_rows, np.int32)
            out_p = np.empty(max_rows, np.int32)
            m = int(self._lib.ring_drain(
                self._h,
                out_v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                out_t.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                _i32p(out_s), _i32p(out_p), max_rows))
            return out_v[:m], out_t[:m], out_s[:m], out_p[:m]
        with self._lock:
            if not self._items:
                z = np.empty((0, self.n_cols), np.float64)
                return (z, np.empty(0, np.int64), np.empty(0, np.int32),
                        np.empty(0, np.int32))
            vs, tss, ss, ps = zip(*self._items)
            self._items.clear()
            v = np.concatenate(vs)
            t = np.concatenate(tss)
            s = np.concatenate(ss)
            p = np.concatenate(ps)
            if len(v) > max_rows:
                self._items.append((v[max_rows:], t[max_rows:],
                                    s[max_rows:], p[max_rows:]))
            return (v[:max_rows], t[:max_rows], s[:max_rows], p[:max_rows])

    def __len__(self):
        if self._lib is not None:
            return int(self._lib.ring_size(self._h))
        with self._lock:
            return sum(len(v) for v, *_ in self._items)

    @property
    def dropped(self) -> int:
        if self._lib is not None:
            return int(self._lib.ring_dropped(self._h))
        return self._dropped

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._h:
            self._lib.ring_destroy(self._h)
            self._h = None
