"""Concrete record-table stores (≙ the reference's external siddhi-store-*
extension repos; the SPI they implement lives in core/record_table.py)."""
