"""SQLite-backed queryable record table.

The proof-of-the-SPI store (reference analogue: the siddhi-store-rdbms
extension implementing table/record/AbstractQueryableRecordTable.java):
compiled conditions and selections arrive as store-neutral RecordExpr trees
(core/record_table.py) and are rendered here into parameterised SQL — the
store executes probes natively instead of shipping rows to the engine.

Usage::

    @Store(type='sqlite', database=':memory:', table='StockTable')
    define table StockTable (symbol string, price float, volume long);

The last executed SQL statements are kept in `self.sql_log` so tests (and
curious users) can verify pushdown actually happened.
"""
from __future__ import annotations

import sqlite3
from typing import Any, Dict, Iterable, List, Optional

from ..core.record_table import (AbstractQueryableRecordTable, Agg, Arith,
                                 BoolAnd, BoolNot, BoolOr, Cmp, Col, Const,
                                 NullCheck, Param, RecordExpr,
                                 RecordSelection, record_expr_children)
from ..query_api.definition import AttrType
from ..utils.errors import SiddhiAppCreationError
from ..utils.extension import extension

_SQL_TYPE = {
    AttrType.INT: "INTEGER", AttrType.LONG: "INTEGER",
    AttrType.FLOAT: "REAL", AttrType.DOUBLE: "REAL",
    AttrType.BOOL: "INTEGER", AttrType.STRING: "TEXT",
}

_CMP_SQL = {"==": "=", "!=": "<>", "<": "<", "<=": "<=", ">": ">", ">=": ">="}


def _q(ident: str) -> str:
    """Quote an SQL identifier (embedded quotes doubled)."""
    return '"' + ident.replace('"', '""') + '"'


def _render(e: Optional[RecordExpr]) -> str:
    """RecordExpr → SQL with :name parameter placeholders."""
    if e is None:
        return "1"
    if isinstance(e, Col):
        return _q(e.name)
    if isinstance(e, Const):
        v = e.value
        if isinstance(v, bool):
            return "1" if v else "0"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        return repr(v)
    if isinstance(e, Param):
        return f":{e.name}"
    if isinstance(e, Cmp):
        return f"({_render(e.left)} {_CMP_SQL[e.op]} {_render(e.right)})"
    if isinstance(e, BoolAnd):
        return f"({_render(e.left)} AND {_render(e.right)})"
    if isinstance(e, BoolOr):
        return f"({_render(e.left)} OR {_render(e.right)})"
    if isinstance(e, BoolNot):
        return f"(NOT {_render(e.expr)})"
    if isinstance(e, NullCheck):
        return f"({_render(e.expr)} IS NULL)"
    if isinstance(e, Arith):
        if e.op == "+" and e.type == "str":
            # engine `+` on strings is concatenation; SQL `+` coerces to 0
            return f"({_render(e.left)} || {_render(e.right)})"
        return f"({_render(e.left)} {e.op} {_render(e.right)})"
    if isinstance(e, Agg):
        arg = "*" if e.arg is None else _render(e.arg)
        return f"{e.kind.upper()}({arg})"
    raise SiddhiAppCreationError(f"sqlite store: unrenderable {type(e)}")


def _clean_params(params: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (int(v) if isinstance(v, bool) else v)
            for k, v in params.items()}


@extension(namespace="store", name="sqlite",
           description="SQLite-backed queryable record table with full "
                       "condition and selection pushdown",
           parameters=[("database", "string",
                        "sqlite database path (default ':memory:')"),
                       ("table", "string",
                        "backing table name (default: the definition id)")])
class SQLiteStore(AbstractQueryableRecordTable):

    def init(self, definition, store_annotation) -> None:
        db = ":memory:"
        table = definition.id
        if store_annotation is not None:
            db = store_annotation.get("database", db) or db
            table = store_annotation.get("table", table) or table
        self._table = table
        self._bools = [a.name for a in definition.attributes
                       if a.type == AttrType.BOOL]
        self.sql_log: List[str] = []
        from ..query_api import find_annotation
        pk_ann = find_annotation(definition.annotations, "primarykey")
        self._pk: List[str] = pk_ann.positional() if pk_ann else []
        cols = []
        for a in definition.attributes:
            t = _SQL_TYPE.get(a.type)
            if t is None:
                raise SiddhiAppCreationError(
                    f"sqlite store: unsupported attribute type {a.type} "
                    f"for '{a.name}'")
            cols.append(f'{_q(a.name)} {t}')
        if self._pk:
            cols.append(f'PRIMARY KEY ({", ".join(_q(k) for k in self._pk)})')
        # engine probes may come from any junction/worker thread; all calls
        # are serialized by AbstractRecordTable.lock
        self._conn = sqlite3.connect(db, check_same_thread=False)
        self._conn.execute(
            f'CREATE TABLE IF NOT EXISTS {_q(table)} ({", ".join(cols)})')
        self._conn.commit()
        # a pre-existing table (CREATE IF NOT EXISTS no-op) may lack the
        # declared PK — ON CONFLICT(pk) would then raise OperationalError
        # at runtime, so verify the REAL schema before enabling the native
        # upsert path
        actual_pk = [r[1] for r in sorted(
            (r for r in self._conn.execute(
                f'PRAGMA table_info({_q(table)})') if r[5] > 0),
            key=lambda r: r[5])]
        self._pk_native = bool(self._pk) and actual_pk == list(self._pk)

    def validate_expr(self, e) -> None:
        """Refuse IR whose SQLite semantics diverge from the engine's
        (callers with a host path fall back; others surface the error)."""
        if e is None:
            return
        if isinstance(e, Arith) and e.op == "%" and e.type == "float":
            raise SiddhiAppCreationError(
                "sqlite store: '%' on REAL operands truncates to INTEGER "
                "in SQLite (engine fmod semantics diverge)")
        import math
        if isinstance(e, Const) and isinstance(e.value, float) and \
                not math.isfinite(e.value):
            # repr(inf)/repr(nan) render as bare `inf`/`nan` — invalid
            # SQLite syntax; refuse at compile time (clean host fallback)
            # instead of an OperationalError at probe time
            raise SiddhiAppCreationError(
                "sqlite store: non-finite float constants are not "
                "renderable as SQLite literals")
        for c in record_expr_children(e):
            self.validate_expr(c)

    def _exec(self, sql: str, params=None):
        self.sql_log.append(sql)
        return self._conn.execute(sql, _clean_params(params or {}))

    def _row_dict(self, names, row) -> Dict[str, Any]:
        d = dict(zip(names, row))
        for b in self._bools:
            if b in d and d[b] is not None:
                d[b] = bool(d[b])
        return d

    # ------------------------------------------------------------- SPI

    def add(self, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        cols = self.names
        sql = (f'INSERT INTO {_q(self._table)} '
               f'({", ".join(_q(c) for c in cols)}) '
               f'VALUES ({", ".join(":" + c for c in cols)})')
        self.sql_log.append(sql)
        self._conn.executemany(
            sql, [_clean_params({c: r.get(c) for c in cols})
                  for r in records])
        self._conn.commit()

    def find_records(self, condition, params) -> Iterable[Dict[str, Any]]:
        cur = self._exec(
            f'SELECT {", ".join(_q(c) for c in self.names)} '
            f'FROM {_q(self._table)} WHERE {_render(condition)}', params)
        for row in cur.fetchall():
            yield self._row_dict(self.names, row)

    def update_records(self, condition, param_rows, assignments) -> None:
        sets = ", ".join(f'{_q(col)} = {_render(e)}'
                         for col, e in assignments)
        sql = (f'UPDATE {_q(self._table)} SET {sets} '
               f'WHERE {_render(condition)}')
        for pr in param_rows:
            self._exec(sql, pr)
        self._conn.commit()

    def delete_records(self, condition, param_rows) -> None:
        sql = f'DELETE FROM {_q(self._table)} WHERE {_render(condition)}'
        for pr in (param_rows or [{}]):
            self._exec(sql, pr)
        self._conn.commit()

    def _pk_equality(self, e) -> Optional[Dict[str, Any]]:
        """When the condition is exactly an AND-chain of equality tests
        covering the declared primary key, return {pk col: operand node}
        (Param or Const); else None.  Shape alone is NOT sufficient for
        the native upsert — the caller must also check per row that each
        compared operand VALUE equals the value being inserted into that
        PK column, otherwise `on T.pk == <something else>` would match a
        different row than ON CONFLICT(pk) does."""
        ops: Dict[str, Any] = {}

        def walk(x) -> bool:
            if isinstance(x, BoolAnd):
                return walk(x.left) and walk(x.right)
            if isinstance(x, Cmp) and x.op == "==":
                side = (x.left if isinstance(x.left, Col) else
                        x.right if isinstance(x.right, Col) else None)
                other = x.right if side is x.left else x.left
                if side is not None and isinstance(other, (Param, Const)):
                    ops[side.name] = other
                    return True
            return False
        if e is not None and walk(e) and set(ops) == set(self._pk):
            return ops
        return None

    def upsert_records(self, condition, param_rows, assignments,
                       add_records) -> None:
        """Native atomic upsert via INSERT ... ON CONFLICT when a primary
        key is declared, the match condition is PK equality, AND (per row)
        the compared values equal the inserted PK values — only then do
        engine find-then-update semantics coincide with ON CONFLICT(pk).
        Closes the probe→write race of the SPI default against external
        writers on the same database; non-coinciding rows take the SPI
        default path."""
        ops = self._pk_equality(condition) if self._pk_native else None
        if ops is None:
            super().upsert_records(condition, param_rows, assignments,
                                   add_records)
            return
        cols = self.names
        sets = ", ".join(f'{_q(c)} = {_render(e)}' for c, e in assignments)
        sql = (f'INSERT INTO {_q(self._table)} '
               f'({", ".join(_q(c) for c in cols)}) '
               f'VALUES ({", ".join(":__ins_" + c for c in cols)}) '
               f'ON CONFLICT({", ".join(_q(k) for k in self._pk)}) '
               f'DO UPDATE SET {sets}')
        logged = False
        for pr, rec in zip(param_rows, add_records):
            cmp_vals = {k: (pr.get(op.name) if isinstance(op, Param)
                            else op.value) for k, op in ops.items()}
            if any(cmp_vals[k] != rec.get(k) for k in self._pk):
                # condition matches a row other than the one being
                # inserted — ON CONFLICT semantics diverge, use the
                # find-then-write default for this row
                super().upsert_records(condition, [pr], assignments, [rec])
                continue
            if not logged:
                self.sql_log.append(sql)
                logged = True
            self._conn.execute(sql, _clean_params(
                {**pr, **{"__ins_" + c: rec.get(c) for c in cols}}))
        self._conn.commit()

    def contains_records(self, condition, params) -> bool:
        cur = self._exec(
            f'SELECT EXISTS(SELECT 1 FROM {_q(self._table)} '
            f'WHERE {_render(condition)})', params)
        return bool(cur.fetchone()[0])

    # --------------------------------------------------- selection pushdown

    def query_records(self, condition, params,
                      selection: RecordSelection) -> Iterable[Dict[str, Any]]:
        names = [n for n, _ in selection.select]
        cols = ", ".join(f'{_render(e)} AS {_q(n)}'
                         for n, e in selection.select)
        sql = (f'SELECT {cols} FROM {_q(self._table)} '
               f'WHERE {_render(condition)}')
        if selection.group_by:
            sql += " GROUP BY " + ", ".join(
                _q(g) for g in selection.group_by)
        if selection.having is not None:
            sql += f" HAVING {_render(selection.having)}"
        if selection.order_by:
            sql += " ORDER BY " + ", ".join(
                f'{_q(a)} {"ASC" if asc else "DESC"}'
                for a, asc in selection.order_by)
        if selection.limit is not None or selection.offset is not None:
            sql += f" LIMIT {selection.limit if selection.limit is not None else -1}"
            if selection.offset is not None:
                sql += f" OFFSET {selection.offset}"
        cur = self._exec(sql, params)
        # outputs that are plain bool-column passthroughs keep host parity
        # (sqlite stores BOOL as 0/1)
        bool_outs = [n for n, e in selection.select
                     if isinstance(e, Col) and e.name in self._bools]
        for row in cur.fetchall():
            d = dict(zip(names, row))
            for b in bool_outs:
                if d[b] is not None:
                    d[b] = bool(d[b])
            yield d


# ===================================================================== errors

class SqliteErrorStore:
    """SQLite-backed ErrorStore (core/resilience.py): failed events
    survive a process restart — pair it with a FileSystemPersistenceStore
    for a fully durable recover-and-replay loop.  Events are pickled
    (timestamp, data-row) pairs; listing/purging filter server-side."""

    _SCHEMA = """CREATE TABLE IF NOT EXISTS siddhi_error_store (
        id INTEGER PRIMARY KEY AUTOINCREMENT,
        app_name TEXT NOT NULL,
        stream_id TEXT NOT NULL,
        origin TEXT NOT NULL,
        error TEXT NOT NULL,
        timestamp_ms INTEGER NOT NULL,
        attempts INTEGER NOT NULL,
        events BLOB NOT NULL)"""

    def __init__(self, database: str = ":memory:"):
        import threading
        self.database = database
        self._conn = sqlite3.connect(database, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute(self._SCHEMA)
            self._conn.commit()

    def store(self, entry) -> int:
        from ..core.resilience import pickle_events
        with self._lock:
            cur = self._conn.execute(
                "INSERT INTO siddhi_error_store (app_name, stream_id, "
                "origin, error, timestamp_ms, attempts, events) "
                "VALUES (?, ?, ?, ?, ?, ?, ?)",
                (entry.app_name, entry.stream_id, entry.origin, entry.error,
                 entry.timestamp_ms, entry.attempts,
                 pickle_events(entry.events)))
            self._conn.commit()
            entry.id = cur.lastrowid
            return entry.id

    def list(self, app_name=None, stream_id=None):
        from ..core.resilience import ErrorEntry, unpickle_events
        sql = ("SELECT id, app_name, stream_id, origin, error, "
               "timestamp_ms, attempts, events FROM siddhi_error_store")
        conds, params = [], []
        if app_name is not None:
            conds.append("app_name = ?")
            params.append(app_name)
        if stream_id is not None:
            conds.append("stream_id = ?")
            params.append(stream_id)
        if conds:
            sql += " WHERE " + " AND ".join(conds)
        sql += " ORDER BY id"
        with self._lock:
            rows = self._conn.execute(sql, params).fetchall()
        return [ErrorEntry(id=r[0], app_name=r[1], stream_id=r[2],
                           origin=r[3], error=r[4], timestamp_ms=r[5],
                           attempts=r[6], events=unpickle_events(r[7]))
                for r in rows]

    def purge(self, app_name=None, ids=None) -> int:
        sql = "DELETE FROM siddhi_error_store"
        conds, params = [], []
        if app_name is not None:
            conds.append("app_name = ?")
            params.append(app_name)
        if ids is not None:
            conds.append("id IN (%s)" % ",".join("?" * len(list(ids))))
            params.extend(ids)
        if conds:
            sql += " WHERE " + " AND ".join(conds)
        with self._lock:
            cur = self._conn.execute(sql, params)
            self._conn.commit()
            return cur.rowcount

    def count(self, app_name=None) -> int:
        return len(self.list(app_name))

    def close(self):
        with self._lock:
            self._conn.close()
