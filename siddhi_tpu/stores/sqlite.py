"""SQLite-backed queryable record table.

The proof-of-the-SPI store (reference analogue: the siddhi-store-rdbms
extension implementing table/record/AbstractQueryableRecordTable.java):
compiled conditions and selections arrive as store-neutral RecordExpr trees
(core/record_table.py) and are rendered here into parameterised SQL — the
store executes probes natively instead of shipping rows to the engine.

Usage::

    @Store(type='sqlite', database=':memory:', table='StockTable')
    define table StockTable (symbol string, price float, volume long);

The last executed SQL statements are kept in `self.sql_log` so tests (and
curious users) can verify pushdown actually happened.
"""
from __future__ import annotations

import sqlite3
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core.record_table import (AbstractQueryableRecordTable, Agg, Arith,
                                 BoolAnd, BoolNot, BoolOr, Cmp, Col, Const,
                                 NullCheck, Param, RecordExpr,
                                 RecordSelection, record_expr_children)
from ..query_api.definition import AttrType
from ..utils.errors import SiddhiAppCreationError
from ..utils.extension import extension

_SQL_TYPE = {
    AttrType.INT: "INTEGER", AttrType.LONG: "INTEGER",
    AttrType.FLOAT: "REAL", AttrType.DOUBLE: "REAL",
    AttrType.BOOL: "INTEGER", AttrType.STRING: "TEXT",
}

_CMP_SQL = {"==": "=", "!=": "<>", "<": "<", "<=": "<=", ">": ">", ">=": ">="}


def _q(ident: str) -> str:
    """Quote an SQL identifier (embedded quotes doubled)."""
    return '"' + ident.replace('"', '""') + '"'


def _render(e: Optional[RecordExpr]) -> str:
    """RecordExpr → SQL with :name parameter placeholders."""
    if e is None:
        return "1"
    if isinstance(e, Col):
        return _q(e.name)
    if isinstance(e, Const):
        v = e.value
        if isinstance(v, bool):
            return "1" if v else "0"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        return repr(v)
    if isinstance(e, Param):
        return f":{e.name}"
    if isinstance(e, Cmp):
        return f"({_render(e.left)} {_CMP_SQL[e.op]} {_render(e.right)})"
    if isinstance(e, BoolAnd):
        return f"({_render(e.left)} AND {_render(e.right)})"
    if isinstance(e, BoolOr):
        return f"({_render(e.left)} OR {_render(e.right)})"
    if isinstance(e, BoolNot):
        return f"(NOT {_render(e.expr)})"
    if isinstance(e, NullCheck):
        return f"({_render(e.expr)} IS NULL)"
    if isinstance(e, Arith):
        if e.op == "+" and e.type == "str":
            # engine `+` on strings is concatenation; SQL `+` coerces to 0
            return f"({_render(e.left)} || {_render(e.right)})"
        return f"({_render(e.left)} {e.op} {_render(e.right)})"
    if isinstance(e, Agg):
        arg = "*" if e.arg is None else _render(e.arg)
        return f"{e.kind.upper()}({arg})"
    raise SiddhiAppCreationError(f"sqlite store: unrenderable {type(e)}")


def _clean_params(params: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (int(v) if isinstance(v, bool) else v)
            for k, v in params.items()}


@extension(namespace="store", name="sqlite",
           description="SQLite-backed queryable record table with full "
                       "condition and selection pushdown",
           parameters=[("database", "string",
                        "sqlite database path (default ':memory:')"),
                       ("table", "string",
                        "backing table name (default: the definition id)")])
class SQLiteStore(AbstractQueryableRecordTable):

    def init(self, definition, store_annotation) -> None:
        db = ":memory:"
        table = definition.id
        if store_annotation is not None:
            db = store_annotation.get("database", db) or db
            table = store_annotation.get("table", table) or table
        self._table = table
        self._bools = [a.name for a in definition.attributes
                       if a.type == AttrType.BOOL]
        self.sql_log: List[str] = []
        cols = []
        for a in definition.attributes:
            t = _SQL_TYPE.get(a.type)
            if t is None:
                raise SiddhiAppCreationError(
                    f"sqlite store: unsupported attribute type {a.type} "
                    f"for '{a.name}'")
            cols.append(f'{_q(a.name)} {t}')
        # engine probes may come from any junction/worker thread; all calls
        # are serialized by AbstractRecordTable.lock
        self._conn = sqlite3.connect(db, check_same_thread=False)
        self._conn.execute(
            f'CREATE TABLE IF NOT EXISTS {_q(table)} ({", ".join(cols)})')
        self._conn.commit()

    def validate_expr(self, e) -> None:
        """Refuse IR whose SQLite semantics diverge from the engine's
        (callers with a host path fall back; others surface the error)."""
        if e is None:
            return
        if isinstance(e, Arith) and e.op == "%" and e.type == "float":
            raise SiddhiAppCreationError(
                "sqlite store: '%' on REAL operands truncates to INTEGER "
                "in SQLite (engine fmod semantics diverge)")
        for c in record_expr_children(e):
            self.validate_expr(c)

    def _exec(self, sql: str, params=None):
        self.sql_log.append(sql)
        return self._conn.execute(sql, _clean_params(params or {}))

    def _row_dict(self, names, row) -> Dict[str, Any]:
        d = dict(zip(names, row))
        for b in self._bools:
            if b in d and d[b] is not None:
                d[b] = bool(d[b])
        return d

    # ------------------------------------------------------------- SPI

    def add(self, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        cols = self.names
        sql = (f'INSERT INTO {_q(self._table)} '
               f'({", ".join(_q(c) for c in cols)}) '
               f'VALUES ({", ".join(":" + c for c in cols)})')
        self.sql_log.append(sql)
        self._conn.executemany(
            sql, [_clean_params({c: r.get(c) for c in cols})
                  for r in records])
        self._conn.commit()

    def find_records(self, condition, params) -> Iterable[Dict[str, Any]]:
        cur = self._exec(
            f'SELECT {", ".join(_q(c) for c in self.names)} '
            f'FROM {_q(self._table)} WHERE {_render(condition)}', params)
        for row in cur.fetchall():
            yield self._row_dict(self.names, row)

    def update_records(self, condition, param_rows, assignments) -> None:
        sets = ", ".join(f'{_q(col)} = {_render(e)}'
                         for col, e in assignments)
        sql = (f'UPDATE {_q(self._table)} SET {sets} '
               f'WHERE {_render(condition)}')
        for pr in param_rows:
            self._exec(sql, pr)
        self._conn.commit()

    def delete_records(self, condition, param_rows) -> None:
        sql = f'DELETE FROM {_q(self._table)} WHERE {_render(condition)}'
        for pr in (param_rows or [{}]):
            self._exec(sql, pr)
        self._conn.commit()

    def contains_records(self, condition, params) -> bool:
        cur = self._exec(
            f'SELECT EXISTS(SELECT 1 FROM {_q(self._table)} '
            f'WHERE {_render(condition)})', params)
        return bool(cur.fetchone()[0])

    # --------------------------------------------------- selection pushdown

    def query_records(self, condition, params,
                      selection: RecordSelection) -> Iterable[Dict[str, Any]]:
        names = [n for n, _ in selection.select]
        cols = ", ".join(f'{_render(e)} AS {_q(n)}'
                         for n, e in selection.select)
        sql = (f'SELECT {cols} FROM {_q(self._table)} '
               f'WHERE {_render(condition)}')
        if selection.group_by:
            sql += " GROUP BY " + ", ".join(
                _q(g) for g in selection.group_by)
        if selection.having is not None:
            sql += f" HAVING {_render(selection.having)}"
        if selection.order_by:
            sql += " ORDER BY " + ", ".join(
                f'{_q(a)} {"ASC" if asc else "DESC"}'
                for a, asc in selection.order_by)
        if selection.limit is not None or selection.offset is not None:
            sql += f" LIMIT {selection.limit if selection.limit is not None else -1}"
            if selection.offset is not None:
                sql += f" OFFSET {selection.offset}"
        cur = self._exec(sql, params)
        # outputs that are plain bool-column passthroughs keep host parity
        # (sqlite stores BOOL as 0/1)
        bool_outs = [n for n, e in selection.select
                     if isinstance(e, Col) and e.name in self._bools]
        for row in cur.fetchall():
            d = dict(zip(names, row))
            for b in bool_outs:
                if d[b] is not None:
                    d[b] = bool(d[b])
            yield d
