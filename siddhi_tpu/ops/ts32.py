"""Shared int32 timestamp-offset machinery for device kernels.

x64 is disabled under jit, so device timestamps ride int32 ms offsets from
a host-held base; after ~24.8 days of stream time the base must move
("rebase") and every carried timestamp shifts with it.  Both device paths —
the NFA (plan/nfa_compiler._maybe_rebase) and the time-window aggregation
ring (plan/wagg_compiler._with_ts_offsets) — use these helpers so their
clamp/headroom semantics stay identical.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def safe_max(slack_ms: int) -> int:
    """Largest representable offset, leaving headroom for `offset + slack`
    arithmetic (expiry subtraction, deadline addition) plus a 2^21 guard
    band so a whole ingest block fits past the check."""
    return (1 << 31) - (1 << 21) - (slack_ms + 1)


def shift_clamped(v, delta: int, lo: int) -> jnp.ndarray:
    """Shift carried int32 ts offsets down by `delta`, clamping at `lo`
    in int64 so an arbitrarily large delta can't wrap int32 (anything at
    the clamp floor is expired at every future ts)."""
    s = np.asarray(v, np.int64) - delta
    return jnp.asarray(np.maximum(s, lo).astype(np.int32))


def rebase_offsets(src: np.ndarray, valid: np.ndarray, base,
                   window_ms: int, ring_ts, empty_marker: int,
                   sentinels=None, site: str = "ts32"):
    """Shared i64→i32 offset rebase for time-window device rings (used by
    plan/wagg_compiler AND plan/gagg_compiler — one protocol, one place).

    src: absolute i64 timestamps for the chunk (all rows); ONLY rows with
    `valid` participate in the base/range decisions — rejected rows may
    carry junk timestamps that must not pin or blow the base.  ring_ts:
    the carry's current i32 ts plane (empty slots == empty_marker), or
    None.  Returns (offsets i32 [n] — invalid rows zeroed, new_base,
    shifted_ring_ts or None).  Raises SiddhiAppRuntimeException on
    chunks that cannot be represented (data errors for the @OnError
    boundary)."""
    from ..utils.errors import SiddhiAppRuntimeException
    src = np.asarray(src, np.int64)
    valid = np.asarray(valid, bool)
    if not valid.any():
        return np.zeros(len(src), np.int32), base, ring_ts
    vsrc = src[valid]
    if base is None:
        base = int(vsrc.min())
    offs = src - base
    mx = int(offs[valid].max())
    safe = safe_max(window_ms)
    if mx <= safe and int(offs[valid].min()) < -safe:
        raise SiddhiAppRuntimeException(
            "time-window device path: an event timestamp is more than "
            "~24 days older than the stream's time base")
    new_ring = ring_ts
    if mx > safe:
        delta = int(offs[valid].min())
        base += delta
        offs = offs - delta
        if int(offs[valid].max()) > safe:
            raise SiddhiAppRuntimeException(
                "time-window device path: a single chunk spans more than "
                "~24 days of stream time; split the replay into smaller "
                "chunks or use @app:engine('host')")
        if ring_ts is not None:
            rts = np.asarray(ring_ts, np.int64)
            shifted = shift_clamped(rts, delta, empty_marker + 1)
            new_ring = jnp.where(jnp.asarray(rts == empty_marker),
                                 jnp.int32(empty_marker), shifted)
        if sentinels is not None:
            # NUMGUARD witness (core/numguard.py): count the rebase and
            # report the horizon headroom left after the shift
            sentinels.note_rebase(site, safe - int(offs[valid].max()))
    return np.where(valid, offs, 0).astype(np.int32), base, new_ring
