"""Shared int32 timestamp-offset machinery for device kernels.

x64 is disabled under jit, so device timestamps ride int32 ms offsets from
a host-held base; after ~24.8 days of stream time the base must move
("rebase") and every carried timestamp shifts with it.  Both device paths —
the NFA (plan/nfa_compiler._maybe_rebase) and the time-window aggregation
ring (plan/wagg_compiler._with_ts_offsets) — use these helpers so their
clamp/headroom semantics stay identical.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def safe_max(slack_ms: int) -> int:
    """Largest representable offset, leaving headroom for `offset + slack`
    arithmetic (expiry subtraction, deadline addition) plus a 2^21 guard
    band so a whole ingest block fits past the check."""
    return (1 << 31) - (1 << 21) - (slack_ms + 1)


def shift_clamped(v, delta: int, lo: int) -> jnp.ndarray:
    """Shift carried int32 ts offsets down by `delta`, clamping at `lo`
    in int64 so an arbitrarily large delta can't wrap int32 (anything at
    the clamp floor is expired at every future ts)."""
    s = np.asarray(v, np.int64) - delta
    return jnp.asarray(np.maximum(s, lo).astype(np.int32))
