"""Device selection kernel: having mask + order-by + limit on egress.

``build_select_step(program)`` interprets a pure-data
plan/select_compiler.SelectProgram into a plain-JAX step that runs right
after the grouped-agg step on the SAME 13 output planes, replacing the
per-emission host ``QuerySelector`` pass for device-expressible shapes:

  * having atoms compare normalized two-float pairs lexicographically —
    exactly the host's float64 comparison for every operand kind the
    compiler admits (float-sum pairs, exact i32 counts/min/max split
    into pairs without i32 overflow, two-float-representable constants);
  * order-by replicates the host's numpy loop literally: one stable
    sort pass per key in reverse spec order, descending = reverse the
    permutation after a stable ascending sort.  Sort keys (only) are
    canonicalized first (-0 -> +0, any-NaN pair -> +NaN, inf pairs drop
    their lo residue) because XLA sorts by bit-level total order while
    the host argsorts IEEE doubles with NaN last;
  * rows failing ok/having are stably partitioned to the back, then a
    static offset rotation and an ``out_count = clip(kept - offset, 0,
    limit)`` slice bound make limit/offset free on device;
  * the single-f32-key ascending-limit shape takes ``jax.lax.top_k``
    over a monotone int32 encoding instead of full sorts — top_k's
    lower-index-first tie rule IS the host's stable ascending argsort.

Outputs: ``(sel_rows, meta=[out_count, max_cnt], *13 compacted planes)``
— every array either per-padded-row or tiny, so the whole tuple lands in
the egress fuser as one device->host slab with no per-emission hop.
``max_cnt`` is the pre-having maximum group count (the int64-sum decode
guard must see counts for rows the having mask filtered out).

No jax.jit here: the caller routes compilation through the shape-class
registry (plan/shapes.py) so prewarm/coldstart cover the kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .grouped_agg import _two_sum

# operand plane stems -> index into the 13-tuple grouped-agg output
# (fhi, flo, ihi, ilo, cnt, w_mnf, w_mxf, w_mni, w_mxi,
#  all_mnf, all_mxf, all_mni, all_mxi)
_PLANES = {"wmnf": 5, "wmxf": 6, "wmni": 7, "wmxi": 8,
           "amnf": 9, "amxf": 10, "amni": 11, "amxi": 12}

_I32_MAX = (1 << 31) - 1


def _int_pair(v):
    """Exact i32 -> normalized two-float32 pair, without the f32-round-
    trip overflow trap at |v| near 2**31: split at 2**16 in integers,
    convert both halves exactly, renormalize with two_sum."""
    up = v >> 16
    low = v - (up << 16)
    hi0 = up.astype(jnp.float32) * jnp.float32(65536.0)
    lo0 = low.astype(jnp.float32)
    return _two_sum(hi0, lo0)


def _const_pair(c: float):
    """Host-side split of a compiler-verified two-float constant."""
    import numpy as np
    chi = np.float32(c)
    clo = np.float32(np.float64(c) - np.float64(chi))
    return chi, clo


def build_select_step(program):
    """Returns step(13 grouped-agg planes [P,T,(V)], lanes, rows, okm)
    -> (sel_rows [n_pad] i32, meta [2] i32, 13 planes compacted to
    [n_pad,(V)] in selection order).  lanes/rows/okm are the padded
    emission gather vectors (padding rows carry okm=False and sort to
    the back, never inside out_count)."""
    having = program.having
    order = program.order
    limit = program.limit
    offset = program.offset

    def step(fhi, flo, ihi, ilo, cnt, w_mnf, w_mxf, w_mni, w_mxi,
             a_mnf, a_mxf, a_mni, a_mxi, lanes, rows, okm):
        planes = (fhi, flo, ihi, ilo, cnt, w_mnf, w_mxf, w_mni, w_mxi,
                  a_mnf, a_mxf, a_mni, a_mxi)
        n = lanes.shape[0]
        em = [a[lanes, rows] for a in planes]

        def operand(o):
            tag = o[0]
            if tag == "const":
                chi, clo = _const_pair(o[1])
                return (jnp.full((n,), chi, jnp.float32),
                        jnp.full((n,), clo, jnp.float32))
            if tag == "cnt":
                return _int_pair(em[4])
            if tag == "fpair":
                hi = em[0][:, o[1]]
                lo = em[1][:, o[1]]
                # inf sums carry junk/NaN residues; the represented
                # value is the hi inf alone
                lo = jnp.where(jnp.isinf(hi), jnp.float32(0.0), lo)
                return hi, lo
            if tag == "f32":
                v = em[_PLANES[o[1]]][:, o[2]]
                return v, jnp.zeros_like(v)
            return _int_pair(em[_PLANES[o[1]]][:, o[2]])    # "i32"

        def cmp(op, a, b):
            # lexicographic pair compare == exact f64 compare for
            # normalized pairs; NaN hi makes every ordered compare
            # False, matching host NaN semantics
            (h1, l1), (h2, l2) = a, b
            if op == "lt":
                return (h1 < h2) | ((h1 == h2) & (l1 < l2))
            if op == "gt":
                return (h1 > h2) | ((h1 == h2) & (l1 > l2))
            if op == "le":
                return (h1 < h2) | ((h1 == h2) & (l1 <= l2))
            if op == "ge":
                return (h1 > h2) | ((h1 == h2) & (l1 >= l2))
            eq = (h1 == h2) & (l1 == l2)
            return eq if op == "eq" else ~eq

        def ev(t):
            k = t[0]
            if k == "and":
                return ev(t[1]) & ev(t[2])
            if k == "or":
                return ev(t[1]) | ev(t[2])
            if k == "not":
                return ~ev(t[1])
            return cmp(t[1], operand(t[2]), operand(t[3]))

        keep = okm if having is None else (okm & ev(having))
        max_cnt = jnp.max(jnp.where(okm, em[4], jnp.int32(0)))
        kept = jnp.sum(keep.astype(jnp.int32))

        if program.topk and limit is not None and 0 < limit < n:
            # single ascending f32 key: monotone i32 encoding, smallest
            # ``limit`` rows via top_k, ties broken lower-index-first —
            # identical to the host's stable ascending argsort prefix
            v, _ = operand(order[0][0])
            v = v + jnp.float32(0.0)                       # -0 -> +0
            v = jnp.where(jnp.isnan(v), jnp.float32(jnp.nan), v)
            b = jax.lax.bitcast_convert_type(v, jnp.int32)
            enc = jnp.where(b < 0, b ^ jnp.int32(_I32_MAX), b)
            enc = jnp.where(keep, enc, jnp.int32(_I32_MAX))
            _, idx = jax.lax.top_k(-enc, limit)
            perm = jnp.concatenate(
                [idx.astype(jnp.int32),
                 jnp.zeros((n - limit,), jnp.int32)])
        else:
            perm = jnp.arange(n, dtype=jnp.int32)
            for (o, asc) in reversed(order):
                khi, klo = operand(o)
                kh = khi[perm] + jnp.float32(0.0)
                kl = jnp.where(jnp.isinf(kh), jnp.float32(0.0),
                               klo[perm]) + jnp.float32(0.0)
                nan = jnp.isnan(kh) | jnp.isnan(kl)
                kh = jnp.where(nan, jnp.float32(jnp.nan), kh)
                kl = jnp.where(nan, jnp.float32(0.0), kl)
                _, _, perm = jax.lax.sort((kh, kl, perm), num_keys=2,
                                          is_stable=True)
                if not asc:
                    perm = perm[::-1]
            # stable partition: kept rows first, in current order
            inval = (~keep)[perm].astype(jnp.int32)
            _, perm = jax.lax.sort((inval, perm), num_keys=1,
                                   is_stable=True)
            if offset:
                perm = jnp.concatenate([perm[offset:], perm[:offset]])

        avail = jnp.maximum(kept - jnp.int32(offset), jnp.int32(0))
        outc = avail if limit is None else \
            jnp.minimum(avail, jnp.int32(limit))
        meta = jnp.stack([outc.astype(jnp.int32),
                          max_cnt.astype(jnp.int32)])
        return (perm, meta) + tuple(e[perm] for e in em)

    return step
