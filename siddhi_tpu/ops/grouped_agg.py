"""Grouped window / running aggregation kernel — the device QuerySelector.

Replaces the reference's per-group HashMap of aggregator objects
(query/selector/QuerySelector.java:171+, GroupByKeyGenerator.java) with a
dense per-(lane, group) state slab:

    ring_f/ring_i [P, W, V]  — window contents per value expression
                               (length windows; W == 0 → no window)
    ring_gid [P, W]          — each slot's group id
    fsum/isum [P, G, V]      — per-group running sums (paired lanes)
    gcnt      [P, G]         — per-group live counts (shared: every
                               aggregate sees the same accepted events)
    *min/*max [P, G, V]      — add-only extrema (minForever/maxForever,
                               and plain min/max when there is no window)

    step = lax.scan over T  ∘  vmap over P

P is the partition-lane axis (1 for non-partitioned queries): groups of
different lanes are distinct aggregator states, exactly like the
reference's per-key QuerySelector clones.  V indexes the DISTINCT value
expressions of the select (sum(volume), avg(price), ... — each gets its
own lane; float-typed and int-typed expressions ride separate banks so
both stay exact).  An arriving event updates its group's state (evicting
the window's oldest entry from ITS group first) and emits that group's
aggregates — the reference's CURRENT/EXPIRED algebra netted per event.

Numeric exactness:
  - float bank: f32 values with TWO-FLOAT (TwoSum/Dekker) running sums —
    (hi, lo) pairs whose f64 sum tracks the true sum to ~2^-48 relative
    error, so egress agrees with the host oracle's float64 accumulation
    at float32 precision (plain Kahan is NOT enough: its runsum alone can
    sit one f32 ulp off, which the conformance corpus' f32-normalised
    equality catches).
  - int bank: i32 values with EXACT sums via a hi/lo split: every value
    v = (v >> 16) * 65536 + (v & 65535); both partial sums stay inside
    i32 exactly while a group holds < 32768 live entries (windows are
    plan-capped; the no-window running mode guards the live count at
    egress), and the host reassembles int64 = hi * 65536 + lo — this is
    what lets `sum(volume long)` run on device with exact integer
    equality (reference SumAttributeAggregatorExecutor long/int
    variants); |v| >= 2^31 is a rejected data error.

Windowed min/max need no decrement state: the ring materialises the
window, so extrema are masked reductions over the arriving group's slots
(same dissolution of the sliding-extremum problem as windowed_agg.py).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

INT_EXACT_MAX = 1 << 31        # |int value| bound for i32 device lanes
INT_GROUP_MAX = 1 << 15        # live entries per group for exact int sums
_SPLIT = 65536                 # int hi/lo split base (16 bits)

I32_MAX = np.int32(np.iinfo(np.int32).max)
I32_MIN = np.int32(np.iinfo(np.int32).min)


class GroupedAggCarry(NamedTuple):
    ring_f: jnp.ndarray     # [P, W, VF] f32
    ring_i: jnp.ndarray     # [P, W, VI] i32
    ring_gid: jnp.ndarray   # [P, W] i32
    pos: jnp.ndarray        # [P] i32
    cnt: jnp.ndarray        # [P] i32
    fsum_hi: jnp.ndarray    # [P, G, VF] f32 two-float hi
    fsum_lo: jnp.ndarray    # [P, G, VF] f32 two-float lo
    isum_hi: jnp.ndarray    # [P, G, VI] i32 split hi
    isum_lo: jnp.ndarray    # [P, G, VI] i32 split lo
    gcnt: jnp.ndarray       # [P, G] i32
    fmin_f: jnp.ndarray     # [P, G, VF] f32 add-only min
    fmax_f: jnp.ndarray     # [P, G, VF] f32 add-only max
    fmin_i: jnp.ndarray     # [P, G, VI] i32 add-only min
    fmax_i: jnp.ndarray     # [P, G, VI] i32 add-only max


def make_grouped_carry(n_lanes: int, window: int, n_groups: int,
                       n_float: int, n_int: int) -> GroupedAggCarry:
    P, W, G, VF, VI = n_lanes, window, n_groups, n_float, n_int
    return GroupedAggCarry(
        ring_f=jnp.zeros((P, W, VF), jnp.float32),
        ring_i=jnp.zeros((P, W, VI), jnp.int32),
        ring_gid=jnp.full((P, W), -1, jnp.int32),
        pos=jnp.zeros((P,), jnp.int32),
        cnt=jnp.zeros((P,), jnp.int32),
        fsum_hi=jnp.zeros((P, G, VF), jnp.float32),
        fsum_lo=jnp.zeros((P, G, VF), jnp.float32),
        isum_hi=jnp.zeros((P, G, VI), jnp.int32),
        isum_lo=jnp.zeros((P, G, VI), jnp.int32),
        gcnt=jnp.zeros((P, G), jnp.int32),
        # ±inf sentinels (not ±F32_MAX): an infinite input value must
        # propagate to min/max output exactly as the host oracle's does
        fmin_f=jnp.full((P, G, VF), jnp.inf, jnp.float32),
        fmax_f=jnp.full((P, G, VF), -jnp.inf, jnp.float32),
        fmin_i=jnp.full((P, G, VI), I32_MAX, jnp.int32),
        fmax_i=jnp.full((P, G, VI), I32_MIN, jnp.int32))


def _two_sum(a, b):
    """Error-free transform: a + b = s + err exactly (Knuth TwoSum)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _pair_add(hi, lo, x, ok):
    """Add x ([V]) to the (hi, lo) two-float accumulators where ok."""
    s, e = _two_sum(hi, x)
    lo2 = lo + e
    hi2 = s + lo2                      # fast renormalisation keeps the
    lo3 = lo2 - (hi2 - s)              # pair non-overlapping
    return jnp.where(ok, hi2, hi), jnp.where(ok, lo3, lo)


def build_grouped_step(window: int, want_minmax: bool, want_forever: bool,
                       numguard: bool = False):
    """fn(carry, vals_f [P,T,VF], vals_i [P,T,VI], gids [P,T] i32,
    accepted [P,T]) → (carry, outs): per-event aggregates of the arriving
    event's group after the update — a 13-tuple of [P, T, ...] arrays
    (fsum hi/lo, isum hi/lo, cnt, windowed min/max per bank, forever
    min/max per bank).  Positions with accepted=False carry junk and are
    discarded host-side.

    window == 0 → running (no-window) mode: no eviction, and plain
    min/max equal the forever lanes.

    numguard=True (SIDDHI_TPU_NUMGUARD, core/numguard.py) appends a
    14th output: a [3] int32 sentinel plane [int-sums near the 2^31
    ceiling, count lanes near 2^31, non-finite float-sum lanes] folded
    from the post-step carry the kernel already holds — an extra
    OUTPUT, not a carry leaf, so the persistent schema, the cost model
    and every match output stay bit-identical with the guard off."""
    W = window

    def lane_step(carry, xs):
        (rf, ri, rgid, pos, cnt, fhi, flo, ihi, ilo, gc,
         mnf, mxf, mni, mxi) = carry
        xf, xi, g, ok = xs

        if W > 0:
            oh = jnp.arange(W) == pos
            evict = ok & (cnt == W)
            old_f = jnp.sum(jnp.where(oh[:, None], rf, 0), axis=0)   # [VF]
            old_i = jnp.sum(jnp.where(oh[:, None], ri, 0), axis=0)   # [VI]
            old_g = jnp.sum(jnp.where(oh, rgid, 0))
            h2, l2 = _pair_add(fhi[old_g], flo[old_g], -old_f, evict)
            fhi = fhi.at[old_g].set(h2)
            flo = flo.at[old_g].set(l2)
            ihi = ihi.at[old_g].add(
                jnp.where(evict, -(old_i >> 16), 0))
            ilo = ilo.at[old_g].add(
                jnp.where(evict, -(old_i & (_SPLIT - 1)), 0))
            gc = gc.at[old_g].add(jnp.where(evict, -1, 0))
            rf = jnp.where(ok & oh[:, None], xf[None, :], rf)
            ri = jnp.where(ok & oh[:, None], xi[None, :], ri)
            rgid = jnp.where(ok & oh, g, rgid)
            pos = jnp.where(ok, (pos + 1) % W, pos)
            cnt = jnp.where(ok, jnp.minimum(cnt + 1, W), cnt)

        h2, l2 = _pair_add(fhi[g], flo[g], xf, ok)
        fhi = fhi.at[g].set(h2)
        flo = flo.at[g].set(l2)
        ihi = ihi.at[g].add(jnp.where(ok, xi >> 16, 0))
        ilo = ilo.at[g].add(jnp.where(ok, xi & (_SPLIT - 1), 0))
        gc = gc.at[g].add(jnp.where(ok, 1, 0))
        if want_forever or (want_minmax and W == 0):
            mnf = mnf.at[g].min(jnp.where(ok, xf, mnf[g]))
            mxf = mxf.at[g].max(jnp.where(ok, xf, mxf[g]))
            mni = mni.at[g].min(jnp.where(ok, xi, mni[g]))
            mxi = mxi.at[g].max(jnp.where(ok, xi, mxi[g]))

        if want_minmax and W > 0:
            live = ((jnp.arange(W) < cnt) & (rgid == g))[:, None]
            w_mnf = jnp.min(jnp.where(live, rf, jnp.inf), axis=0)
            w_mxf = jnp.max(jnp.where(live, rf, -jnp.inf), axis=0)
            w_mni = jnp.min(jnp.where(live, ri, I32_MAX), axis=0)
            w_mxi = jnp.max(jnp.where(live, ri, I32_MIN), axis=0)
        else:
            w_mnf, w_mxf, w_mni, w_mxi = mnf[g], mxf[g], mni[g], mxi[g]
        out = (fhi[g], flo[g], ihi[g], ilo[g], gc[g],
               w_mnf, w_mxf, w_mni, w_mxi,
               mnf[g], mxf[g], mni[g], mxi[g])
        return (rf, ri, rgid, pos, cnt, fhi, flo, ihi, ilo, gc,
                mnf, mxf, mni, mxi), out

    def per_lane(carry_l, f_l, i_l, g_l, ok_l):
        return jax.lax.scan(lane_step, carry_l, (f_l, i_l, g_l, ok_l))

    def step(carry: GroupedAggCarry, vals_f, vals_i, gids, accepted):
        new_c, outs = jax.vmap(per_lane)(tuple(carry), vals_f, vals_i,
                                         gids, accepted)
        nc = GroupedAggCarry(*new_c)
        if numguard:
            outs = outs + (sentinel_plane(nc.fsum_hi, nc.isum_hi,
                                          nc.isum_lo, nc.gcnt),)
        return nc, outs

    return step


def sentinel_plane(fsum_hi, isum_hi, isum_lo, gcnt) -> jnp.ndarray:
    """[3] int32 NUMGUARD flags folded from accumulator planes a step
    already produced: [int sums past 90% of 2^31, count lanes past 90%
    of 2^31, non-finite float-sum lanes].  The int reassembly rides f32
    (x64 is off under jit) — exactness does not matter for a 0.9x
    threshold test, only magnitude."""
    near = jnp.float32(0.9 * INT_EXACT_MAX)
    isum = (isum_hi.astype(jnp.float32) * _SPLIT +
            isum_lo.astype(jnp.float32))
    n_int = jnp.sum(jnp.abs(isum) >= near).astype(jnp.int32)
    n_cnt = jnp.sum(gcnt.astype(jnp.float32) >= near).astype(jnp.int32)
    n_fin = jnp.sum(~jnp.isfinite(fsum_hi)).astype(jnp.int32)
    return jnp.stack([n_int, n_cnt, n_fin])


def reassemble_int_sums(sum_hi: np.ndarray, sum_lo: np.ndarray
                        ) -> np.ndarray:
    """hi/lo split partial sums → exact int64 totals (host egress side)."""
    return sum_hi.astype(np.int64) * _SPLIT + sum_lo.astype(np.int64)


# ------------------------------------------------------------ time windows

TS_EMPTY = np.iinfo(np.int32).min      # empty-slot timestamp marker


class GroupedTimeCarry(NamedTuple):
    ring_f: jnp.ndarray     # [P, W, VF] f32
    ring_i: jnp.ndarray     # [P, W, VI] i32
    ring_gid: jnp.ndarray   # [P, W] i32
    ring_ts: jnp.ndarray    # [P, W] i32 offsets (TS_EMPTY = empty)
    pos: jnp.ndarray        # [P] i32
    cnt: jnp.ndarray        # [P] i32
    overflow: jnp.ndarray   # [P] bool — sticky: a still-in-window entry
    #                         was evicted; caller grows capacity + replays
    fmin_f: jnp.ndarray     # [P, G, VF] add-only extrema (forever lanes)
    fmax_f: jnp.ndarray
    fmin_i: jnp.ndarray     # [P, G, VI]
    fmax_i: jnp.ndarray


def make_grouped_time_carry(n_lanes: int, capacity: int, n_groups: int,
                            n_float: int, n_int: int) -> GroupedTimeCarry:
    P, W, G, VF, VI = n_lanes, capacity, n_groups, n_float, n_int
    return GroupedTimeCarry(
        ring_f=jnp.zeros((P, W, VF), jnp.float32),
        ring_i=jnp.zeros((P, W, VI), jnp.int32),
        ring_gid=jnp.full((P, W), -1, jnp.int32),
        ring_ts=jnp.full((P, W), TS_EMPTY, jnp.int32),
        pos=jnp.zeros((P,), jnp.int32),
        cnt=jnp.zeros((P,), jnp.int32),
        overflow=jnp.zeros((P,), bool),
        fmin_f=jnp.full((P, G, VF), jnp.inf, jnp.float32),
        fmax_f=jnp.full((P, G, VF), -jnp.inf, jnp.float32),
        fmin_i=jnp.full((P, G, VI), I32_MAX, jnp.int32),
        fmax_i=jnp.full((P, G, VI), I32_MIN, jnp.int32))


def _pair_tree_sum(vals, live):
    """Masked two-float tree reduction over axis 0 (W must be pow2):
    returns (hi, lo) whose f64 sum tracks the true sum to ~2^-45 —
    a plain f32 tree reduce can sit an f32 ulp off the host's float64
    accumulation, which conformance equality catches."""
    hi = jnp.where(live, vals, 0.0)
    lo = jnp.zeros_like(hi)
    w = hi.shape[0]
    while w > 1:
        half = w // 2
        a_hi, a_lo = hi[:half], lo[:half]
        b_hi, b_lo = hi[half:w], lo[half:w]
        s, e = _two_sum(a_hi, b_hi)
        lo2 = a_lo + b_lo + e
        hi = s + lo2
        lo = lo2 - (hi - s)
        w = half
    return hi[0], lo[0]


def build_grouped_time_step(window_ms: int, capacity: int,
                            want_forever: bool):
    """Grouped sliding time(t)/externalTime aggregation: the ring
    materialises the window's (value, gid, ts) entries; each accepted
    event's outputs are exact masked reductions over entries of ITS group
    with `entry_ts > event_ts - window_ms` — the same expiry-in-the-mask
    treatment as ops/windowed_agg.build_time_wagg_step, with a group-id
    plane (per-group aggregator maps, QuerySelector.java:171).  Float
    sums reduce via the two-float pairwise tree (_pair_tree_sum — host
    float64 parity at f32 precision); INT sums reduce hi/lo split lanes
    and stay EXACT.  Same output contract as build_grouped_step
    (13-tuple)."""
    W = capacity
    iota = jnp.arange(W)

    def lane_step(carry, xs):
        (rf, ri, rgid, rts, pos, cnt, ovf, mnf, mxf, mni, mxi) = carry
        xf, xi, g, t, ok = xs
        oh = iota == pos
        old_ts = jnp.sum(jnp.where(oh, rts, 0))
        evicting_live = (cnt == W) & (old_ts > t - window_ms)
        ovf = ovf | (ok & evicting_live)
        rf = jnp.where((ok & oh)[:, None], xf[None, :], rf)
        ri = jnp.where((ok & oh)[:, None], xi[None, :], ri)
        rgid = jnp.where(ok & oh, g, rgid)
        rts = jnp.where(ok & oh, t, rts)
        pos = jnp.where(ok, (pos + 1) % W, pos)
        cnt = jnp.where(ok, jnp.minimum(cnt + 1, W), cnt)
        if want_forever:
            mnf = mnf.at[g].min(jnp.where(ok, xf, mnf[g]))
            mxf = mxf.at[g].max(jnp.where(ok, xf, mxf[g]))
            mni = mni.at[g].min(jnp.where(ok, xi, mni[g]))
            mxi = mxi.at[g].max(jnp.where(ok, xi, mxi[g]))
        live = ((iota < cnt) & (rts > t - window_ms) & (rgid == g))[:, None]
        s_f, s_f_lo = _pair_tree_sum(rf, live)
        s_ihi = jnp.sum(jnp.where(live, ri >> 16, 0), axis=0)
        s_ilo = jnp.sum(jnp.where(live, ri & (_SPLIT - 1), 0), axis=0)
        c = jnp.sum(live[:, 0].astype(jnp.int32))
        w_mnf = jnp.min(jnp.where(live, rf, jnp.inf), axis=0)
        w_mxf = jnp.max(jnp.where(live, rf, -jnp.inf), axis=0)
        w_mni = jnp.min(jnp.where(live, ri, I32_MAX), axis=0)
        w_mxi = jnp.max(jnp.where(live, ri, I32_MIN), axis=0)
        out = (s_f, s_f_lo, s_ihi, s_ilo, c,
               w_mnf, w_mxf, w_mni, w_mxi,
               mnf[g], mxf[g], mni[g], mxi[g])
        return (rf, ri, rgid, rts, pos, cnt, ovf, mnf, mxf, mni, mxi), out

    def per_lane(carry_l, f_l, i_l, g_l, ts_l, ok_l):
        return jax.lax.scan(lane_step, carry_l, (f_l, i_l, g_l, ts_l,
                                                 ok_l))

    def step(carry: GroupedTimeCarry, vals_f, vals_i, gids, ts, accepted):
        new_c, outs = jax.vmap(per_lane)(tuple(carry), vals_f, vals_i,
                                         gids, ts, accepted)
        return GroupedTimeCarry(*new_c), outs

    return step
