"""Sliding length-window aggregation kernel (BASELINE config 2 path).

Replaces the reference's per-event window buffer mutation + per-key
aggregator map lookups (query/processor/stream/window/LengthWindowProcessor
.java + QuerySelector.java:171 — linked-list buffer, HashMap of aggregator
objects per group key) with a dense formulation:

    ring   [P, W]  — last W accepted values per partition/group lane
    state  pos/cnt/runsum [P]
    step: evict-one + append-one via a one-hot over W, runsum updated
          incrementally; scan over the block's T events, lanes vectorised.

Two implementations with identical semantics:
  - `build_wagg_step`        — pure jax.numpy (runs everywhere; conformance
                               reference and CPU-backend path)
  - `build_wagg_step_pallas` — Pallas TPU kernel: the ring tile stays
                               resident in VMEM across the whole event loop
                               instead of round-tripping HBM per scan step;
                               lanes ride the 128-wide vector dimension.

Filter + value projection are evaluated OUTSIDE the kernel by the shared
expression compiler (plan/expr_compiler with xp=jnp) — the kernel consumes
(values, accepted) lanes, so any SiddhiQL filter works on both paths.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class WaggCarry(NamedTuple):
    ring: jnp.ndarray      # [P, W] f32
    pos: jnp.ndarray       # [P] i32 — next write slot
    cnt: jnp.ndarray       # [P] i32 — entries held (≤ W)
    runsum: jnp.ndarray    # [P] f32
    comp: jnp.ndarray      # [P] f32 — Kahan compensation for runsum


def make_wagg_carry(n_partitions: int, window: int) -> WaggCarry:
    return WaggCarry(
        ring=jnp.zeros((n_partitions, window), jnp.float32),
        pos=jnp.zeros((n_partitions,), jnp.int32),
        cnt=jnp.zeros((n_partitions,), jnp.int32),
        runsum=jnp.zeros((n_partitions,), jnp.float32),
        comp=jnp.zeros((n_partitions,), jnp.float32))


# ------------------------------------------------------------------ jnp path

def build_wagg_step(window: int, want_minmax: bool = False):
    """fn(carry, values [P,T], accepted [P,T]) →
    (carry, (sums [P,T], counts [P,T][, mins, maxs]))  — running aggregate
    after each accepted event (positions with accepted=False repeat the
    previous).  min/max reduce the live ring slots exactly — no
    subtract-on-expiry state is needed because the window contents are
    materialised (the classic sliding-extremum problem dissolves)."""

    def lane_step(carry, xs):
        ring, pos, cnt, runsum, comp = carry
        x, ok = xs
        oh = jnp.arange(window) == pos            # [W]
        old = jnp.sum(ring * oh)
        evict = cnt == window
        delta = x - jnp.where(evict, old, 0.0)
        # Kahan-compensated add: float32 running sums would drift over long
        # streams of incremental add/subtract updates
        y = delta - comp
        t = runsum + y
        comp2 = jnp.where(ok, (t - runsum) - y, comp)
        runsum2 = jnp.where(ok, t, runsum)
        ring2 = jnp.where(ok & oh, x, ring)
        pos2 = jnp.where(ok, (pos + 1) % window, pos)
        cnt2 = jnp.where(ok, jnp.minimum(cnt + 1, window), cnt)
        out = (runsum2, cnt2)
        if want_minmax:
            valid = jnp.arange(window) < cnt2     # filled slots (see ring
            mn = jnp.min(jnp.where(valid, ring2, jnp.inf))      # fill order)
            mx = jnp.max(jnp.where(valid, ring2, -jnp.inf))
            out = (runsum2, cnt2, mn, mx)
        return (ring2, pos2, cnt2, runsum2, comp2), out

    def per_lane(carry_l, values_l, ok_l):
        return jax.lax.scan(lane_step, carry_l, (values_l, ok_l))

    def step(carry: WaggCarry, values, accepted):
        (ring, pos, cnt, runsum, comp), outs = jax.vmap(per_lane)(
            tuple(carry), values, accepted)
        return WaggCarry(ring, pos, cnt, runsum, comp), outs

    return step


# ------------------------------------------------------------- time windows

TS_EMPTY = np.iinfo(np.int32).min    # empty-slot timestamp marker


class TimeWaggCarry(NamedTuple):
    ring: jnp.ndarray      # [P, W] f32 — last W accepted values
    ring_ts: jnp.ndarray   # [P, W] i32 — ts offsets (TS_EMPTY = empty);
    #                        offsets from the compiler's rebasing base —
    #                        x64 is disabled under jit, so absolute ms
    #                        don't fit (plan/wagg_compiler rebases)
    pos: jnp.ndarray       # [P] i32
    cnt: jnp.ndarray       # [P] i32 — entries written (≤ W)
    last_ts: jnp.ndarray   # [P] i32 — most recent accepted ts offset
    overflow: jnp.ndarray  # [P] bool — sticky: a still-in-window entry was
    #                        evicted (results undercount; caller must grow
    #                        the capacity and replay the block)


def make_time_wagg_carry(n_partitions: int, capacity: int) -> TimeWaggCarry:
    return TimeWaggCarry(
        ring=jnp.zeros((n_partitions, capacity), jnp.float32),
        ring_ts=jnp.full((n_partitions, capacity), TS_EMPTY, jnp.int32),
        pos=jnp.zeros((n_partitions,), jnp.int32),
        cnt=jnp.zeros((n_partitions,), jnp.int32),
        last_ts=jnp.zeros((n_partitions,), jnp.int32),
        overflow=jnp.zeros((n_partitions,), bool))


def build_time_wagg_step(window_ms: int, capacity: int,
                         want_minmax: bool = False):
    """Sliding time(t) aggregation: fn(carry, values [P,T], ts [P,T] i32
    offsets, accepted [P,T]) → (carry, (sums, counts[, mins, maxs])).

    The ring materialises the window's events (value + ts offset); each
    accepted event's output is an exact masked reduction over entries with
    `entry_ts > event_ts - window_ms` — the host TimeWindowProcessor's
    expiry boundary (entries at ts <= now - window expire first,
    core/window.py TimeWindowProcessor._collect_expired).  No incremental
    subtract state: expiry is implicit in the mask, so sums are exact and
    min/max come free.  When an eviction would discard a still-in-window
    entry the lane's sticky `overflow` flag sets — results undercount and
    the caller must grow the capacity and replay from the previous carry.

    Per-event semantics: each event expires by ITS OWN timestamp (the host
    oracle batches expiry at the chunk's final timestamp, so a multi-event
    chunk spanning an expiry boundary can differ; the planner feeds this
    kernel per-junction-chunk exactly as the host path receives them)."""

    iota = jnp.arange(capacity)

    def lane_step(carry, xs):
        ring, rts, pos, cnt, last_ts, ovf = carry
        x, t, ok = xs
        oh = iota == pos
        old_ts = jnp.sum(jnp.where(oh, rts, 0))
        evicting_live = (cnt == capacity) & (old_ts > t - window_ms)
        ovf2 = ovf | (ok & evicting_live)
        ring2 = jnp.where(ok & oh, x, ring)
        rts2 = jnp.where(ok & oh, t, rts)
        pos2 = jnp.where(ok, (pos + 1) % capacity, pos)
        cnt2 = jnp.where(ok, jnp.minimum(cnt + 1, capacity), cnt)
        last2 = jnp.where(ok, t, last_ts)
        valid = (iota < cnt2) & (rts2 > t - window_ms)
        s = jnp.sum(jnp.where(valid, ring2, 0.0))
        c = jnp.sum(valid.astype(jnp.int32))
        if want_minmax:
            mn = jnp.min(jnp.where(valid, ring2, jnp.inf))
            mx = jnp.max(jnp.where(valid, ring2, -jnp.inf))
            out = (s, c, mn, mx)
        else:
            out = (s, c)
        return (ring2, rts2, pos2, cnt2, last2, ovf2), out

    def per_lane(carry_l, values_l, ts_l, ok_l):
        return jax.lax.scan(lane_step, carry_l, (values_l, ts_l, ok_l))

    def step(carry: TimeWaggCarry, values, ts, accepted):
        new_c, outs = jax.vmap(per_lane)(tuple(carry), values, ts, accepted)
        return TimeWaggCarry(*new_c), outs

    return step


# --------------------------------------------------------------- pallas path

LANES = 128


def build_wagg_step_pallas(window: int, t_per_block: int,
                           want_minmax: bool = False):
    """Same contract as build_wagg_step, lowered to one Pallas kernel.

    Layout: partition lanes ride the last (128-wide) dim; the grid walks
    P/128 tiles; each program keeps its (W, 128) ring tile in VMEM for the
    whole T loop."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    W, T = window, t_per_block

    def kernel(values_ref, ok_ref, ring_in, pos_in, cnt_in, sum_in, comp_in,
               ring_out, pos_out, cnt_out, sum_out, comp_out, sums_ref,
               counts_ref, *minmax_refs):
        # refs carry a leading block dim of 1 (one tile per program)
        ring = ring_in[0, :, :]                  # (W, 128)
        pos = pos_in[0, 0, :]                    # (128,)
        cnt = cnt_in[0, 0, :]
        runsum = sum_in[0, 0, :]
        comp = comp_in[0, 0, :]
        iota_w = jax.lax.broadcasted_iota(jnp.int32, (W, LANES), 0)
        for t in range(T):                       # static unroll over events
            x = values_ref[0, t, :]
            ok = ok_ref[0, t, :] != 0
            oh = iota_w == pos[None, :]
            old = jnp.sum(jnp.where(oh, ring, 0.0), axis=0)
            evict = cnt == W
            delta = x - jnp.where(evict, old, 0.0)
            # Kahan-compensated add (see build_wagg_step)
            y = delta - comp
            tt = runsum + y
            comp = jnp.where(ok, (tt - runsum) - y, comp)
            runsum = jnp.where(ok, tt, runsum)
            ring = jnp.where(oh & ok[None, :], x[None, :], ring)
            pos = jnp.where(ok, (pos + 1) % W, pos)
            cnt = jnp.where(ok, jnp.minimum(cnt + 1, W), cnt)
            sums_ref[0, t, :] = runsum
            counts_ref[0, t, :] = cnt
            if want_minmax:
                valid = iota_w < cnt[None, :]
                minmax_refs[0][0, t, :] = jnp.min(
                    jnp.where(valid, ring, jnp.inf), axis=0)
                minmax_refs[1][0, t, :] = jnp.max(
                    jnp.where(valid, ring, -jnp.inf), axis=0)
        ring_out[0, :, :] = ring
        pos_out[0, 0, :] = pos
        cnt_out[0, 0, :] = cnt
        sum_out[0, 0, :] = runsum
        comp_out[0, 0, :] = comp

    def step(carry: WaggCarry, values, accepted):
        P = carry.ring.shape[0]
        assert P % LANES == 0, f"partitions must be a multiple of {LANES}"
        tiles = P // LANES
        # lanes-last layout: [tiles, T|W, 128]
        vals = values.reshape(tiles, LANES, -1).transpose(0, 2, 1)
        ok = accepted.astype(jnp.int32).reshape(tiles, LANES, -1) \
            .transpose(0, 2, 1)
        ring = carry.ring.reshape(tiles, LANES, W).transpose(0, 2, 1)
        pos = carry.pos.reshape(tiles, 1, LANES)
        cnt = carry.cnt.reshape(tiles, 1, LANES)
        rs = carry.runsum.reshape(tiles, 1, LANES)
        cp = carry.comp.reshape(tiles, 1, LANES)

        grid = (tiles,)

        def tile_spec(shape):
            return pl.BlockSpec((1,) + shape,
                                lambda i: (i,) + (0,) * len(shape),
                                memory_space=pltpu.VMEM)

        out_shape = [
            jax.ShapeDtypeStruct(ring.shape, jnp.float32),   # ring'
            jax.ShapeDtypeStruct(pos.shape, jnp.int32),
            jax.ShapeDtypeStruct(cnt.shape, jnp.int32),
            jax.ShapeDtypeStruct(rs.shape, jnp.float32),
            jax.ShapeDtypeStruct(cp.shape, jnp.float32),
            jax.ShapeDtypeStruct(vals.shape, jnp.float32),   # sums
            jax.ShapeDtypeStruct(ok.shape, jnp.int32),       # counts
        ]
        out_specs = [tile_spec((W, LANES)), tile_spec((1, LANES)),
                     tile_spec((1, LANES)), tile_spec((1, LANES)),
                     tile_spec((1, LANES)), tile_spec((T, LANES)),
                     tile_spec((T, LANES))]
        if want_minmax:
            out_shape += [jax.ShapeDtypeStruct(vals.shape, jnp.float32),
                          jax.ShapeDtypeStruct(vals.shape, jnp.float32)]
            out_specs += [tile_spec((T, LANES)), tile_spec((T, LANES))]

        ring2, pos2, cnt2, rs2, cp2, sums, counts, *mm = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[tile_spec((T, LANES)), tile_spec((T, LANES)),
                      tile_spec((W, LANES)), tile_spec((1, LANES)),
                      tile_spec((1, LANES)), tile_spec((1, LANES)),
                      tile_spec((1, LANES))],
            out_specs=out_specs,
            out_shape=out_shape,
            input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3, 6: 4},
        )(vals, ok, ring, pos, cnt, rs, cp)

        new_carry = WaggCarry(
            ring=ring2.transpose(0, 2, 1).reshape(P, W),
            pos=pos2.reshape(P), cnt=cnt2.reshape(P),
            runsum=rs2.reshape(P), comp=cp2.reshape(P))

        def back(a):
            return a.transpose(0, 2, 1).reshape(P, -1)
        outs = (back(sums), back(counts))
        if want_minmax:
            outs += (back(mm[0]), back(mm[1]))
        return new_carry, outs

    return step
