"""Device-side incremental-aggregation bucket slabs.

TPU-native replacement for the reference's per-event bucket updates
(aggregation/IncrementalExecutor.java:45-180 — a HashMap of
BaseIncrementalValueStore per (bucket, group key), mutated one event at a
time under synchronization).

Here each duration's bucket store is a fixed SLAB of device tensors

    vals [S, B] float32   — one column per decomposed base (sum / sumsq /
                            min / max / last); counts ride a dedicated lane
    cnt  [S]    int32     — event count per slot (shared by 'count' bases)

updated once per event micro-batch with segment reductions: the host maps
(bucket_ts, group key) pairs to slot ids (dict over the batch's UNIQUE
pairs only), the device folds the whole batch with one `segment_sum` /
`segment_min` / `segment_max` per base — no per-event work on the hot path.

Precision note: values ride float32 lanes (TPU-native); exact integer
conformance is kept for counts (int32 lane).  In the default NAIVE mode
int-typed sums above 2^24 lose precision vs the host cascade's
arbitrary-precision ints — the static NS003 finding
(analysis/ranges.py).  ``@numeric(sum='compensated')`` on the
aggregation definition switches :func:`build_slab_update` to
COMPENSATED mode: each slab keeps a TwoSum error lane per base column,
batch partial sums fold in error-free (Knuth TwoSum, the
ops/grouped_agg.py treatment), and the sync path reads
``vals + comp`` in float64 — integer sums stay exact to 2^48-scale
magnitudes at ~one extra f32 slab of state.  Parity is proven in
tests/test_numguard.py.
"""
from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = np.float32(-np.inf)
POS_INF = np.float32(np.inf)


def init_row(base_fns: List[str]) -> np.ndarray:
    """Initial slab row: identity of each base's reduction."""
    out = np.zeros(len(base_fns), np.float32)
    for i, fn in enumerate(base_fns):
        if fn == "min":
            out[i] = POS_INF
        elif fn == "max":
            out[i] = NEG_INF
    return out


def _two_sum(a, b):
    """Error-free transform: a + b = s + err exactly (Knuth TwoSum)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def build_slab_update(base_fns: Tuple[str, ...],
                      compensated: bool = False):
    """→ jitted fn(vals [S, B], cnt [S], seg [n], base_vals [n, B]) →
    (vals, cnt).  `seg` < 0 marks masked-out rows.

    compensated=True changes the signature to fn(vals, comp, cnt, seg,
    base_vals) → (vals, comp, cnt): sum/sumsq batch partials fold into
    the running slab via TwoSum with the rounding error banked in the
    ``comp`` lane, so ``float64(vals) + float64(comp)`` tracks the true
    sum far past the f32 2^24 cliff (see module docstring)."""
    base_fns = tuple(base_fns)

    def _fold(vals, comp, cnt, seg, base_vals):
        S = vals.shape[0]
        n = seg.shape[0]
        valid = seg >= 0
        seg_c = jnp.where(valid, seg, S)   # OOB segment swallows masked rows
        cnt = cnt + jax.ops.segment_sum(valid.astype(jnp.int32), seg_c,
                                        num_segments=S + 1)[:S]
        cols = []
        ccols = []
        for b, fn in enumerate(base_fns):
            col = base_vals[:, b]
            cur = vals[:, b]
            if fn in ("sum", "sumsq"):
                v = col * col if fn == "sumsq" else col
                add = jax.ops.segment_sum(jnp.where(valid, v, 0.0), seg_c,
                                          num_segments=S + 1)[:S]
                if comp is not None:
                    s, err = _two_sum(cur, add)
                    ccols.append(comp[:, b] + err)
                    cols.append(s)
                else:
                    cols.append(cur + add)
            elif fn == "min":
                m = jax.ops.segment_min(jnp.where(valid, col, POS_INF),
                                        seg_c, num_segments=S + 1)[:S]
                cols.append(jnp.minimum(cur, m))
            elif fn == "max":
                m = jax.ops.segment_max(jnp.where(valid, col, NEG_INF),
                                        seg_c, num_segments=S + 1)[:S]
                cols.append(jnp.maximum(cur, m))
            elif fn == "count":
                cols.append(cur)     # counts ride the dedicated cnt lane
            elif fn == "last":
                # batch-order last event per slot wins
                idx = jnp.arange(n)
                li = jax.ops.segment_max(jnp.where(valid, idx, -1), seg_c,
                                         num_segments=S + 1)[:S]
                has = li >= 0
                lastv = col[jnp.clip(li, 0, max(n - 1, 0))]
                cols.append(jnp.where(has, lastv, cur))
            else:
                raise ValueError(f"Unknown base fn {fn}")
            if comp is not None and fn not in ("sum", "sumsq"):
                ccols.append(comp[:, b])   # untouched for non-sum lanes
        new_vals = jnp.stack(cols, axis=1)
        if comp is not None:
            return new_vals, jnp.stack(ccols, axis=1), cnt
        return new_vals, cnt

    if compensated:
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def update_c(vals, comp, cnt, seg, base_vals):
            return _fold(vals, comp, cnt, seg, base_vals)
        return update_c

    @partial(jax.jit, donate_argnums=(0, 1))
    def update(vals, cnt, seg, base_vals):
        return _fold(vals, None, cnt, seg, base_vals)

    return update


@partial(jax.jit, donate_argnums=(0, 1), static_argnums=(3,))
def reset_slots(vals, cnt, slots, b):
    """Reset freed slots to their reduction identities (purge support)."""
    init = jnp.asarray(init_row(b))
    vals = vals.at[slots].set(init)
    cnt = cnt.at[slots].set(0)
    return vals, cnt
