"""Device window kernels: sliding + tumbling window state as ring slabs.

The window buffer of record lives on device as left-aligned ring slabs
([P, W] payload banks + timestamps + fill), and each input chunk is one
jitted step that (a) computes every eviction / batch-flush VECTORIZED —
closed forms over the concatenated [carry ‖ chunk] stream (searchsorted /
cummax), no per-event host loop — and (b) emits the affected rows through
one compacted egress transfer (pack-with-cap, NFA-style).  The host
composes the reference's CURRENT/EXPIRED/RESET emission order from the
decoded refs (plan/dwin_compiler.py).

Eviction index math per kind (j = index in the concat stream of length
fill+T, FIFO order; t = chunk event index):

- length(n): entry j is displaced by arrival j+n → evict_t = j+n-fill
  (reference LengthWindowProcessor.java:68-90: displaced-by semantics).
- time(t): one cutoff per chunk (now = last ts): evicted iff carried and
  ts_j <= now - window (TimeWindowProcessor.java:118-142 collects expired
  once per batch before appending).
- externalTime(ts, t): evict_t[j] = first event index t with
  etime_t - window >= ts_j, clamped to arrivals after j
  (ExternalTimeWindowProcessor.java: per-event expiry on event time).
- timeLength(t, n): FIFO evictions; total evicted after event t is
  E(t) = max(timeE(t), fill+t+1-n) with timeE monotone — entry rank r is
  evicted at the first t with E(t) > r, by length iff the length bound is
  what crossed r (TimeLengthWindowProcessor.java).
- delay(t): emission (as CURRENT) at first t with now_t >= ts_j + delay
  (DelayWindowProcessor.java).
- lengthBatch(n): batches are consecutive n-blocks of the appended
  stream: batch_id = j // n (LengthBatchWindowProcessor.java).
- timeBatch(t) / externalTimeBatch(ts, t): flush boundaries are control
  state (host-scheduled); the kernel flushes the carried buffer at
  host-directed event positions (TimeBatchWindowProcessor.java).
- hopping(t, hop): ONE flush per step (the host dispatches a separate
  step per hop boundary — an entry can be CURRENT in many overlapping
  windows, which a single per-entry emit mask cannot express).  At a
  flush the window is the live entries with ts in (now - window, now];
  the exp plane carries the previous hop's window, whose entries with
  ts <= now - window emit EXPIRED (HopingWindowProcessor semantics).

Egress row schema (int32): [pool_idx, evict_t, cause, ts_off,
f-bank bitcast ×F, i-bank ×I]; tail row: [count, fill', exp_fill',
min_live_ts, overflow, pad...].  Causes: 1=time-expired, 2=length-
displaced, 3=batch-current, 4=carry-expired-batch, 5=delayed-current.
"""
from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

TS_NONE = np.int32(2 ** 31 - 1)      # "never" / empty sentinel
C_TIME, C_LEN, C_BATCH, C_EXPBATCH, C_DELAY = 1, 2, 3, 4, 5


class DwinSpec(NamedTuple):
    kind: str            # length|time|externalTime|timeLength|delay|
    #                      lengthBatch|timeBatch|externalTimeBatch|batch|
    #                      sort|session
    capacity: int        # ring capacity W (grow-and-replay on overflow)
    n_f: int             # f32 payload lanes
    n_i: int             # i32 payload lanes
    window_ms: int       # time span (0 for pure length kinds); session gap
    length: int          # count bound (0 for pure time kinds)
    sort_keys: tuple = ()  # sort kind: ((bank 0=f/1=i, lane, asc), ...) —
    #                        lex compare order; LONG attrs ride two (hi,
    #                        lo) entries whose lex order IS int64 order
    skey_lane: int = -1  # session kind: i32 lane holding the dict-encoded
    #                      session key (keyless apps encode one code)
    telemetry: bool = False  # @app:statistics(telemetry='true'): carry a
    #                      [P, 3] int32 telemetry leaf (fill gauge,
    #                      evictions total, overflow total) and append a
    #                      summary row to the egress buffer (before the
    #                      tail) — no extra D2H, emissions bit-identical
    hop_ms: int = 0      # hopping kind: emission period (window_ms is the
    #                      span); appended last to keep positional
    #                      construction stable


def make_dwin_carry(spec: DwinSpec, n_lanes: int) -> Dict[str, np.ndarray]:
    P, W = n_lanes, spec.capacity
    F, I = max(spec.n_f, 1), max(spec.n_i, 1)
    c = {"ring_f": np.zeros((P, W, F), np.float32),
         "ring_i": np.zeros((P, W, I), np.int32),
         "ring_ts": np.full((P, W), TS_NONE, np.int32),
         "fill": np.zeros((P,), np.int32)}
    if spec.kind in ("lengthBatch", "timeBatch", "externalTimeBatch",
                     "batch", "hopping"):
        c.update(exp_f=np.zeros((P, W, F), np.float32),
                 exp_i=np.zeros((P, W, I), np.int32),
                 exp_ts=np.full((P, W), TS_NONE, np.int32),
                 exp_fill=np.zeros((P,), np.int32))
    if spec.telemetry:
        # [fill gauge, evictions total, overflow total] per lane
        c["telem"] = np.zeros((P, 3), np.int32)
    return c


def _pool(carry, ev_f, ev_i, ev_ts, ev_valid, W):
    """Concat [carry-ring ‖ chunk] into the stream pool [P, M]."""
    pf = jnp.concatenate([carry["ring_f"], ev_f], axis=1)
    pi = jnp.concatenate([carry["ring_i"], ev_i], axis=1)
    pts = jnp.concatenate([carry["ring_ts"],
                           jnp.where(ev_valid, ev_ts, TS_NONE)], axis=1)
    P, M = pts.shape
    j = jnp.arange(M)[None, :]
    fill = carry["fill"][:, None]
    # concat slot j holds a live entry iff (carry slot < fill) or (chunk
    # slot valid); arrival rank = j for carry, fill + #valid-before for
    # chunk rows (chunk validity is a prefix per lane by construction)
    nv = jnp.sum(ev_valid.astype(jnp.int32), axis=1)[:, None]
    live = jnp.where(j < W, j < fill, j - W < nv)
    rank = jnp.where(j < W, j, fill + (j - W))
    return pf, pi, pts, live, rank, nv[:, 0]


def _rank_order(live, rank, M):
    """Gather order that left-aligns live entries by arrival rank."""
    key = jnp.where(live, rank, M + 1)
    return jnp.argsort(key, axis=1, stable=True)


def _gather(a, order):
    return jnp.take_along_axis(
        a, order.reshape(order.shape + (1,) * (a.ndim - 2)), axis=1) \
        if a.ndim > 2 else jnp.take_along_axis(a, order, axis=1)


def _new_ring(pf, pi, pts, keep, rank, W, F, I):
    """Left-align surviving entries into a fresh [P, W] ring."""
    P, M = pts.shape
    order = _rank_order(keep, rank, M)
    sf = _gather(pf, order)[:, :W]
    si = _gather(pi, order)[:, :W]
    sts = jnp.take_along_axis(jnp.where(keep, pts, TS_NONE), order,
                              axis=1)[:, :W]
    fill = jnp.sum(keep.astype(jnp.int32), axis=1)
    # entries beyond W were lost: overflow → host grows & replays
    overflow = fill > W
    sts = jnp.where(jnp.arange(W)[None, :] < fill[:, None], sts, TS_NONE)
    return sf, si, sts, jnp.minimum(fill, W), overflow


def _pack_egress(emit_mask, pool_idx, evict_t, cause, pts, pf, pi,
                 tail_vals, cap, telem_row=None):
    """[P, M] emission set → [cap+1, 4+F+I] compacted rows + tail.
    When `telem_row` (a [3] int32 summary) is given, one extra row is
    appended BEFORE the tail, so ``buf[-1]`` stays the tail row."""
    P, M = emit_mask.shape
    F = pf.shape[-1]
    I = pi.shape[-1]
    flat = emit_mask.reshape(-1)
    (idx,) = jnp.nonzero(flat, size=cap, fill_value=-1)
    safe = jnp.maximum(idx, 0)

    def g(a):
        return a.reshape(-1)[safe][:, None].astype(jnp.int32)
    f_bits = jax.lax.bitcast_convert_type(
        pf.reshape(-1, F), jnp.int32)[safe]
    i_vals = pi.reshape(-1, I)[safe]
    rows = jnp.concatenate(
        [idx[:, None], g(evict_t), g(cause), g(pts), f_bits, i_vals],
        axis=1)
    tail = jnp.zeros((1, 4 + F + I), jnp.int32)
    tail = tail.at[0, 0].set(jnp.sum(flat.astype(jnp.int32)))
    for k, v in enumerate(tail_vals):
        tail = tail.at[0, 1 + k].set(v)
    if telem_row is not None:
        trow = jnp.zeros((1, 4 + F + I), jnp.int32)
        trow = trow.at[0, :3].set(telem_row)
        return jnp.concatenate([rows, trow, tail], axis=0)
    return jnp.concatenate([rows, tail], axis=0)


def build_dwin_step(spec: DwinSpec):
    """→ step(carry, ev_f, ev_i, ev_ts, ev_valid, now, directive, cap)
    jittable; returns (new_carry, egress buffer).  `directive` is the
    kind-specific host control input (flush count / boundary ids)."""
    W = spec.capacity
    F, I = max(spec.n_f, 1), max(spec.n_i, 1)
    kind = spec.kind

    def step(carry, ev_f, ev_i, ev_ts, ev_valid, now, directive, cap):
        pf, pi, pts, live, rank, nv = _pool(carry, ev_f, ev_i, ev_ts,
                                            ev_valid, W)
        P, M = pts.shape
        fill = carry["fill"]
        j = jnp.arange(M)[None, :]
        is_carry = j < W
        new_carry = dict(carry)

        def telem(nfill, emit_mask, ovf_mask):
            """Accumulate the telemetry leaf; returns the [3] summary row
            for _pack_egress (None when telemetry is off).  Pure addition
            over masks the kernel already computed — emissions and ring
            contents are untouched."""
            tel = carry.get("telem")
            if tel is None:
                return None
            ev = jnp.sum(emit_mask.astype(jnp.int32), axis=1)
            nt = jnp.stack([nfill, tel[:, 1] + ev,
                            tel[:, 2] + ovf_mask.astype(jnp.int32)],
                           axis=1)
            new_carry["telem"] = nt
            return jnp.stack([jnp.max(nt[:, 0]), jnp.sum(nt[:, 1]),
                              jnp.sum(nt[:, 2])])

        if kind == "sort":
            # Keep the bottom-N by (sort key, arrival rank); each
            # overflowing arrival evicts the current lex-max (reference
            # SortWindowProcessor.java).  Greedy max-eviction telescopes:
            # the set after event t is bottom_N(pool through t), so entry
            # x is evicted at the FIRST t where >= N lex-smaller entries
            # have arrived — the N-th smallest arrival step among x's
            # lex-predecessors (an [M, M] order statistic; dwin rings are
            # single-lane and modest, the quadratic mask is cheap).
            n = spec.length
            less = jnp.zeros((P, M, M), bool)
            eq = jnp.ones((P, M, M), bool)
            for (bank, lane, asc) in spec.sort_keys:
                v = pf[:, :, lane] if bank == 0 else pi[:, :, lane]
                a = v[:, :, None]           # x
                b = v[:, None, :]           # y
                lt = (b < a) if asc else (b > a)
                less = less | (eq & lt)
                eq = eq & (b == a)
            # tie: equal keys keep buffer order — the NEWEST (largest
            # rank) is evicted first, so older counts as smaller
            less = less | (eq & (rank[:, None, :] < rank[:, :, None]))
            less = less & live[:, None, :]
            arr = jnp.where(is_carry, -1, rank - fill[:, None])  # [P, M]
            BIG = jnp.int32(2 ** 30)
            a_mask = jnp.where(less, arr[:, None, :], BIG)
            a_sorted = jnp.sort(a_mask, axis=2)
            idx = min(n - 1, M - 1)
            tN = a_sorted[:, :, idx]
            evict_t = jnp.maximum(tN, arr)
            evicted = live & (tN < BIG) & (evict_t < nv[:, None]) if \
                n - 1 < M else jnp.zeros((P, M), bool)
            cause = jnp.full((P, M), C_LEN, jnp.int32)
            keep = live & ~evicted
            sf, si, sts, nfill, ovf = _new_ring(pf, pi, pts, keep, rank,
                                                W, F, I)
            new_carry.update(ring_f=sf, ring_i=si, ring_ts=sts,
                             fill=nfill)
            buf = _pack_egress(evicted, j, evict_t, cause, pts, pf, pi,
                               (jnp.max(nfill), jnp.int32(0), TS_NONE,
                                jnp.max(ovf.astype(jnp.int32))), cap,
                               telem_row=telem(nfill, evicted, ovf))
            return new_carry, buf

        if kind == "session":
            # Per-key gap sessions (reference SessionWindowProcessor):
            # the host expires due sessions BEFORE appending the chunk
            # (its _expire_sessions(now) runs first, so same-key chunk
            # events start a FRESH session).  A carried entry's session
            # is due when its key's last activity + gap <= now; evicted
            # rows carry (last + gap) in the evict_t column as the
            # EXPIRED emission timestamp offsets.
            key = pi[:, :, spec.skey_lane]
            carry_live = live & is_carry
            same = (key[:, None, :] == key[:, :, None]) & \
                carry_live[:, None, :]
            NEG = jnp.int32(-(2 ** 30))
            last = jnp.max(jnp.where(same, pts[:, None, :], NEG), axis=2)
            expired = carry_live & (last + spec.window_ms <= now[:, None])
            evict_ts = last + spec.window_ms
            cause = jnp.full((P, M), C_TIME, jnp.int32)
            keep = live & ~expired
            sf, si, sts, nfill, ovf = _new_ring(pf, pi, pts, keep, rank,
                                                W, F, I)
            new_carry.update(ring_f=sf, ring_i=si, ring_ts=sts,
                             fill=nfill)
            # the host re-arms its gap timer at (reported min + gap), so
            # report the min over live entries of their KEY'S last
            # activity in the post-step ring — a session expires at
            # last+gap, not at its oldest event + gap.  Reporting the
            # min event ts re-armed the timer at an instant where
            # nothing can expire (oldest event's key stayed active),
            # which in playback degenerated to 1 ms timer crawl —
            # 50k+ dispatches on a 60-event stream.
            w_live = jnp.arange(W)[None, :] < nfill[:, None]
            k_new = si[:, :, spec.skey_lane]
            same_new = (k_new[:, None, :] == k_new[:, :, None]) & \
                w_live[:, None, :]
            last_new = jnp.max(jnp.where(same_new, sts[:, None, :], NEG),
                               axis=2)
            live_min = jnp.min(jnp.where(w_live, last_new, TS_NONE))
            buf = _pack_egress(expired, j, evict_ts, cause, pts, pf, pi,
                               (jnp.max(nfill), jnp.int32(0), live_min,
                                jnp.max(ovf.astype(jnp.int32))), cap,
                               telem_row=telem(nfill, expired, ovf))
            return new_carry, buf

        if kind in ("length", "time", "externalTime", "timeLength",
                    "delay"):
            if kind == "length":
                n = spec.length
                # displaced by arrival of rank+n → valid when that arrival
                # exists in this chunk
                evict_rank = rank + n
                evict_t = evict_rank - fill[:, None]     # chunk index
                evicted = live & (evict_t < nv[:, None]) & (evict_t >= 0)
                cause = jnp.full((P, M), C_LEN, jnp.int32)
            elif kind == "time":
                cutoff = now[:, None] - spec.window_ms
                evicted = live & is_carry & (pts <= cutoff)
                evict_t = jnp.zeros((P, M), jnp.int32)
                cause = jnp.full((P, M), C_TIME, jnp.int32)
            elif kind == "externalTime":
                # first chunk event whose etime - window >= entry ts, and
                # strictly after the entry's own arrival
                # int32 throughout: the host rebase guard keeps live offsets
                # below TS_NONE - window - 1, and dead (TS_NONE) entries
                # are masked by `live` before any wrapped value matters
                tgt = pts + spec.window_ms
                ets = jnp.where(ev_valid, ev_ts, TS_NONE)
                evict_t = jax.vmap(
                    lambda e, t: jnp.searchsorted(e, t, side="left"))(
                        ets, tgt).astype(jnp.int32)
                after_self = rank - fill[:, None] + 1   # chunk rows only
                evict_t = jnp.maximum(evict_t, jnp.maximum(after_self, 0))
                evicted = live & (evict_t < nv[:, None])
                cause = jnp.full((P, M), C_TIME, jnp.int32)
            elif kind == "timeLength":
                n = spec.length
                ets64 = jnp.where(ev_valid, ev_ts, TS_NONE)
                # timeE(t): #entries with ts <= now_t - window among those
                # arrived up to t.  Entries are FIFO by ts (arrival order);
                # carried entries sorted; chunk appended in order.
                # int32 throughout: the host rebase guard keeps live offsets
                # below TS_NONE - window - 1, and dead (TS_NONE) entries
                # are masked by `live` before any wrapped value matters
                tgt = pts + spec.window_ms
                t_evict = jax.vmap(
                    lambda e, t: jnp.searchsorted(e, t, side="left"))(
                        ets64, tgt).astype(jnp.int32)
                after_self = rank - fill[:, None] + 1
                t_evict = jnp.maximum(t_evict, jnp.maximum(after_self, 0))
                # length bound: E_len(t) = fill + t + 1 - n → rank r
                # crosses at t = r + n - fill
                l_evict = rank + n - fill[:, None]
                l_evict = jnp.maximum(l_evict,
                                      jnp.maximum(after_self, 0))
                evict_t = jnp.minimum(t_evict, l_evict)
                # timer steps (no events): time-expire against `now`
                by_now = (nv[:, None] == 0) & \
                    (pts + spec.window_ms <= now[:, None])
                evicted = live & ((evict_t < nv[:, None]) | by_now)
                cause = jnp.where(t_evict <= l_evict, C_TIME,
                                  C_LEN).astype(jnp.int32)
            else:                                        # delay
                # due = carried entries with ts <= now - delay, collected
                # once per step BEFORE appending (DelayWindowProcessor:
                # same shape as time, but re-emitted as CURRENT at their
                # original timestamps)
                cutoff = now[:, None] - spec.window_ms
                evicted = live & is_carry & (pts <= cutoff)
                evict_t = jnp.zeros((P, M), jnp.int32)
                cause = jnp.full((P, M), C_DELAY, jnp.int32)
            keep = live & ~evicted
            sf, si, sts, nfill, ovf = _new_ring(pf, pi, pts, keep, rank,
                                                W, F, I)
            new_carry.update(ring_f=sf, ring_i=si, ring_ts=sts,
                             fill=nfill)
            live_min = jnp.min(jnp.where(
                jnp.arange(W)[None, :] < nfill[:, None], sts, TS_NONE))
            buf = _pack_egress(evicted, j, evict_t, cause, pts, pf, pi,
                               (jnp.max(nfill), jnp.int32(0), live_min,
                                jnp.max(ovf.astype(jnp.int32))), cap,
                               telem_row=telem(nfill, evicted, ovf))
            return new_carry, buf

        if kind == "hopping":
            # ONE hop boundary per step: the host dispatches a separate
            # kernel step per boundary (a row can be CURRENT in many
            # overlapping windows, so a single per-entry emit id cannot
            # express multi-flush membership).  `directive[:, 0] > 0`
            # marks a flush step at instant `now`; append-only steps
            # just pool the chunk.  At a flush the window is the live
            # entries with ts in (now - window, now]; the exp plane
            # holds the PREVIOUS hop's window, whose entries with
            # ts <= now - window emit EXPIRED (restamped at the
            # boundary by the host composer — HopingWindowProcessor).
            flushing = directive[:, 0] > 0
            cutoff = now[:, None] - spec.window_ms
            keep = live & (~flushing[:, None] | (pts > cutoff))
            sf, si, sts, nfill, ovf = _new_ring(pf, pi, pts, keep, rank,
                                                W, F, I)
            cur_emit = keep & flushing[:, None]
            cause = jnp.full((P, M), C_BATCH, jnp.int32)
            eslot = jnp.arange(W)[None, :]
            exp_emit = (eslot < carry["exp_fill"][:, None]) & \
                flushing[:, None] & (carry["exp_ts"] <= cutoff)
            exp_cause = jnp.full((P, W), C_EXPBATCH, jnp.int32)
            post_exp_fill = jnp.where(flushing, nfill, carry["exp_fill"])
            new_carry.update(
                ring_f=sf, ring_i=si, ring_ts=sts, fill=nfill,
                exp_f=jnp.where(flushing[:, None, None], sf,
                                carry["exp_f"]),
                exp_i=jnp.where(flushing[:, None, None], si,
                                carry["exp_i"]),
                exp_ts=jnp.where(flushing[:, None], sts,
                                 carry["exp_ts"]),
                exp_fill=post_exp_fill)
            all_mask = jnp.concatenate([cur_emit, exp_emit], axis=1)
            all_idx = jnp.concatenate([j, M + eslot], axis=1)
            all_t = jnp.zeros((P, M + W), jnp.int32)
            all_cause = jnp.concatenate([cause, exp_cause], axis=1)
            all_ts = jnp.concatenate([pts, carry["exp_ts"]], axis=1)
            all_f = jnp.concatenate([pf, carry["exp_f"]], axis=1)
            all_i = jnp.concatenate([pi, carry["exp_i"]], axis=1)
            buf = _pack_egress(all_mask, all_idx, all_t, all_cause,
                               all_ts, all_f, all_i,
                               (jnp.max(nfill), jnp.max(post_exp_fill),
                                TS_NONE,
                                jnp.max(ovf.astype(jnp.int32))), cap,
                               telem_row=telem(nfill, all_mask, ovf))
            return new_carry, buf

        # ---------------- batch kinds ----------------
        # `directive` is [P, T] int32: the flush id each chunk row belongs
        # to (host-computed control state — next_emit / window_end);
        # `now` rides the per-lane count of flushes completed this step.
        if kind == "lengthBatch":
            n = spec.length
            batch_id = rank // n                        # tumbling blocks
            total = fill[:, None] + nv[:, None]
            n_done = (fill + nv) // n
            flushed = live & (batch_id < n_done[:, None])
            # exp state follows the LAST flushed batch (always non-empty)
            last_id = n_done - 1
        elif kind in ("timeBatch", "externalTimeBatch"):
            batch_id = jnp.concatenate(
                [jnp.zeros((P, W), jnp.int32), directive], axis=1)
            n_done = now.astype(jnp.int32)
            flushed = live & (batch_id < n_done[:, None])
            if kind == "timeBatch":
                # expired_batch = the last flush's batch even when empty
                # (TimeBatchWindowProcessor._flush assigns unconditionally)
                last_id = n_done - 1
            else:
                # expired_batch only replaced by a NON-EMPTY batch
                # (ExternalTimeBatchWindowProcessor._flush quirk)
                last_id = jnp.max(jnp.where(flushed, batch_id, -1),
                                  axis=1)
        else:                                           # batch()
            # whole chunk replaces the ring; the previous ring emits as
            # the expired batch (no separate exp plane needed)
            has_ev = (nv > 0)[:, None]
            emit = live & ((is_carry & has_ev) | ~is_carry)
            cause = jnp.where(is_carry, C_EXPBATCH,
                              C_BATCH).astype(jnp.int32)
            keep = live & (~is_carry | (is_carry & ~has_ev))
            sf, si, sts, nfill, ovf = _new_ring(pf, pi, pts, keep, rank,
                                                W, F, I)
            new_carry.update(ring_f=sf, ring_i=si, ring_ts=sts,
                             fill=nfill)
            buf = _pack_egress(emit, j, jnp.zeros((P, M), jnp.int32),
                               cause, pts, pf, pi,
                               (jnp.max(nfill), jnp.int32(0), TS_NONE,
                                jnp.max(ovf.astype(jnp.int32))), cap,
                               telem_row=telem(nfill, emit, ovf))
            return new_carry, buf

        cause = jnp.full((P, M), C_BATCH, jnp.int32)
        keep = live & ~flushed
        in_last = flushed & (batch_id == last_id[:, None]) & \
            (last_id >= 0)[:, None]
        sf, si, sts, nfill, ovf = _new_ring(pf, pi, pts, keep, rank,
                                            W, F, I)
        ef, ei, ets_, efill, eovf = _new_ring(pf, pi, pts, in_last,
                                              rank, W, F, I)
        any_flush = n_done > 0
        post_exp_fill = jnp.where(any_flush, efill, carry["exp_fill"])
        new_carry.update(
            ring_f=sf, ring_i=si, ring_ts=sts, fill=nfill,
            exp_f=jnp.where(any_flush[:, None, None], ef,
                            carry["exp_f"]),
            exp_i=jnp.where(any_flush[:, None, None], ei,
                            carry["exp_i"]),
            exp_ts=jnp.where(any_flush[:, None], ets_, carry["exp_ts"]),
            exp_fill=post_exp_fill)
        # carried expired-batch rows ride the exp plane region: reuse the
        # pool layout by emitting them with pool_idx offset M (host maps
        # idx >= M to the exp plane)
        eslot = jnp.arange(W)[None, :]
        exp_emit = (eslot < carry["exp_fill"][:, None]) & \
            any_flush[:, None]
        exp_cause = jnp.full((P, W), C_EXPBATCH, jnp.int32)
        all_mask = jnp.concatenate([flushed, exp_emit], axis=1)
        all_idx = jnp.concatenate([j, M + eslot], axis=1)
        all_t = jnp.concatenate([batch_id, jnp.zeros((P, W), jnp.int32)],
                                axis=1)
        all_cause = jnp.concatenate([cause, exp_cause], axis=1)
        all_ts = jnp.concatenate([pts, carry["exp_ts"]], axis=1)
        all_f = jnp.concatenate([pf, carry["exp_f"]], axis=1)
        all_i = jnp.concatenate([pi, carry["exp_i"]], axis=1)
        buf = _pack_egress(all_mask, all_idx, all_t, all_cause, all_ts,
                           all_f, all_i,
                           (jnp.max(nfill), jnp.max(post_exp_fill), TS_NONE,
                            jnp.max((ovf | eovf).astype(jnp.int32))),
                           cap, telem_row=telem(nfill, all_mask,
                                                ovf | eovf))
        return new_carry, buf

    return step
