"""Batched NFA step kernel — the TPU pattern-matching hot path.

This replaces the reference's per-event, per-partial-match Java loop
(query/input/stream/state/StreamPreStateProcessor.java:292-337 — a linked
list of partial matches stepped one event at a time under a ReentrantLock)
with a dense tensor program:

    state:    slot_state [P, K] int32   — next condition each partial waits on
              slot_start [P, K] int32   — first-capture timestamp (within)
              captures   [P, K, S, C]   — captured attribute lanes per state
    events:   [P, T] time-major blocks, one independent lane per partition

    step = lax.scan over T  ∘  vmap over P  ∘  (condition gate + advance)

All K partial slots of all P partitions evaluate their pending condition
against the incoming event in one vectorised pass; advancing slots write
capture lanes; slots completing state S-1 emit matches into a per-step match
buffer.  Partition lanes are fully independent, so the P axis shards over an
ICI mesh with jax.sharding (see parallel/mesh.py) with zero collectives on
the hot path.

Semantics covered (PATTERN type, the reference's non-strict mode):
`every c0 -> c1 -> ... -> c_{S-1} within t` chains, per-state filters that
may reference earlier captures (e.g. ``e2=S[price > e1.price]``), multiple
input streams (per-state stream gating), slot-ring eviction by `within`
expiry.  Conformance vs the host oracle is asserted in
tests/test_tpu_nfa.py.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

NO_SLOT = jnp.int32(-1)


class NfaSpec(NamedTuple):
    """Compiled NFA structure (built by plan/nfa_compiler.py)."""
    n_states: int
    n_caps: int                       # capture lanes per state
    n_slots: int                      # K: max concurrent partials
    within_ms: Optional[int]
    state_streams: np.ndarray         # [S] int32 — stream code per state
    # cond_fns[j](event_cols: {attr: scalar}, captures: [K, S, C]) -> [K] bool
    cond_fns: List[Callable]
    # cap_cols[j]: attr names captured into lanes for state j (≤ C)
    cap_cols: List[List[str]]
    attr_names: List[str]             # event column order
    is_every: bool
    # leading kleene state `A<m:n>` (reference CountPre/PostStateProcessor):
    # one accumulator lane per partition counts condition-0 matches and
    # spawns a slot at state 1 when min is reached; first/last capture banks
    # serve e1[0].x / e1[last].x.  None → plain chain.
    count0_min: Optional[int] = None
    count0_max: Optional[int] = None
    n_first_lanes: int = 0            # lanes 0..n-1 = first-occurrence bank


def make_carry(spec: NfaSpec, n_partitions: int) -> Dict[str, jnp.ndarray]:
    P, K, S, C = n_partitions, spec.n_slots, spec.n_states, spec.n_caps
    carry = {
        "slot_state": jnp.full((P, K), -1, jnp.int32),
        "slot_start": jnp.zeros((P, K), jnp.int32),
        "captures": jnp.zeros((P, K, S, max(C, 1)), jnp.float32),
        "dropped": jnp.zeros((P,), jnp.int32),   # slot-overflow counter
    }
    if spec.count0_min is not None:
        carry["acc_ctr"] = jnp.zeros((P,), jnp.int32)
        carry["acc_caps"] = jnp.zeros((P, max(C, 1)), jnp.float32)
        carry["acc_ts"] = jnp.zeros((P,), jnp.int32)
        # a PATTERN leading-kleene chain is single-shot: the one initial
        # partial accumulates, forwards exactly at min, and dies at max or
        # on within expiry — PATTERN start states are never re-initialised
        # (StreamPreStateProcessor.resetState runs only for SEQUENCE) and
        # the `every` re-arm clone can never re-reach min
        carry["acc_dead"] = jnp.zeros((P,), jnp.bool_)
    if not spec.is_every:
        carry["armed_total"] = jnp.zeros((P,), jnp.int32)
    return carry


def _one_partition_step(spec: NfaSpec, carry: Dict, event):
    """Step one partition's slot ring over one event.

    carry: slot_state [K], slot_start [K], captures [K, S, C], dropped []
           (+ acc_ctr/acc_caps/acc_ts for a leading kleene state)
    event: cols dict of scalars + ts + stream_code + valid
    returns (new_carry, (match_mask [K], match_caps [K, S, C], match_ts [K]))
    """
    K = spec.n_slots
    S = spec.n_states
    C = max(spec.n_caps, 1)
    slot_state = carry["slot_state"]
    slot_start = carry["slot_start"]
    captures = carry["captures"]
    dropped = carry["dropped"]
    ts = event["__ts"]
    valid = event["__valid"]
    stream = event["__stream"]

    active = slot_state >= 0

    # within expiry (reference isExpired :104-113)
    if spec.within_ms is not None:
        expired = active & (ts - slot_start > spec.within_ms)
        slot_state = jnp.where(expired, -1, slot_state)
        active = slot_state >= 0

    ev_caps = _event_capture_matrix(spec, event)          # [S, C]
    out_carry = {}

    # --- leading kleene: append to the accumulator BEFORE evaluating later
    # conditions (the reference's count pre-state runs first in unit order,
    # and the chain object is shared with slots waiting on later states) ---
    if spec.count0_min is not None:
        acc_ctr = carry["acc_ctr"]
        acc_caps = carry["acc_caps"]
        acc_ts = carry["acc_ts"]
        acc_dead = carry["acc_dead"]
        if spec.within_ms is not None:
            acc_dead = acc_dead | \
                ((acc_ctr > 0) & (ts - acc_ts > spec.within_ms))
        # condition 0 never reads captures → uniform over K; take lane 0
        c0 = valid & (stream == spec.state_streams[0]) & ~acc_dead & \
            spec.cond_fns[0](event, captures)[0]
        ctr2 = jnp.where(c0, acc_ctr + 1, acc_ctr)
        fresh = c0 & (ctr2 == 1)
        lane_is_last = jnp.arange(C) >= spec.n_first_lanes
        acc_caps = jnp.where(
            fresh | (c0 & lane_is_last), ev_caps[0], acc_caps)
        acc_ts = jnp.where(fresh, ts, acc_ts)
        # live last-bank append under the armed slot while the chain grows
        # (the reference shares one StateEvent object between the kleene
        # chain and the next state's pending list)
        wl = (c0 & (slot_state == 1))[:, None, None] & \
            (jnp.arange(S)[None, :, None] == 0) & \
            lane_is_last[None, None, :]
        captures = jnp.where(wl, ev_caps[0][None, None, :], captures)

    # evaluate every state's condition against this event for all K slots
    cond = jnp.stack([fn(event, captures) for fn in spec.cond_fns], axis=1)
    # [K, S] → gate each slot on its own pending state
    idx = jnp.clip(slot_state, 0, S - 1)
    slot_cond = jnp.take_along_axis(cond, idx[:, None], axis=1)[:, 0]
    stream_ok = jnp.asarray(spec.state_streams)[idx] == stream
    advance = active & stream_ok & slot_cond & valid

    # write captures for advancing slots at their pending state
    write = advance[:, None, None] & \
        (jnp.arange(S)[None, :, None] == idx[:, None, None])
    captures = jnp.where(write, ev_caps[None, :, :], captures)

    new_state = jnp.where(advance, slot_state + 1, slot_state)
    completed = advance & (new_state == S)

    match_mask = completed
    match_caps = captures
    match_ts = jnp.where(completed, ts, jnp.int32(0))

    # completed slots free up
    new_state = jnp.where(completed, -1, new_state)

    # --- arming a fresh partial (reference `every` re-arm / start init) ---
    if spec.count0_min is None:
        # condition 0 never reads captures, so row 0 of cond is uniform
        c0 = valid & (stream == spec.state_streams[0]) & cond[0, 0]
        arm = c0
        arm_caps0 = ev_caps[0]                 # [C]
        arm_ts = ts
    else:
        # reference CountPostStateProcessor: forward exactly at min count;
        # the chain keeps growing (NOT reset by the forward) and freezes at
        # max (stateChanged removes it) — arming is intrinsically single-shot
        arm = c0 & (ctr2 == spec.count0_min)
        hit_max = (c0 & (ctr2 == spec.count0_max)
                   if (spec.count0_max or 0) > 0 else jnp.bool_(False))
        out_carry["acc_ctr"] = ctr2
        out_carry["acc_caps"] = acc_caps
        out_carry["acc_ts"] = acc_ts
        out_carry["acc_dead"] = acc_dead | hit_max
        arm_caps0 = acc_caps
        arm_ts = acc_ts
    if not spec.is_every:
        # without `every` only the initial partial exists: first arm wins
        # (reference StreamPreStateProcessor.init + resetState guards)
        armed_total = carry["armed_total"]
        arm = arm & (armed_total == 0)
        out_carry["armed_total"] = armed_total + \
            jnp.where(arm, 1, 0)
    free = new_state < 0
    first_free = jnp.argmax(free)            # 0 if none free — guarded below
    any_free = jnp.any(free)
    do_arm = arm & any_free
    slot_iota = jnp.arange(K)
    armed_here = do_arm & (slot_iota == first_free)
    write0 = armed_here[:, None, None] & \
        (jnp.arange(S)[None, :, None] == 0)
    if S == 1:
        # single-state pattern: arming IS completion
        match_mask = match_mask | armed_here
        caps0 = jnp.where(write0, arm_caps0[None, None, :], captures)
        match_caps = jnp.where(armed_here[:, None, None], caps0, match_caps)
        match_ts = jnp.where(armed_here, ts, match_ts)
    else:
        new_state = jnp.where(armed_here, 1, new_state)
        slot_start = jnp.where(armed_here, arm_ts, slot_start)
        captures = jnp.where(write0, arm_caps0[None, None, :], captures)
    dropped = dropped + jnp.where(arm & ~any_free, 1, 0)

    out_carry.update({"slot_state": new_state, "slot_start": slot_start,
                      "captures": captures, "dropped": dropped})
    return out_carry, (match_mask, match_caps, match_ts)


def _event_capture_matrix(spec: NfaSpec, event) -> jnp.ndarray:
    """[S, C] capture lanes this event would write at each state."""
    S, C = spec.n_states, max(spec.n_caps, 1)
    rows = []
    for j in range(S):
        lanes = [event[a].astype(jnp.float32) for a in spec.cap_cols[j]]
        lanes += [jnp.float32(0)] * (C - len(lanes))
        rows.append(jnp.stack(lanes) if lanes else jnp.zeros((C,),
                                                             jnp.float32))
    return jnp.stack(rows)


def build_block_step(spec: NfaSpec):
    """Returns jittable fn(carry, block) → (carry, matches).

    block: dict of [P, T] arrays — per-partition event lanes, time-major
    scan; `__valid` masks padding.  matches: (mask [P, T, K],
    caps [P, T, K, S, C], ts [P, T, K]).
    """

    def per_partition(carry_p, events_p):
        # events_p: dict of [T] arrays for one partition
        def step(c, ev):
            return _one_partition_step(spec, c, ev)
        return jax.lax.scan(step, carry_p, events_p)

    def block_step(carry, block):
        # carry dict [P, ...]; block dict [P, T]
        new_carry, (mm, mc, mt) = jax.vmap(per_partition)(carry, block)
        return new_carry, (mm, mc, mt)

    return block_step


def build_bank_step(spec: NfaSpec):
    """N structurally-identical patterns (constants differ) × P partitions.

    Returns jittable fn(carry, block, params) → (carry, match_counts [N]):
      carry:  NFA carry with a leading pattern axis [N, P, ...]
      block:  one [P, T] event block, shared by every pattern
      params: {param_name: [N]} per-pattern constant lanes
    Match COUNTS only (the 1k-NFA fleet configs are alert-counting scale;
    full capture decode stays on the single-pattern path) — summing inside
    the scan keeps the [N, P, T, K] mask from materialising in HBM.
    """

    def per_partition(carry_p, events_p, prm):
        def step(c, ev):
            inner, acc = c
            inner2, (mm, _mc, _mt) = _one_partition_step(spec, inner,
                                                         {**ev, **prm})
            # accumulate in-carry: avoids a [N, P, T] stacked ys buffer
            return (inner2, acc + jnp.sum(mm.astype(jnp.int32))), None
        (c2, acc), _ = jax.lax.scan(step, (carry_p, jnp.int32(0)), events_p)
        return c2, acc

    def pattern_step(carry_n, prm, block):
        new_carry, counts = jax.vmap(
            per_partition, in_axes=(0, 0, None))(carry_n, block, prm)
        return new_carry, jnp.sum(counts)

    def bank_step(carry, block, params):
        return jax.vmap(pattern_step, in_axes=(0, 0, None))(carry, params,
                                                            block)

    return bank_step


def make_bank_carry(spec: NfaSpec, n_patterns: int,
                    n_partitions: int) -> Dict[str, jnp.ndarray]:
    c = make_carry(spec, n_partitions)
    return {k: jnp.broadcast_to(v[None], (n_patterns,) + v.shape)
            for k, v in c.items()}


def pack_blocks(partition_ids: np.ndarray, columns: Dict[str, np.ndarray],
                timestamps: np.ndarray, stream_codes: np.ndarray,
                n_partitions: int, base_ts: int = 0,
                pad_t_pow2: bool = False, return_rows: bool = False):
    """Host-side: scatter a flat event batch into dense [P, T] lanes
    (T = max events of any partition in the batch; padding masked invalid;
    pad_t_pow2 rounds T up to a power of two so jit sees few distinct
    shapes).  return_rows additionally yields each input event's row index
    within its lane (for per-event output decode).

    This is the columnar replacement for the reference's per-key junction
    routing (partition/PartitionStreamReceiver.java:83-153)."""
    from ..native_ext import assign_rows
    n = len(partition_ids)
    partition_ids = np.ascontiguousarray(partition_ids, np.int32)
    row, _counts, T = assign_rows(partition_ids, n_partitions)
    if pad_t_pow2:
        T = 1 << (T - 1).bit_length()
    block: Dict[str, np.ndarray] = {}
    for name, col in columns.items():
        out = np.zeros((n_partitions, T), np.float32)
        out[partition_ids, row] = col.astype(np.float32)
        block[name] = out
    ts = np.zeros((n_partitions, T), np.int32)
    ts[partition_ids, row] = (np.asarray(timestamps, np.int64) -
                              base_ts).astype(np.int32)
    block["__ts"] = ts
    sc = np.zeros((n_partitions, T), np.int32)
    sc[partition_ids, row] = stream_codes
    block["__stream"] = sc
    valid = np.zeros((n_partitions, T), bool)
    valid[partition_ids, row] = True
    block["__valid"] = valid
    if return_rows:
        return block, row
    return block
