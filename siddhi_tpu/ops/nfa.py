"""Batched NFA step kernel — the TPU pattern-matching hot path.

This replaces the reference's per-event, per-partial-match Java loop
(query/input/stream/state/StreamPreStateProcessor.java:292-337 — a linked
list of partial matches stepped one event at a time under a ReentrantLock)
with a dense tensor program:

    state:    slot_state [P, K] int32   — unit each partial slot waits on
              slot_start [P, K] int32   — first-capture timestamp (within)
              captures   [P, K, R, C]   — capture rows (one per unit side)
    events:   [P, T] time-major blocks, one independent lane per partition

    step = lax.scan over T  ∘  vmap over P  ∘  (condition gate + advance)

All K partial slots of all P partitions evaluate their pending condition
against the incoming event in one vectorised pass.  Partition lanes are
fully independent, so the P axis shards over an ICI mesh with jax.sharding
(see parallel/mesh.py) with zero collectives on the hot path.

The pattern algebra is a chain of *units* compiled by plan/nfa_compiler.py
(reference util/parser/StateInputStreamParser.java:76-404):

  - simple   one condition; advance on match
             (Stream Pre/PostStateProcessor)
  - count    kleene <m:n>: per-slot counter accumulates matches, forwards
             at min, keeps live-appending into the last-capture bank while
             the next unit is pending, freezes at max
             (CountPreStateProcessor.java:53-105, CountPostStateProcessor)
  - logical  and/or partner pair: two (stream, condition, capture-row)
             sides + a per-slot side bitmask
             (LogicalPreStateProcessor.java:57-92)
  - absent   `not X for t`: per-slot deadline lane; an arriving match
             kills the partial, deadline expiry (driven by real events or
             host-injected TIMER rows) confirms the absence and advances
             (AbsentStreamPreStateProcessor.java:63-96)

Both PATTERN (non-strict) and SEQUENCE (strict contiguity: a partial must
advance or append on every event or die — the reference's per-event
resetState/updateState barriers, StreamPreStateProcessor.java:263-290)
semantics are supported.  Conformance vs the host oracle (core/pattern.py)
is asserted in tests/test_tpu_nfa.py and tests/test_planner.py.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NO_SLOT = jnp.int32(-1)
COUNT_INF = 0x7FFFFFFF

#: B-event micro-batching of the scan chain (round 6).  The env value is
#: B itself: unset/empty → DEFAULT_BATCH_B; ``=1`` is the kill switch
#: (legacy one-event ticks, no hoisting — mirrors SIDDHI_TPU_NFA_PRUNE).
BATCH_ENV = "SIDDHI_TPU_NFA_BATCH"
DEFAULT_BATCH_B = 4


def resolve_batch_b(batch_b: Optional[int] = None) -> int:
    """Effective events-per-tick B: explicit argument wins, else the
    BATCH_ENV value, else DEFAULT_BATCH_B.  Anything < 1 (or
    unparseable) clamps to the legacy/default respectively."""
    if batch_b is None:
        raw = os.environ.get(BATCH_ENV, "").strip().lower()
        if raw in ("", "on", "true", "default"):
            return DEFAULT_BATCH_B
        try:
            return max(1, int(raw))
        except ValueError:
            return DEFAULT_BATCH_B
    return max(1, int(batch_b))


#: Chunk stacking (round 7): a bank of C homogeneous-shape pattern
#: chunks runs as ONE jitted super-dispatch (vmap over the chunk axis)
#: instead of C sequential device calls.  ``=0``/``off`` restores the
#: legacy sequential chunk loop.
STACK_ENV = "SIDDHI_TPU_NFA_STACK"


def resolve_stack(stack: Optional[bool] = None) -> bool:
    """Effective chunk-stacking switch: explicit argument wins, else the
    STACK_ENV value (default on; 0/false/off disables)."""
    if stack is None:
        raw = os.environ.get(STACK_ENV, "").strip().lower()
        return raw not in ("0", "false", "off", "no")
    return bool(stack)


class UnitSpec(NamedTuple):
    """One chain position (≙ one Pre/PostStateProcessor pair)."""
    kind: str                 # 'simple' | 'count' | 'logical' | 'absent'
    stream_a: int             # stream code of side A
    cond_a: int               # index into NfaSpec.cond_fns
    row_a: int                # capture row (-1: no captures, absent units)
    stream_b: int = -1        # logical pairs only
    cond_b: int = -1
    row_b: int = -1
    is_and: bool = False      # logical: and vs or
    min_count: int = 1        # count units
    max_count: int = 1
    waiting_ms: int = 0       # absent units


class NfaSpec(NamedTuple):
    """Compiled NFA structure (built by plan/nfa_compiler.py)."""
    units: Tuple[UnitSpec, ...]
    n_rows: int                       # capture rows
    n_caps: int                       # lanes per row (C)
    n_slots: int                      # K: max concurrent partials
    within_ms: Optional[int]
    # cond_fns[i](event_cols: {attr: scalar}, captures: [K, R, C]) -> [K]
    cond_fns: Tuple[Callable, ...]
    cap_cols: Tuple[Tuple[str, ...], ...]   # per row: first bank ++ last bank
    n_first: Tuple[int, ...]          # per row: #lanes in the first bank
    n_lane: Tuple[int, ...]           # per row: __n counter lane (-1: none)
    matched_lane: Tuple[int, ...]     # per row: __matched lane (-1: none)
    attr_names: Tuple[str, ...]       # event column order
    is_every: bool
    is_sequence: bool = False
    arm_once: bool = False            # single-shot arming
    every_group_end: int = 0          # last unit of the `every` re-arm group
    tail_every_start: int = -1        # first unit of a trailing `every`
    #                                   group: a completing partial re-arms
    #                                   there (captures intact) instead of
    #                                   dying — `A -> every B` semantics
    #                                   (StateInputStreamParser.java:272-273)
    mid_every: Tuple[Tuple[int, int], ...] = ()
    #                                   mid-chain `every` groups (g0, g1):
    #                                   a partial advancing OUT of g1 forks
    #                                   a clone that re-arms at g0 with its
    #                                   pre-group captures while the
    #                                   original advances (the reference's
    #                                   addEveryState clone,
    #                                   StreamPostStateProcessor.java:66-68)
    eps_start: bool = False           # leading min-0 kleene: unit 1 is an
    #                                   alternate start state (empty-kleene
    #                                   path), see _one_partition_step
    n_last: Tuple[int, ...] = ()      # per row: #lanes in the last bank
    idx_banks: Tuple = ()             # per row: ((k, start, len), ...) —
    #                                   e[k] banks, written when the kleene
    #                                   chain reaches k+1 elements
    lastk_banks: Tuple = ()           # per row: ((j, start), ...) — e[last-j]
    #                                   banks, shift chain behind the last
    #                                   bank on every append
    m_src: Tuple = ()                 # per row: last-bank source lanes for
    #                                   the shift chain (lane-aligned)
    lead_absent: bool = False         # `not A for t -> ...`: the start
    #                                   state is an absent unit — a partial
    #                                   with a deadline is kept armed at
    #                                   unit 0 (ensure-arm; arrivals kill +
    #                                   re-arm with a fresh deadline), the
    #                                   reference's AbsentStreamPreState
    #                                   Processor start/init/re-init loop
    dead_start: bool = False          # SEQUENCE leading kleene min >= 2:
    #                                   the per-event barrier clears every
    #                                   pending list and CountPost only
    #                                   re-adds at cnt >= min, so a sub-min
    #                                   accumulator never survives — the
    #                                   shape produces ZERO matches (oracle
    #                                   verified); arming is suppressed
    cond_free: Tuple[bool, ...] = ()  # per cond_fn: True when the program
    #                                   reads ONLY the current event (no
    #                                   captures, no __cnt lanes, no
    #                                   nullable-row gates) — eligible for
    #                                   block-wide hoisting out of the scan
    batch_b: int = 0                  # events consumed per scan tick (the
    #                                   compiler pins resolve_batch_b();
    #                                   0 → resolve from env at build time,
    #                                   1 → legacy one-event ticks)
    telemetry: bool = False           # @app:statistics(telemetry='true'):
    #                                   accumulate an int32 telemetry leaf
    #                                   (per-state occupancy, gate
    #                                   pass/fail, within-expiry drops) in
    #                                   the carry — read out through the
    #                                   fused egress slab; MUST leave match
    #                                   outputs bit-identical

    @property
    def n_states(self) -> int:
        return len(self.units)


def _has(spec: NfaSpec, kind: str) -> bool:
    return any(u.kind == kind for u in spec.units)


def _land_static(spec: NfaSpec, j_from: int):
    """Where a slot advancing out of unit j_from ends up.

    Returns (target, live0, completed): `live0` marks an epsilon-skipped
    min-0 count unit at target-1 that keeps live-appending
    (CountPreStateProcessor.addState min==0 branch); `completed` means the
    chain is done and the advance emits a match."""
    S = len(spec.units)
    t = j_from + 1
    live0 = False
    if t < S and spec.units[t].kind == "count" and \
            spec.units[t].min_count == 0:
        live0 = True
        t += 1
    return t, live0, t >= S


def make_carry(spec: NfaSpec, n_partitions: int) -> Dict[str, jnp.ndarray]:
    # NOTE: the static cost model (analysis/cost_model.nfa_state_bytes)
    # mirrors these shapes closed-form and is asserted BYTE-EXACT against
    # the arrays allocated here (tests/test_plan_verify.py) — adding or
    # resizing a carry array must update both, or that test fails.
    P, K = n_partitions, spec.n_slots
    R, C = max(spec.n_rows, 1), max(spec.n_caps, 1)
    carry = {
        "slot_state": jnp.full((P, K), -1, jnp.int32),
        "slot_start": jnp.zeros((P, K), jnp.int32),
        # ts the slot entered its current unit + per-partition arm sequence
        # — together they reproduce the oracle's pending-list insertion
        # order for same-event completions
        "slot_enter": jnp.zeros((P, K), jnp.int32),
        "slot_seq": jnp.zeros((P, K), jnp.int32),
        "arm_seq": jnp.zeros((P,), jnp.int32),
        "captures": jnp.zeros((P, K, R, C), jnp.float32),
        "dropped": jnp.zeros((P,), jnp.int32),   # slot-overflow counter
    }
    if _has(spec, "count"):
        carry["cnt_cur"] = jnp.zeros((P, K), jnp.int32)
        carry["cnt_prev"] = jnp.full((P, K), -1, jnp.int32)
    if spec.eps_start and spec.is_sequence:
        # 1 when the leading kleene froze at max on the previous event:
        # the oracle's fresh virgin then finds the next unit's new-list
        # still holding the frozen partial and is closer-blocked for its
        # creation event (CountPre addState SEQUENCE empty-list guard)
        carry["seq_froze"] = jnp.zeros((P,), jnp.int32)
    if _has(spec, "logical"):
        carry["lmask"] = jnp.zeros((P, K), jnp.int32)
    if _has(spec, "absent"):
        carry["deadline"] = jnp.zeros((P, K), jnp.int32)
    if spec.arm_once:
        carry["armed_total"] = jnp.zeros((P,), jnp.int32)
    if spec.telemetry:
        # [occ[S] (gauge) ‖ gate_pass[S] ‖ gate_fail[S] ‖ within_drops]
        carry["telem"] = jnp.zeros((P, 3 * len(spec.units) + 1), jnp.int32)
    return carry


def _event_rows(spec: NfaSpec, event) -> jnp.ndarray:
    """[R, C] matrix of the lanes this event would write into each row
    (__matched lanes read 1.0; __n lanes are patched per-slot later)."""
    R, C = max(spec.n_rows, 1), max(spec.n_caps, 1)
    rows = []
    for r in range(R):
        cols = spec.cap_cols[r] if r < len(spec.cap_cols) else ()
        lanes = [event[a].astype(jnp.float32) if a in event
                 else jnp.float32(1.0)          # __matched / __n defaults
                 for a in cols]
        lanes += [jnp.float32(0)] * (C - len(lanes))
        rows.append(jnp.stack(lanes) if lanes
                    else jnp.zeros((C,), jnp.float32))
    return jnp.stack(rows)


def _gate_key(i: int) -> str:
    """Event-dict column carrying cond i's hoisted block-wide gate."""
    return f"__gate_{i}"


def _eval_conds(spec: NfaSpec, event, caps) -> List[jnp.ndarray]:
    """Per-cond [K] booleans for one event.

    Hoisted conditions (capture-free, precomputed for the whole block by
    ``_hoist_cond_gates``) read their scalar gate straight from the event
    dict — the scan body then carries only the truly sequential masked
    state update; everything else evaluates its program against the
    current captures exactly as before."""
    K = caps.shape[0]
    conds = []
    for i, fn in enumerate(spec.cond_fns):
        key = _gate_key(i)
        if key in event:
            conds.append(jnp.broadcast_to(event[key], (K,)))
        else:
            conds.append(fn(event, caps))
    return conds


def _cond_on(spec: NfaSpec, event, cond_id: int, caps) -> jnp.ndarray:
    """One condition against an explicit capture context (the virgin
    zero-caps re-arm/seed sites).  A hoisted gate IS fn(event, zeros) by
    construction, so it substitutes exactly."""
    key = _gate_key(cond_id)
    if key in event:
        return event[key]
    return spec.cond_fns[cond_id](event, caps)[0]


def _hoist_cond_gates(spec: NfaSpec, events_p: Dict[str, jnp.ndarray],
                      extra: Optional[Dict[str, jnp.ndarray]] = None
                      ) -> Dict[str, jnp.ndarray]:
    """Evaluate every capture-free condition for a whole [T] event lane in
    ONE vectorized pass outside the scan → {__gate_i: [T] bool} columns.

    Capture-free programs never read the slot captures (spec.cond_free,
    proven statically by plan/nfa_compiler), so evaluating them against a
    zero capture context is exact and uniform over K.  `extra` carries
    per-pattern parameter scalars in bank mode."""
    free = [i for i, f in enumerate(spec.cond_free) if f]
    if not free:
        return {}
    R, C = max(spec.n_rows, 1), max(spec.n_caps, 1)
    zero_caps = jnp.zeros((1, R, C), jnp.float32)

    def one(ev):
        if extra:
            ev = {**ev, **extra}
        return jnp.stack([jnp.asarray(spec.cond_fns[i](ev, zero_caps)[0],
                                      bool) for i in free])
    g = jax.vmap(one)(events_p)                  # [T, n_free]
    return {_gate_key(i): g[:, j] for j, i in enumerate(free)}


def _pad_block_t(events_p: Dict[str, jnp.ndarray], batch_b: int):
    """Pad the time axis up to a batch_b multiple.  Padding rows are
    invalid (__valid False — every transition/arm is gated on it) and
    repeat the LAST event's timestamp, so the only unconditional per-tick
    pass (within expiry) re-runs at a time it already ran at and kills
    nothing new: the carry stays bit-identical to the unpadded scan."""
    T = int(events_p["__ts"].shape[0])
    ticks = -(-T // batch_b) if T else 0
    pad = ticks * batch_b - T
    if not pad:
        return events_p, T, ticks

    def pad_leaf(name, v):
        if name == "__ts":
            fill = jnp.broadcast_to(v[T - 1], (pad,))
        else:
            fill = jnp.zeros((pad,) + v.shape[1:], v.dtype)
        return jnp.concatenate([v, fill], axis=0)
    return ({k: pad_leaf(k, v) for k, v in events_p.items()}, T, ticks)


class _StepState:
    """Mutable per-event slot arrays threaded through the unit loop."""

    def __init__(self, spec: NfaSpec, carry: Dict, K: int):
        self.spec = spec
        self.st = carry["slot_state"]
        self.start = carry["slot_start"]
        self.enter = carry["slot_enter"]
        self.seq = carry["slot_seq"]
        self.arm_seq = carry["arm_seq"]
        self.caps = carry["captures"]
        self.dropped = carry["dropped"]
        self.cnt_cur = carry.get("cnt_cur")
        self.cnt_prev = carry.get("cnt_prev")
        self.seq_froze = carry.get("seq_froze")
        self.lmask = carry.get("lmask")
        self.deadline = carry.get("deadline")
        self.armed_total = carry.get("armed_total")
        self.m_mask = jnp.zeros((K,), bool)
        self.m_ts = jnp.zeros((K,), jnp.int32)
        self.m_enter = jnp.zeros((K,), jnp.int32)
        self.m_seq = jnp.zeros((K,), jnp.int32)
        # captures snapshotted AT COMPLETION — a trailing-every re-arm may
        # clear group rows in the live slot after the match is recorded
        R, C = self.caps.shape[1], self.caps.shape[2]
        self.m_caps = jnp.zeros((K, R, C), jnp.float32)
        # mid-chain `every` clone requests collected during land():
        # group start → (source mask, source rank by pre-land (enter, seq))
        self.spawn: Dict[int, Tuple[jnp.ndarray, jnp.ndarray]] = {}

    def _pending_rank(self, pred):
        """Rank `pred` slots by their pending-list order (enter, seq) —
        the oracle's append order for re-arm clones and fork clones."""
        e, sq = self.enter, self.seq
        less = (e[None, :] < e[:, None]) | \
            ((e[None, :] == e[:, None]) & (sq[None, :] < sq[:, None]))
        return jnp.sum(pred[None, :] & less, axis=1)

    def _clear_group_logical_rows(self, caps, sel_or_range, g0, g1):
        """Zero the logical-side capture rows of units[g0..g1] — the
        oracle's re-arm/fork clone clears LOGICAL sides (addEveryState);
        simple rows are overwritten on the next match and stay.
        sel_or_range: [K] bool (applied per-slot) or None (whole array)."""
        spec = self.spec
        log_rows = [r for u in spec.units[g0:g1 + 1]
                    for r in (u.row_a, u.row_b)
                    if u.kind == "logical" and r >= 0]
        if not log_rows:
            return caps
        R = caps.shape[-2]
        rm = np.zeros((R,), bool)
        rm[log_rows] = True
        mask = jnp.asarray(rm)[None, :, None]
        if sel_or_range is not None:
            mask = sel_or_range[:, None, None] & mask
        return jnp.where(mask, jnp.float32(0), caps)

    def land(self, pred, j_from: int, base_ts, fwd_cnt=None, fwd_dead=None):
        """Advance `pred` slots out of unit j_from at time base_ts.

        fwd_cnt: forwarded count for count-unit exits (stays live unless
        fwd_dead).  base_ts may be scalar (event ts) or [K] (deadlines)."""
        spec = self.spec
        t, live0, completed = _land_static(spec, j_from)
        for g0, g1 in spec.mid_every:
            if j_from == g1:
                # fork request: rank sources by pre-land pending order so
                # the clones append in oracle order (see alloc_clones)
                rank = self._pending_rank(pred)
                old_m, old_r = self.spawn.get(g0, (None, None))
                if old_m is not None:       # a second land on the same g1
                    rank = rank + jnp.sum(old_m.astype(jnp.int32))
                    pred_all = old_m | pred
                    rank = jnp.where(pred, rank, old_r)
                    self.spawn[g0] = (pred_all, rank)
                else:
                    self.spawn[g0] = (pred, rank)
        if completed:
            self.m_mask = self.m_mask | pred
            self.m_ts = jnp.where(pred, base_ts, self.m_ts)
            self.m_caps = jnp.where(pred[:, None, None], self.caps,
                                    self.m_caps)
            # oracle emission order for same-event completions follows the
            # last unit's pending-list insertion order
            self.m_enter = jnp.where(pred, self.enter, self.m_enter)
            self.m_seq = jnp.where(pred, self.seq, self.m_seq)
            if spec.tail_every_start >= 0:
                # trailing `every`: the match is emitted AND the partial
                # re-arms at the group start, keeping its pre-group
                # captures (the reference's nextEveryStatePreProcessor
                # loop, StreamPostStateProcessor.java:66-68); group-side
                # captures are overwritten by the next firing
                te = spec.tail_every_start
                self.st = jnp.where(pred, te, self.st)
                # the oracle APPENDS re-armed clones to the pending list in
                # emission order, so future same-ts ties must rank them
                # after older entries and in their prior pending order:
                # fresh seq = counter + rank by prior (enter, seq)
                rank = self._pending_rank(pred)
                self.seq = jnp.where(pred, self.arm_seq + rank, self.seq)
                self.arm_seq = self.arm_seq + \
                    jnp.sum(pred.astype(jnp.int32))
                self.enter = jnp.where(pred, base_ts, self.enter)
                if self.lmask is not None:
                    self.lmask = jnp.where(pred, 0, self.lmask)
                self.caps = self._clear_group_logical_rows(
                    self.caps, pred, te, len(spec.units) - 1)
                # count units are compile-rejected alongside trailing
                # every; pre-group absent deadlines are never revisited
            else:
                self.st = jnp.where(pred, -1, self.st)
            if live0 and self.cnt_prev is not None:
                # trailing min-0 count: match emitted on arrival, slot dies
                pass
            return
        self.st = jnp.where(pred, t, self.st)
        self.enter = jnp.where(pred, base_ts, self.enter)
        if self.lmask is not None:
            self.lmask = jnp.where(pred, 0, self.lmask)
        if self.cnt_prev is not None:
            if fwd_cnt is not None:
                dead = fwd_dead if fwd_dead is not None else \
                    jnp.zeros_like(pred)
                self.cnt_prev = jnp.where(
                    pred, jnp.where(dead, -1, fwd_cnt), self.cnt_prev)
            elif live0:
                self.cnt_prev = jnp.where(pred, 0, self.cnt_prev)
            else:
                self.cnt_prev = jnp.where(pred, -1, self.cnt_prev)
            self.cnt_cur = jnp.where(pred, 0, self.cnt_cur)
        if spec.units[t].kind == "absent":
            self.deadline = jnp.where(
                pred, base_ts + spec.units[t].waiting_ms, self.deadline)

    def write_all(self, pred, row: int, ev_rows):
        """Write every lane of `row` for `pred` slots."""
        if row < 0:
            return
        R = self.caps.shape[1]
        sel = pred[:, None, None] & \
            (jnp.arange(R)[None, :, None] == row)
        self.caps = jnp.where(sel, ev_rows[row][None, None, :], self.caps)

    def write_count(self, pred_first, pred_last, row: int, ev_rows, new_n):
        """Count-row append: first bank on the first append, last bank +
        __n lane on every append; e[last-j] banks shift behind the last
        bank (deepest first, BEFORE the new value lands) and e[k] banks
        capture the append that brings the chain to k+1 elements."""
        if row < 0:
            return
        spec = self.spec
        R, C = self.caps.shape[1], self.caps.shape[2]
        lane = jnp.arange(C)
        nf = spec.n_first[row]
        first_lanes = lane < nf
        nl = spec.n_lane[row]
        n_l = spec.n_last[row] if spec.n_last else 0
        last_lanes = (lane >= nf) & (lane < nf + n_l) & \
            ((lane != nl) if nl >= 0 else True)
        row_sel = (jnp.arange(R)[None, :, None] == row)
        ev = ev_rows[row][None, None, :]
        mb = spec.lastk_banks[row] if spec.lastk_banks else ()
        src = spec.m_src[row] if spec.m_src else ()
        if mb and src:
            L = len(src)
            starts = {j: st for (j, st) in mb}
            for j, start in sorted(mb, reverse=True):
                src_lanes = np.asarray(
                    src if j == 1
                    else range(starts[j - 1], starts[j - 1] + L),
                    np.int32)
                dst_lanes = np.asarray(range(start, start + L), np.int32)
                vals = self.caps[:, row, src_lanes]
                cur = self.caps[:, row, dst_lanes]
                self.caps = self.caps.at[:, row, dst_lanes].set(
                    jnp.where(pred_last[:, None], vals, cur))
        self.caps = jnp.where(
            pred_first[:, None, None] & row_sel & first_lanes[None, None, :],
            ev, self.caps)
        self.caps = jnp.where(
            pred_last[:, None, None] & row_sel & last_lanes[None, None, :],
            ev, self.caps)
        for (k, start, ln) in (spec.idx_banks[row]
                               if spec.idx_banks else ()):
            predk = pred_last & (new_n == k + 1)
            sel = (lane >= start) & (lane < start + ln)
            self.caps = jnp.where(
                predk[:, None, None] & row_sel & sel[None, None, :],
                ev, self.caps)
        if nl >= 0:
            nsel = pred_last[:, None, None] & row_sel & \
                (lane == nl)[None, None, :]
            self.caps = jnp.where(
                nsel, new_n.astype(jnp.float32)[:, None, None], self.caps)

    def clear_slot(self, pred):
        self.caps = jnp.where(pred[:, None, None],
                              jnp.float32(0), self.caps)

    def alloc_clones(self, g0: int, spawn, rank, ts):
        """Fork mid-chain `every` clones: for each source slot in `spawn`,
        place a new partial at unit g0 carrying the source's captures
        (group-side logical rows cleared — the oracle's addEveryState
        clone) and chain-start timestamp (within runs from the original
        first event).  Sources ranked by pre-land pending order fill free
        slots in that order; unplaceable clones count as drops (the
        engine's grow-and-replay reruns the chunk on a bigger ring)."""
        spec = self.spec
        K = spawn.shape[0]
        n_spawn = jnp.sum(spawn.astype(jnp.int32))
        free = (self.st < 0) & ~self.m_mask
        free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
        by_rank = jnp.zeros((K,), jnp.int32).at[
            jnp.where(spawn, rank, K)].set(jnp.arange(K, dtype=jnp.int32),
                                           mode="drop")
        src = by_rank[jnp.clip(free_rank, 0, K - 1)]
        fill = free & (free_rank < n_spawn)
        self.st = jnp.where(fill, g0, self.st)
        self.start = jnp.where(fill, self.start[src], self.start)
        caps_src = self.caps[src]
        g1 = next(g1 for (s0, g1) in spec.mid_every if s0 == g0)
        caps_src = self._clear_group_logical_rows(caps_src, None, g0, g1)
        self.caps = jnp.where(fill[:, None, None], caps_src, self.caps)
        self.enter = jnp.where(fill, ts, self.enter)
        self.seq = jnp.where(fill, self.arm_seq + free_rank, self.seq)
        self.arm_seq = self.arm_seq + n_spawn
        self.dropped = self.dropped + \
            jnp.maximum(n_spawn - jnp.sum(free.astype(jnp.int32)), 0)
        if self.lmask is not None:
            self.lmask = jnp.where(fill, 0, self.lmask)
        if self.cnt_cur is not None:
            self.cnt_cur = jnp.where(fill, 0, self.cnt_cur)
            self.cnt_prev = jnp.where(fill, -1, self.cnt_prev)


def _one_partition_step(spec: NfaSpec, carry: Dict, event):
    """Step one partition's slot ring over one event.

    event: cols dict of scalars + __ts/__stream/__valid
    returns (new_carry, (match_mask [K], match_caps [K, R, C],
    match_ts [K]))"""
    units = spec.units
    S = len(units)
    K = spec.n_slots
    ts = event["__ts"]
    valid = event["__valid"]
    stream = event["__stream"]

    s = _StepState(spec, carry, K)

    # telemetry leaf rides the carry untouched by the match math: every
    # contribution below is a NEW reduction over masks the transition
    # logic already computes, so match outputs stay bit-identical
    tel = carry.get("telem") if spec.telemetry else None
    tel_exp = jnp.int32(0)

    # ---- within expiry (reference isExpired :104-113 — start-state
    # partials are exempt: a half-filled leading pair or accumulating
    # kleene start never expires, only later units enforce `within`)
    if spec.within_ms is not None:
        expired = (s.st >= 1) & (ts - s.start > spec.within_ms)
        if spec.eps_start:
            # the empty-kleene start partial (leading min-0) sits at unit
            # 1 but IS a start-state partial — exempt
            expired = expired & ~((s.st == 1) & (s.cnt_prev == 0))
        if tel is not None:
            tel_exp = jnp.sum(expired.astype(jnp.int32))
        s.st = jnp.where(expired, -1, s.st)

    # ---- leading absent ensure-arm: the oracle re-initializes the start
    # absent partial whenever its pending list is empty (absent_tick
    # initialize + init_start), so exactly one partial waits at unit 0
    # with a live deadline; arrivals below kill + re-arm it in place
    if spec.lead_absent:
        # REAL events only: the oracle's ticks stop after a successful
        # confirmation until an arrival (or fresh scheduling) restarts
        # them — re-arming on an injected TIMER row would chain
        # confirmations the reference never produces
        have0 = jnp.any(s.st == 0)
        want0 = valid & (stream != -2) & ~have0
        free0 = (s.st < 0) & ~s.m_mask
        armed0 = (want0 & jnp.any(free0)) & \
            (jnp.arange(K) == jnp.argmax(free0))
        s.clear_slot(armed0)
        s.st = jnp.where(armed0, 0, s.st)
        s.deadline = jnp.where(armed0, ts + spec.units[0].waiting_ms,
                               s.deadline)
        s.start = jnp.where(armed0, ts, s.start)
        s.enter = jnp.where(armed0, ts, s.enter)
        s.seq = jnp.where(armed0, s.arm_seq, s.seq)
        s.arm_seq = s.arm_seq + jnp.where(jnp.any(armed0), 1, 0)
        if s.lmask is not None:
            s.lmask = jnp.where(armed0, 0, s.lmask)
        if s.cnt_cur is not None:
            s.cnt_cur = jnp.where(armed0, 0, s.cnt_cur)
            s.cnt_prev = jnp.where(armed0, -1, s.cnt_prev)
        s.dropped = s.dropped + jnp.where(want0 & ~jnp.any(free0), 1, 0)

    # ---- SEQUENCE early deadline pass: the playback scheduler fires a
    # deadline that coincides with (or precedes) an event's timestamp
    # BEFORE that event stabilizes the sequence — a due `not … for t`
    # confirms the absence even though the arriving event would clear the
    # pending list (see the stabilize barrier below); fired slots advance
    # and may consume THIS event at their new unit
    if spec.is_sequence and _has(spec, "absent"):
        for j, u in enumerate(spec.units):
            if u.kind != "absent":
                continue
            fire = valid & (s.st == j) & (s.deadline <= ts)
            s.land(fire, j, s.deadline)

    # ---- SEQUENCE stabilize barrier for absent units: the oracle clears
    # every unit's pending list BEFORE each real event (stabilizeStates →
    # resetState), so a partial waiting at a `not … for t` unit survives
    # only an event-free gap — any arriving event (even a non-matching
    # one) breaks the sequence before the deadline could fire.  Timer
    # rows (stream -2) do not stabilize.
    if spec.is_sequence and _has(spec, "absent"):
        absent_u = np.asarray([u.kind == "absent" for u in spec.units] +
                              [False], bool)
        at_absent = jnp.asarray(absent_u)[jnp.clip(s.st, 0, S)]
        kill0 = valid & (stream != -2) & (s.st >= 0) & at_absent
        s.st = jnp.where(kill0, -1, s.st)

    # ---- leading min-0 kleene: the start partial lives at unit 1 with an
    # empty, live-appending kleene chain (the reference parks the shared
    # StateEvent in BOTH the count's and the successor's pending lists —
    # epsilon closure at arm time).  Ensure exactly one such virgin
    # (cnt_prev == 0) exists; re-created here after the previous one
    # advanced (every mode) — eligible from this event on
    if spec.eps_start:
        # exactly one start chain: unit 1 is only ever occupied by the
        # shared start StateEvent (virgin, accumulating, or frozen at
        # max) — the reference start partial sits in BOTH the count's and
        # the successor's pending lists, never duplicated; re-init only
        # after it advances out
        if spec.is_sequence:
            # the oracle re-inits whenever the start's new-list is empty:
            # a LIVE chain (appending, cnt_prev >= 0) occupies it, a
            # frozen-at-max chain (cnt_prev == -1) does not — the frozen
            # partial keeps waiting at unit 1 while a fresh virgin arms
            have = jnp.any((s.st == 1) & (s.cnt_prev >= 0))
        else:
            have = jnp.any(s.st == 1)
        want = valid & ~have
        if spec.arm_once:
            want = want & (s.armed_total == 0)
        freev = (s.st < 0) & ~s.m_mask
        armed_v = (want & jnp.any(freev)) & \
            (jnp.arange(K) == jnp.argmax(freev))
        s.clear_slot(armed_v)
        s.st = jnp.where(armed_v, 1, s.st)
        s.cnt_cur = jnp.where(armed_v, 0, s.cnt_cur)
        s.cnt_prev = jnp.where(armed_v, 0, s.cnt_prev)
        s.start = jnp.where(armed_v, ts, s.start)
        s.enter = jnp.where(armed_v, ts, s.enter)
        s.seq = jnp.where(armed_v, s.arm_seq, s.seq)
        s.arm_seq = s.arm_seq + jnp.where(jnp.any(armed_v), 1, 0)
        if s.lmask is not None:
            s.lmask = jnp.where(armed_v, 0, s.lmask)
        if spec.arm_once:
            s.armed_total = s.armed_total + \
                jnp.where(want & jnp.any(freev), 1, 0)
        s.dropped = s.dropped + jnp.where(want & ~jnp.any(freev), 1, 0)

    st_pre = s.st
    # pre-event live-append state: the arm occupancy gate must see the
    # chain as the ORACLE's barrier did (a freeze during this event's
    # live-append frees the start only at the NEXT event's re-init)
    cnt_prev_pre = s.cnt_prev

    # ---- condition programs over the current capture state (hoisted
    # capture-free gates ride the event dict — see _eval_conds)
    conds = _eval_conds(spec, event, s.caps)
    ev_rows = _event_rows(spec, event)

    advanced = jnp.zeros((K,), bool)
    appended = jnp.zeros((K,), bool)
    # every-min-0 SEQUENCE: set when the empty-chain virgin closes this
    # event — the re-init pair's every-clone (oracle _min_count_reached →
    # addEveryState) then appends the SAME event, seeding the next chain
    seed_req = None
    # SEQUENCE single-admission: a unit's new-list admits ONE partial per
    # event (StreamPreStateProcessor.addState empty-list guard) and units
    # process in REVERSE order, so a chain re-adding itself into the
    # count unit's list (CountPost, cnt >= min and cnt != max) blocks the
    # every-arm forwarded there the same event
    seq_block_arm = jnp.zeros((), bool)

    # ---- main transitions, one unit at a time (statically unrolled)
    for j, u in enumerate(units):
        at = valid & (st_pre == j)
        if u.kind == "simple":
            ok = at & (stream == u.stream_a) & conds[u.cond_a]
            if spec.eps_start and j == 1:
                if spec.is_sequence and s.seq_froze is not None:
                    # a virgin created right after a freeze is closer-
                    # blocked for its creation event (see make_carry)
                    ok = ok & ~((s.cnt_prev == 0) & (s.seq_froze > 0))
                if spec.is_sequence and spec.is_every:
                    seed_req = jnp.any(ok & (s.cnt_prev == 0))
                # empty-kleene start partial advancing directly: its
                # chain-start timestamp is THIS event (a normal arm would
                # have set start = ts)
                s.start = jnp.where(ok & (s.cnt_prev == 0), ts, s.start)
            s.write_all(ok, u.row_a, ev_rows)
            s.land(ok, j, ts)
            advanced = advanced | ok
        elif u.kind == "logical":
            bitA = (s.lmask & 1) > 0
            bitB = (s.lmask & 2) > 0
            # a side already satisfied ignores further matches (the
            # reference removes the partial from that side's pending list)
            newA = at & (stream == u.stream_a) & conds[u.cond_a] & ~bitA
            newB = at & (stream == u.stream_b) & conds[u.cond_b] & ~bitB
            if not u.is_and:
                # or: when ONE event satisfies both sides, the left side
                # captures and completes first — the right side's partner
                # is already gone (oracle: left pre-processor runs first,
                # LogicalPreStateProcessor partner removal)
                newB = newB & ~newA
            s.write_all(newA, u.row_a, ev_rows)
            s.write_all(newB, u.row_b, ev_rows)
            haveA, haveB = bitA | newA, bitB | newB
            done = at & ((haveA & haveB) if u.is_and else (newA | newB))
            s.lmask = jnp.where(newA, s.lmask | 1, s.lmask)
            s.lmask = jnp.where(newB, s.lmask | 2, s.lmask)
            s.land(done, j, ts)
            advanced = advanced | done
            appended = appended | ((newA | newB) & ~done)
        elif u.kind == "count":
            # accumulating phase: slot sits at j while cnt < min
            ok = at & (stream == u.stream_a) & conds[u.cond_a]
            c2 = s.cnt_cur + 1
            s.write_count(ok & (s.cnt_cur == 0), ok, u.row_a, ev_rows, c2)
            s.cnt_cur = jnp.where(ok, c2, s.cnt_cur)
            reach = ok & (c2 == u.min_count)
            dead = reach & (c2 == u.max_count)
            s.land(reach, j, ts, fwd_cnt=c2, fwd_dead=dead)
            advanced = advanced | reach
            if spec.is_sequence and j == 1 and \
                    units[0].kind == "simple":
                seq_block_arm = seq_block_arm | \
                    jnp.any(ok & (c2 >= u.min_count) & (c2 != u.max_count))
            if spec.is_sequence:
                appended = appended | (ok & (c2 >= u.min_count))
            else:
                appended = appended | ok
        elif u.kind == "absent":
            # an actual arrival on the `not` stream kills the partial
            # (AbsentStreamPostStateProcessor: never advances)
            kill = at & (stream == u.stream_a) & conds[u.cond_a]
            if j == 0 and spec.lead_absent:
                # leading absent: the kill re-arms in place with a fresh
                # deadline (oracle add_every_state on arrival — the wait
                # restarts from the arrival)
                s.deadline = jnp.where(kill, ts + u.waiting_ms,
                                       s.deadline)
                s.start = jnp.where(kill, ts, s.start)
                s.enter = jnp.where(kill, ts, s.enter)
            else:
                s.st = jnp.where(kill, -1, s.st)

    # ---- live-append phase: a forwarded count keeps growing its last
    # bank while the next unit is pending (the reference shares one
    # StateEvent between the kleene chain and the next pending list,
    # CountPreStateProcessor.removeIfNextStateProcessed)
    if s.cnt_prev is not None:
        for j, u in enumerate(units):
            if u.kind != "count":
                continue
            t, _live0, completed = _land_static(spec, j)
            if completed:
                continue        # trailing count: match already emitted
            live = valid & (st_pre == t) & (s.cnt_prev >= 0) & ~advanced
            ok = live & (stream == u.stream_a) & conds[u.cond_a] & \
                (s.cnt_prev < u.max_count)
            if spec.eps_start and j == 0:
                # first append into the leading kleene: the chain starts
                # here (within runs from the first captured event)
                s.start = jnp.where(ok & (s.cnt_prev == 0), ts, s.start)
            c2 = s.cnt_prev + 1
            s.write_count(ok & (s.cnt_prev == 0), ok, u.row_a, ev_rows, c2)
            s.cnt_prev = jnp.where(ok, c2, s.cnt_prev)
            # max reached → the reference marks stateChanged and stops
            froze = ok & (c2 == u.max_count)
            s.cnt_prev = jnp.where(froze, -1, s.cnt_prev)
            appended = appended | ok
            if j == 0 and spec.eps_start and spec.is_sequence and \
                    s.seq_froze is not None:
                s.seq_froze = jnp.where(
                    valid, jnp.any(froze).astype(jnp.int32),
                    s.seq_froze)
            if spec.is_sequence and j == 1 and \
                    units[0].kind == "simple":
                # CountPost re-adds while cnt != max — that re-add owns
                # the count's new-list slot for this event
                seq_block_arm = seq_block_arm | jnp.any(ok & ~froze)

    # ---- SEQUENCE strict contiguity: partials at simple/count/logical
    # units must advance or append on every event or die (per-event
    # resetState barriers, StreamPreStateProcessor.java:263-279); an `and`
    # partial with one side already satisfied waits for its partner, and
    # absent partials survive (processAndReturn keeps them)
    if spec.is_sequence:
        # injected TIMER rows (stream -2) are not events: the oracle's
        # absent_tick never runs the per-event reset barrier
        is_real = valid & (stream != -2)
        # logical units are strict too: a sequence partial whose or/and
        # unit matched NEITHER side on this event dies — EXCEPT an and-
        # partial that already satisfied one side (the oracle's logical
        # pending entry survives while waiting for its partner)
        strict = np.asarray([u.kind in ("simple", "count", "logical")
                             for u in units] + [False], bool)
        logical_u = np.asarray([u.kind == "logical" for u in units] +
                               [False], bool)
        at_strict = jnp.asarray(strict)[jnp.clip(st_pre, 0, S)]
        at_logical = jnp.asarray(logical_u)[jnp.clip(st_pre, 0, S)]
        half_done = at_logical & (s.lmask != 0)
        kill = is_real & (st_pre >= 0) & (s.st >= 0) & at_strict & \
            ~(advanced | appended) & ~half_done
        s.st = jnp.where(kill, -1, s.st)

    # ---- arming a fresh partial at unit 0 (reference `every` re-arm /
    # start-state init)
    u0 = units[0]
    # conditions at unit 0 never read captures → uniform over K: lane 0
    occ_gate = ~jnp.any((st_pre >= 0) & (st_pre <= spec.every_group_end)) \
        if (spec.is_every and spec.every_group_end > 0) or \
        u0.kind in ("count", "logical") else jnp.bool_(True)
    if spec.is_sequence and u0.kind == "count" and not spec.eps_start \
            and not spec.dead_start:
        # SEQUENCE leading min-1 kleene: the shared StateEvent re-occupies
        # the start's new-list on every successful append, so the oracle
        # re-inits only once the chain freezes at max, closes, or dies —
        # and only at the NEXT event's barrier, hence the PRE-event
        # cnt_prev (a freeze during this event frees nothing yet)
        t0, _l0, _c0 = _land_static(spec, 0)
        occ = (st_pre >= 0) & (st_pre <= spec.every_group_end)
        if not _c0:
            occ = occ | ((st_pre == t0) & (cnt_prev_pre >= 0))
        occ_gate = ~jnp.any(occ)
    if spec.arm_once:
        occ_gate = occ_gate & (s.armed_total == 0)

    arm = jnp.zeros((), bool)
    arm_state = jnp.int32(0)
    arm_lmask = jnp.int32(0)
    arm_cnt_cur = jnp.int32(0)
    arm_cnt_prev = jnp.int32(-1)
    arm_match = jnp.zeros((), bool)
    arm_row_writes: List[int] = []      # rows the arming event captures
    arm_n1_rows: List[int] = []         # count rows written with __n = 1

    if u0.kind == "simple":
        c0 = valid & (stream == u0.stream_a) & conds[u0.cond_a][0]
        t, _live0, completed = _land_static(spec, 0)
        arm = c0
        arm_row_writes.append(u0.row_a)
        if completed:
            arm_match = c0
        else:
            arm_state = jnp.int32(t)
            arm_cnt_prev = jnp.int32(0 if _live0 else -1)
    elif u0.kind == "count" and spec.eps_start:
        pass        # leading min-0: arming is the ensure-virgin block above
    elif u0.kind == "count" and spec.dead_start:
        pass        # SEQUENCE min>=2: dead shape, never arms (see NfaSpec)
    elif u0.kind == "count":
        if spec.is_sequence:
            # a SEQUENCE re-arm is a FRESH empty chain: self e[last] refs
            # in the kleene's own condition must see a virgin context
            # (empty last bank, __cnt == 0), not slot 0's stale captures
            zero_caps = jnp.zeros((1,) + s.caps.shape[1:], s.caps.dtype)
            cond0 = _cond_on(spec, event, u0.cond_a, zero_caps)
        else:
            cond0 = conds[u0.cond_a][0]
        c0 = valid & (stream == u0.stream_a) & cond0
        arm = c0
        arm_row_writes.append(u0.row_a)
        arm_n1_rows.append(u0.row_a)
        if u0.min_count <= 1:
            t, _live0, completed = _land_static(spec, 0)
            if completed:
                arm_match = c0
            else:
                arm_state = jnp.int32(t)
                arm_cnt_prev = jnp.where(
                    jnp.bool_(u0.max_count == 1), jnp.int32(-1),
                    jnp.int32(1))
        else:
            arm_state = jnp.int32(0)
            arm_cnt_cur = jnp.int32(1)
    elif u0.kind == "logical":
        cA = valid & (stream == u0.stream_a) & conds[u0.cond_a][0]
        cB = valid & (stream == u0.stream_b) & conds[u0.cond_b][0]
        if not u0.is_and:
            cB = cB & ~cA       # or: same-event double match, left wins
        arm = cA | cB
        both = (cA & cB) if u0.is_and else (cA | cB)
        t, _live0, completed = _land_static(spec, 0)
        arm_match = both if completed else jnp.zeros((), bool)
        arm_state = jnp.where(both, jnp.int32(-2 if completed else t),
                              jnp.int32(0))
        # a completed leading unit advances with a CLEAN mask — stale side
        # bits would leak into a later logical unit (land() zeroes lmask
        # on advance; the arm path must match)
        arm_lmask = jnp.where(both, 0,
                              jnp.where(cA, 1, 0) | jnp.where(cB, 2, 0))
        arm_cnt_prev = jnp.int32(0 if _live0 else -1)
        # capture whichever side(s) matched
        arm_row_writes = []     # handled below with per-side predicates
    else:                       # absent at start: planner rejects
        arm = jnp.zeros((), bool)

    do_arm = arm & occ_gate & ~seq_block_arm
    free = (s.st < 0) & ~s.m_mask
    first_free = jnp.argmax(free)
    any_free = jnp.any(free)
    armed_here = (do_arm & any_free) & (jnp.arange(K) == first_free)
    s.dropped = s.dropped + jnp.where(do_arm & ~any_free, 1, 0)
    if spec.arm_once:
        s.armed_total = s.armed_total + jnp.where(do_arm & any_free, 1, 0)
        if spec.is_sequence:
            # a non-every sequence is single-shot: its one initial partial
            # dies forever on the first real event it cannot advance on
            # (StreamPreStateProcessor.init runs once; SEQUENCE barriers
            # clear the pending list every event; TIMER rows don't count)
            virgin_dies = valid & (stream != -2) & (s.armed_total == 0)
            s.armed_total = jnp.where(virgin_dies, 2, s.armed_total)

    s.clear_slot(armed_here)
    if u0.kind == "logical":
        cA = valid & (stream == u0.stream_a) & conds[u0.cond_a][0]
        cB = valid & (stream == u0.stream_b) & conds[u0.cond_b][0]
        if not u0.is_and:
            cB = cB & ~cA       # or: left side captures on a double match
        s.write_all(armed_here & cA, u0.row_a, ev_rows)
        s.write_all(armed_here & cB, u0.row_b, ev_rows)
    else:
        for r in arm_row_writes:
            if r in arm_n1_rows:
                s.write_count(armed_here, armed_here, r, ev_rows,
                              jnp.full((K,), 1, jnp.int32))
            else:
                s.write_all(armed_here, r, ev_rows)
    emit_arm = armed_here & arm_match
    s.m_mask = s.m_mask | emit_arm
    s.m_ts = jnp.where(emit_arm, ts, s.m_ts)
    s.m_caps = jnp.where(emit_arm[:, None, None], s.caps, s.m_caps)
    s.m_enter = jnp.where(emit_arm, ts, s.m_enter)
    s.m_seq = jnp.where(emit_arm, s.arm_seq, s.m_seq)
    live_arm = armed_here & ~arm_match
    s.st = jnp.where(live_arm, arm_state, s.st)
    s.start = jnp.where(live_arm | emit_arm, ts, s.start)
    s.enter = jnp.where(live_arm, ts, s.enter)
    s.seq = jnp.where(live_arm, s.arm_seq, s.seq)
    s.arm_seq = s.arm_seq + jnp.where(jnp.any(armed_here), 1, 0)
    if s.lmask is not None:
        s.lmask = jnp.where(live_arm, arm_lmask, s.lmask)
    if s.cnt_cur is not None:
        s.cnt_cur = jnp.where(live_arm, arm_cnt_cur, s.cnt_cur)
        s.cnt_prev = jnp.where(live_arm, arm_cnt_prev, s.cnt_prev)
    if s.deadline is not None and len(units) > 1:
        t0, _l0, _c0 = _land_static(spec, 0)
        if t0 < S and units[t0].kind == "absent":
            s.deadline = jnp.where(live_arm & (s.st == t0),
                                   ts + units[t0].waiting_ms, s.deadline)

    # ---- every-min-0 SEQUENCE seed: the virgin closed this event while
    # the event also passes the kleene condition — the oracle's re-init
    # every-clone appends it, so the NEXT chain starts with THIS event
    if seed_req is not None:
        # the seed clone starts an EMPTY chain — virgin condition context
        # (self e[last] refs read nothing), like the count re-arm above
        zero_caps = jnp.zeros((1,) + s.caps.shape[1:], s.caps.dtype)
        c0 = valid & (stream == u0.stream_a) & \
            _cond_on(spec, event, u0.cond_a, zero_caps)
        want_seed = seed_req & c0
        free_s = (s.st < 0) & ~s.m_mask
        seeded = (want_seed & jnp.any(free_s)) & \
            (jnp.arange(K) == jnp.argmax(free_s))
        s.clear_slot(seeded)
        s.st = jnp.where(seeded, 1, s.st)
        s.write_count(seeded, seeded, u0.row_a, ev_rows,
                      jnp.full((K,), 1, jnp.int32))
        mx1 = u0.max_count == 1
        s.cnt_prev = jnp.where(seeded, jnp.int32(-1 if mx1 else 1),
                               s.cnt_prev)
        s.cnt_cur = jnp.where(seeded, 0, s.cnt_cur)
        s.start = jnp.where(seeded, ts, s.start)
        s.enter = jnp.where(seeded, ts, s.enter)
        s.seq = jnp.where(seeded, s.arm_seq, s.seq)
        s.arm_seq = s.arm_seq + jnp.where(jnp.any(seeded), 1, 0)
        s.dropped = s.dropped + jnp.where(want_seed & ~jnp.any(free_s),
                                          1, 0)
        if mx1 and s.seq_froze is not None:
            # a max-1 seed freezes immediately: its forward blocks the
            # next virgin's closer-eligibility (see make_carry)
            s.seq_froze = jnp.where(jnp.any(seeded), 1, s.seq_froze)

    # ---- mid-chain `every` clone allocation (requests collected by
    # land() during the unit loop; placed after arming so pending-list
    # append order matches the oracle: armed partial first, clones after)
    for g0 in sorted(s.spawn):
        spm, rk = s.spawn[g0]
        s.alloc_clones(g0, spm, rk, ts)

    # ---- absent deadline pass: virtual time has reached ts, so every due
    # `not … for t` deadline fires now — AFTER the event was processed (the
    # playback scheduler advances to an event's time after routing it);
    # ascending unit order cascades an absence chain in one pass.  Slots
    # that advance here capture the NEXT event onward.
    if s.deadline is not None:
        for j, u in enumerate(units):
            if u.kind != "absent":
                continue
            fire = valid & (s.st == j) & (s.deadline <= ts)
            s.land(fire, j, s.deadline)

    match_caps = s.m_caps

    out = {"slot_state": s.st, "slot_start": s.start,
           "slot_enter": s.enter, "slot_seq": s.seq, "arm_seq": s.arm_seq,
           "captures": s.caps, "dropped": s.dropped}
    if s.cnt_cur is not None:
        out["cnt_cur"] = s.cnt_cur
        out["cnt_prev"] = s.cnt_prev
    if s.seq_froze is not None:
        out["seq_froze"] = s.seq_froze
    if s.lmask is not None:
        out["lmask"] = s.lmask
    if s.deadline is not None:
        out["deadline"] = s.deadline
    if s.armed_total is not None:
        out["armed_total"] = s.armed_total
    if tel is not None:
        # gate pass/fail per unit: reuse the conds/st_pre/stream values
        # the transitions consumed — an "eligible" slot sat at unit j on
        # the matching stream; "pass" means its condition program fired
        tel_pass, tel_fail = [], []
        for j, u in enumerate(units):
            at = valid & (st_pre == j)
            if u.cond_a >= 0:
                elig = at & (stream == u.stream_a)
                hit = elig & conds[u.cond_a]
            else:
                elig = jnp.zeros((K,), bool)
                hit = elig
            if u.cond_b >= 0:
                elig_b = at & (stream == u.stream_b)
                hit = hit | (elig_b & conds[u.cond_b])
                elig = elig | elig_b
            tel_pass.append(jnp.sum(hit.astype(jnp.int32)))
            tel_fail.append(jnp.sum((elig & ~hit).astype(jnp.int32)))
        occ = jnp.sum((s.st[None, :] == jnp.arange(S)[:, None])
                      .astype(jnp.int32), axis=1)
        out["telem"] = jnp.concatenate([
            occ,                                    # live occupancy gauge
            tel[S:2 * S] + jnp.stack(tel_pass),
            tel[2 * S:3 * S] + jnp.stack(tel_fail),
            (tel[3 * S] + tel_exp)[None],           # within-expiry drops
        ])
    return out, (s.m_mask, match_caps, s.m_ts, s.m_enter, s.m_seq)


def build_block_step(spec: NfaSpec, batch_b: Optional[int] = None,
                     unroll: int = 1):
    """Returns jittable fn(carry, block) → (carry, matches).

    block: dict of [P, T] arrays — per-partition event lanes, time-major
    scan; `__valid` masks padding.  matches: (mask [P, T, K],
    caps [P, T, K, R, C], ts [P, T, K], enter [P, T, K], seq [P, T, K]).

    Round 6 — fatter scan ticks.  The legacy scan ran T ticks, each a
    chain of ~10² small fused ops whose issue LATENCY (not throughput)
    set the pace (docs/perf_notes.md §roofline accounting).  Two
    composable restructurings, both gated by ``SIDDHI_TPU_NFA_BATCH``
    (default B=4; ``=1`` is the kill switch → this exact legacy path):

      1. **Condition hoisting** — capture-free condition programs
         (spec.cond_free, the common case) are evaluated for the WHOLE
         block in one vectorized [T] pass outside the scan; the scan body
         reads precomputed boolean gates and shrinks to the truly
         sequential masked state update.
      2. **B-event micro-batching** — each scan tick consumes
         ``batch_b`` events (a static unroll of the per-event transition
         over the precomputed gates), cutting tick count T→⌈T/B⌉ so the
         fixed per-tick issue cost amortizes and XLA can overlap the
         independent per-lane work of the B sub-steps.

    Sub-steps are the SAME per-event function, so match semantics are
    bit-identical by construction (randomized parity across B × pattern
    shapes is asserted in tests/test_nfa_batch.py)."""
    B = resolve_batch_b(spec.batch_b or None) if batch_b is None \
        else resolve_batch_b(batch_b)

    def per_partition(carry_p, events_p):
        def step(c, ev):
            return _one_partition_step(spec, c, ev)
        if B == 1:
            return jax.lax.scan(step, carry_p, events_p, unroll=unroll)
        events_p = {**events_p, **_hoist_cond_gates(spec, events_p)}
        events_p, T, ticks = _pad_block_t(events_p, B)
        chunks = {k: v.reshape((ticks, B) + v.shape[1:])
                  for k, v in events_p.items()}

        def tick(c, evs):
            # inner scan fully unrolled (length B == unroll B): the step
            # body traces ONCE and XLA inlines B copies into the outer
            # tick — the outer sequential chain genuinely shrinks to
            # ⌈T/B⌉ ticks (asserted at the jaxpr level in tests)
            return jax.lax.scan(step, c, evs, unroll=B)
        carry2, ys = jax.lax.scan(tick, carry_p, chunks, unroll=unroll)
        ys = tuple(y.reshape((ticks * B,) + y.shape[2:])[:T] for y in ys)
        return carry2, ys

    def block_step(carry, block):
        return jax.vmap(per_partition)(carry, block)

    return block_step


def build_bank_step(spec: NfaSpec, ring: int = 0,
                    batch_b: Optional[int] = None):
    """N structurally-identical patterns (constants differ) × P partitions.

    Returns jittable fn(carry, block, params):
      carry:  NFA carry with a leading pattern axis [N, P, ...]
      block:  one [P, T] event block, shared by every pattern
      params: {param_name: [N]} per-pattern constant lanes

    ring == 0 → (carry, match_counts [N]): counts only; summing inside the
    scan keeps the [N, P, T, K] mask from materialising in HBM.

    ring > 0 → (carry, (match_counts [N], ring_cnt [N, ring],
    ring_pid [N, ring], ring_caps [N, ring, R, C], ring_ts [N, ring],
    ring_ok [N, ring])): a bounded per-pattern match-payload buffer — for
    up to `ring` matched partitions per block (those with the most
    matches), the capture rows + timestamp of a match from that
    partition's last matching event.  Counts stay exact; payloads beyond
    the ring are counted but not decoded.  This is the production alert
    payload the fleet path owes (reference matches carry the full
    StateEvent chain, query/output/callback/QueryCallback.java).

    Zero-copy design: touching the per-step match captures inside the scan
    forces XLA to double-buffer the whole captures carry every step (~20x
    throughput loss measured on v5e).  Instead the scan records only the
    last match's (ts, slot) scalars; captures are gathered from the FINAL
    carry after the scan — a completed match's capture rows stay in their
    slot until the slot is re-armed (clear_slot runs only on arming).
    `ring_ok` is False when the slot WAS re-armed after the match
    (slot_start moved past the match ts), i.e. the payload was overwritten
    and is dropped (still counted); with monotonically increasing block
    timestamps the check is exact, under repeated equal timestamps a
    same-ts re-arm can slip through as a stale payload.
    """

    B = resolve_batch_b(spec.batch_b or None) if batch_b is None \
        else resolve_batch_b(batch_b)

    def per_partition(carry_p, events_p, prm):
        def sub_step(c, ev):
            inner, acc, lmt, lmk = c
            inner2, (mm, *_rest) = _one_partition_step(
                spec, inner, {**ev, **prm})
            # accumulate in-carry: avoids a [N, P, T] stacked ys buffer
            acc2 = acc + jnp.sum(mm.astype(jnp.int32))
            if ring:
                # the EVENT's ts, not the per-slot match ts (m_ts): reading
                # m_ts forces the per-unit emission-bookkeeping chains XLA
                # otherwise dead-code-eliminates — 5.5x slower measured.
                # They only differ for absent-deadline completions, whose
                # payload ts then reads as the triggering event's time.
                hit = jnp.any(mm)
                lmt = jnp.where(hit, ev["__ts"], lmt)
                lmk = jnp.where(hit, jnp.argmax(mm).astype(jnp.int32), lmk)
            return (inner2, acc2, lmt, lmk)
        init = (carry_p, jnp.int32(0), jnp.int32(0), jnp.int32(0))
        if B == 1:
            def step(c, ev):
                return sub_step(c, ev), None
            (c2, acc, lmt, lmk), _ = jax.lax.scan(step, init, events_p)
            return c2, acc, lmt, lmk
        # fatter ticks (see build_block_step): hoist capture-free gates
        # for the whole lane, then consume B events per scan tick
        events_p = {**events_p,
                    **_hoist_cond_gates(spec, events_p, extra=prm)}
        events_p, _T, ticks = _pad_block_t(events_p, B)
        chunks = {k: v.reshape((ticks, B) + v.shape[1:])
                  for k, v in events_p.items()}

        def tick(c, evs):
            def inner(c2, ev):
                return sub_step(c2, ev), None
            c2, _ = jax.lax.scan(inner, c, evs, unroll=B)
            return c2, None
        (c2, acc, lmt, lmk), _ = jax.lax.scan(tick, init, chunks)
        return c2, acc, lmt, lmk

    def pattern_step(carry_n, prm, block):
        new_carry, counts, lmt, lmk = jax.vmap(
            per_partition, in_axes=(0, 0, None))(carry_n, block, prm)
        total = jnp.sum(counts)
        if not ring:
            return new_carry, total
        ring_cnt, ring_pid = jax.lax.top_k(counts, ring)
        sel_k = lmk[ring_pid]                              # [ring]
        ring_caps = new_carry["captures"][ring_pid, sel_k]
        ring_ts = lmt[ring_pid]
        # slot re-armed after the match → captures overwritten → drop
        ring_ok = new_carry["slot_start"][ring_pid, sel_k] <= ring_ts
        return new_carry, (total, ring_cnt, ring_pid, ring_caps, ring_ts,
                           ring_ok)

    def bank_step(carry, block, params):
        return jax.vmap(pattern_step, in_axes=(0, 0, None))(carry, params,
                                                            block)

    return bank_step


def build_super_bank_step(spec: NfaSpec, ring: int = 0,
                          batch_b: Optional[int] = None):
    """C homogeneous pattern chunks stepped as ONE dispatch.

    Returns jittable fn(carry, block, params):
      carry:  stacked bank carry [C, N, P, ...] (one array per leaf)
      block:  one [P, T] event block, shared by every chunk
      params: {param_name: [C, N]} stacked per-pattern constant lanes

    Semantically identical to running ``build_bank_step`` C times on the
    per-chunk slices (patterns never interact), but XLA sees a single
    executable and the runtime pays one launch per ingest block instead
    of C — the dispatch-side half of "fewer, fatter steps"."""
    bank = build_bank_step(spec, ring=ring, batch_b=batch_b)

    def super_step(carry, block, params):
        return jax.vmap(bank, in_axes=(0, None, 0))(carry, block, params)

    return super_step


def make_bank_carry(spec: NfaSpec, n_patterns: int,
                    n_partitions: int) -> Dict[str, jnp.ndarray]:
    c = make_carry(spec, n_partitions)
    return {k: jnp.broadcast_to(v[None], (n_patterns,) + v.shape)
            for k, v in c.items()}


def pack_blocks(partition_ids: np.ndarray, columns: Dict[str, np.ndarray],
                timestamps: np.ndarray, stream_codes: np.ndarray,
                n_partitions: int, base_ts: int = 0,
                pad_t_pow2: bool = False, return_rows: bool = False):
    """Host-side: scatter a flat event batch into dense [P, T] lanes
    (T = max events of any partition in the batch; padding masked invalid;
    pad_t_pow2 rounds T up to a power of two so jit sees few distinct
    shapes).  return_rows additionally yields each input event's row index
    within its lane (for per-event output decode).

    This is the columnar replacement for the reference's per-key junction
    routing (partition/PartitionStreamReceiver.java:83-153)."""
    from ..native_ext import assign_rows
    n = len(partition_ids)
    partition_ids = np.ascontiguousarray(partition_ids, np.int32)
    row, _counts, T = assign_rows(partition_ids, n_partitions)
    if pad_t_pow2:
        T = 1 << (T - 1).bit_length()
    block: Dict[str, np.ndarray] = {}
    for name, col in columns.items():
        out = np.zeros((n_partitions, T), np.float32)
        out[partition_ids, row] = col.astype(np.float32)
        block[name] = out
    ts = np.zeros((n_partitions, T), np.int32)
    ts[partition_ids, row] = (np.asarray(timestamps, np.int64) -
                              base_ts).astype(np.int32)
    block["__ts"] = ts
    sc = np.zeros((n_partitions, T), np.int32)
    sc[partition_ids, row] = stream_codes
    block["__stream"] = sc
    valid = np.zeros((n_partitions, T), bool)
    valid[partition_ids, row] = True
    block["__valid"] = valid
    if return_rows:
        return block, row
    return block


def make_timer_block(n_partitions: int, ts_offset: int,
                     attr_names) -> Dict[str, np.ndarray]:
    """One virtual TIMER row per partition lane (stream code -2 matches no
    unit): drives absent-state deadlines and within expiry between real
    events (≙ the reference Scheduler's TIMER StreamEvents,
    util/Scheduler.java:180-211)."""
    block = {a: np.zeros((n_partitions, 1), np.float32) for a in attr_names}
    block["__ts"] = np.full((n_partitions, 1), ts_offset, np.int32)
    block["__stream"] = np.full((n_partitions, 1), -2, np.int32)
    block["__valid"] = np.ones((n_partitions, 1), bool)
    return block
