"""QueryRuntime: wires input → handler chain → selector → rate limiter → output.

(reference: query/QueryRuntime.java + util/parser/QueryParser.java:83-249 —
input-stream runtime construction, selector, lock strategy, rate limiter and
output callback; query/input/ProcessStreamReceiver.java junction entry.)
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..plan.expr_compiler import ExprCompiler, Scope
from ..query_api import (Filter, InsertIntoStream, JoinInputStream, Query,
                         SingleInputStream, StateInputStream,
                         StreamFunctionHandler, WindowHandler)
from ..query_api.definition import StreamDefinition
from ..query_api.query import DeleteStream, UpdateOrInsertStream, UpdateStream
from ..utils.errors import SiddhiAppCreationError
from .event import EventChunk
from .output import (DeleteTableCallback, InsertIntoStreamCallback,
                     InsertIntoTableCallback, InsertIntoWindowCallback,
                     OutputCallbackProcessor, ReturnCallback,
                     UpdateOrInsertTableCallback, UpdateTableCallback)
from .processor import FilterProcessor, LogStreamProcessor, Processor
from .ratelimit import build_rate_limiter
from .selector import QuerySelector
from .window import WindowProcessor, create_window_processor


def _expr_has_aggregate(e) -> bool:
    """Walk an expression IR tree for aggregator AttributeFunctions."""
    from dataclasses import fields, is_dataclass

    from ..query_api.expression import AttributeFunction, Expression
    from .aggregator import is_aggregator
    if e is None:
        return False
    if isinstance(e, AttributeFunction) and \
            is_aggregator(e.namespace, e.name, len(e.args)):
        return True
    if isinstance(e, (list, tuple)):
        return any(_expr_has_aggregate(x) for x in e)
    if is_dataclass(e) and isinstance(e, Expression):
        return any(_expr_has_aggregate(getattr(e, f.name))
                   for f in fields(e))
    return False


def _selector_has_aggregates(selector) -> bool:
    """IR-level aggregate detection (works on both the host path, where a
    QuerySelector exists, and the device path, where the select clause is
    folded into the kernel) — drives snapshot-limiter dispatch (reference
    WrappedSnapshotOutputRateLimiter.init's aggregateAttributePositionList)."""
    return any(_expr_has_aggregate(oa.expr) for oa in selector.attributes)


class ProcessStreamReceiver:
    """Junction entry point for a query; holds the query lock
    (reference query/input/ProcessStreamReceiver.java; debugger check at the
    IN terminal :103-106)."""

    def __init__(self, first: Processor, lock: threading.RLock,
                 latency_tracker=None, query_name: str = "",
                 app_ctx=None):
        self.first = first
        self.lock = lock
        self.latency_tracker = latency_tracker
        self.query_name = query_name
        self.app_ctx = app_ctx

    def flush(self):
        """Retire pipelined device work held anywhere in the processor
        chain (device ingress heads, mid-chain device windows) under the
        query lock — junction idle/drain hook."""
        p = self.first
        while p is not None:
            f = getattr(p, "flush", None)
            if f is not None:
                with self.lock:
                    f()
            p = getattr(p, "next", None)

    def receive_chunk(self, chunk: EventChunk):
        dbg = getattr(self.app_ctx, "debugger", None) if self.app_ctx else None
        if dbg is not None:
            dbg.check(self.query_name, dbg.IN, chunk)
        with self.lock:
            if self.latency_tracker is not None:
                self.latency_tracker.mark_in()
            try:
                self.first.process(chunk)
            finally:
                if self.latency_tracker is not None:
                    self.latency_tracker.mark_out()


class QueryRuntime:
    def __init__(self, query: Query, app_runtime, query_name: str,
                 partition_key: Optional[str] = None,
                 device_key_executors: Optional[Dict] = None):
        self.query = query
        self.app_runtime = app_runtime
        self.name = query_name
        self.partition_key = partition_key
        self.lock = threading.RLock()
        self.output_processor: Optional[OutputCallbackProcessor] = None
        self.selector: Optional[QuerySelector] = None
        self.windows: List[WindowProcessor] = []
        self.receivers: Dict[str, ProcessStreamReceiver] = {}
        self.state_runtime = None          # set for pattern/sequence queries
        self.join_runtime = None
        self.device_runtime = None         # set when the planner picked TPU
        self.backend = "host"
        self.backend_reason: Optional[str] = None
        self._device_key_executors = device_key_executors
        self.output_definition: Optional[StreamDefinition] = None
        self._build()

    # ------------------------------------------------------------ build

    @property
    def selection_route(self) -> Optional[Dict]:
        """Where the query's selection tail (having / order-by / limit /
        offset) executes.  None when the query has no selection tail;
        ``{"backend": "device", "sig": ...}`` when plan/select_compiler
        lowered it into the egress kernel (ops/select.py);
        ``{"backend": "host", "reason": ...}`` for the documented
        host-QuerySelector fallback (value-identical, per-emission
        Python).  Surfaced by service/rest.py stats and
        tools/t1_report.py coverage artifacts."""
        from ..plan.select_compiler import (classify_selection,
                                            selection_active)
        if not selection_active(self.query.selector):
            return None
        route = getattr(self.device_runtime, "selection_route", None)
        if route is not None:
            return dict(route)
        # host route: the static classifier gives the atom-level blocking
        # reason even when another plan stage (e.g. the dwin hybrid)
        # overwrote backend_reason
        reason = None
        app = getattr(self.app_runtime, "app", None)
        ins = self.query.input_stream
        if app is not None and isinstance(ins, SingleInputStream):
            d = app.stream_definitions.get(ins.stream_id)
            attr_types = {a.name: a.type for a in d.attributes} \
                if d is not None else {}
            dec = classify_selection(
                self.query, attr_types,
                in_partition=(self.partition_key is not None or
                              self._device_key_executors is not None))
            if dec.active and not dec.device:
                reason = dec.reason
        return {"backend": "host",
                "reason": reason or self.backend_reason or
                "host query path"}

    def _expr_compiler_factory(self) -> Callable[[Scope], ExprCompiler]:
        app = self.app_runtime
        return lambda scope: ExprCompiler(
            scope, np, app.app_ctx.script_functions, app.extension_registry,
            tables=app.tables)

    def _build(self):
        q = self.query
        app = self.app_runtime
        factory = self._expr_compiler_factory()

        if isinstance(q.input_stream, SingleInputStream):
            if self._device_key_executors is not None:
                # keyed (partition) mode: device or raise, as below.
                # The specialized window-ring path (group == partition
                # key) is tried first — MEASURED 4.6x faster than the
                # grouped-agg slabs on the shape both support (keyed
                # length-window f32 sum, 10k lanes x W=64, r4 benchmark
                # in docs/perf_notes.md); the grouped-agg kernel covers
                # finer group-bys, running aggregates and INT/LONG values
                from ..plan.planner import (DeviceGroupedAggRuntime,
                                            DeviceWindowedAggRuntime)
                try:
                    self.device_runtime = DeviceWindowedAggRuntime(
                        self, q.input_stream, factory,
                        self._device_key_executors)
                except SiddhiAppCreationError:
                    self.device_runtime = DeviceGroupedAggRuntime(
                        self, q.input_stream, factory,
                        key_executors=self._device_key_executors)
                self.backend = "device"
                return
            dev, reason = None, "inside host partition clone"
            if self.partition_key is None and \
                    getattr(app, "app", None) is not None:
                from ..plan.planner import plan_single_runtime
                dev, reason = plan_single_runtime(self, q.input_stream,
                                                  factory)
            if dev is not None:
                self.device_runtime = dev
                self.backend = "device"
                return
            self.backend_reason = reason
            self._build_single(q.input_stream, factory)
        elif isinstance(q.input_stream, JoinInputStream):
            from .join import JoinRuntime
            self.join_runtime = JoinRuntime(self, q.input_stream, factory)
            # the on-condition probe — the join's per-event hot loop — may
            # have compiled to the device; buffers/windows stay host
            if self.join_runtime.device_probe is not None:
                self.backend = "device"
            else:
                self.backend_reason = \
                    self.join_runtime.device_probe_reason
        elif isinstance(q.input_stream, StateInputStream):
            if self._device_key_executors is not None:
                # keyed (partition) mode: device or raise — the caller
                # (PartitionRuntime) owns the host fallback, because a host
                # fallback HERE would wire an unpartitioned state runtime
                from ..plan.planner import DevicePatternRuntime
                self.device_runtime = DevicePatternRuntime(
                    self, q.input_stream, factory,
                    key_executors=self._device_key_executors)
                self.backend = "device"
                return
            dev, reason = None, "inside host partition clone"
            if self.partition_key is None and \
                    getattr(app, "app", None) is not None:
                from ..plan.planner import plan_state_runtime
                dev, reason = plan_state_runtime(self, q.input_stream,
                                                 factory)
            if dev is not None:
                self.device_runtime = dev
                self.backend = "device"
            else:
                self.backend_reason = reason
                from .pattern import StateStreamRuntime
                self.state_runtime = StateStreamRuntime(self, q.input_stream,
                                                        factory)
        else:
            raise SiddhiAppCreationError(
                f"Unsupported input stream {type(q.input_stream).__name__}")

    def _build_single(self, s: SingleInputStream, factory):
        app = self.app_runtime
        definition = app.definition_of(s.stream_id, s.is_inner, s.is_fault)
        scope = Scope()
        scope.add_primary(s.stream_id, s.stream_ref, definition)

        chain: List[Processor] = []
        compiler = factory(scope)
        for h in s.handlers:
            if isinstance(h, Filter):
                chain.append(FilterProcessor(compiler.compile(h.expr)))
            elif isinstance(h, WindowHandler):
                wp = self._try_device_window(h, definition, compiler)
                if wp is None:
                    wp = create_window_processor(
                        h.name, h.params, app.app_ctx,
                        definition.attribute_names,
                        lambda e: compiler.compile(e),
                        namespace=h.namespace or "",
                        extension_registry=app.extension_registry)
                wp.lock = self.lock
                self.windows.append(wp)
                chain.append(wp)
            elif isinstance(h, StreamFunctionHandler):
                chain.append(self._make_stream_function(h, compiler))
        self._finish_chain(chain, scope, definition, factory)
        receiver = ProcessStreamReceiver(
            self._chain_head(chain), self.lock,
            app.latency_tracker_for(self.name), self.name, app.app_ctx)
        if app.has_named_window(s.stream_id):
            app.named_window_of(s.stream_id).subscribe(receiver)
        else:
            junction = app.junction_of(s.stream_id, s.is_inner, s.is_fault,
                                       self.partition_key)
            junction.subscribe(receiver)
        self.receivers[s.stream_id] = receiver

    def _try_device_window(self, h, definition, compiler):
        """Device window state (plan/dwin_compiler) in place of the host
        window processor when the kind/payload types have device lanes —
        the buffer of record and all eviction/flush math move to the
        device kernel; the selector stays host (hybrid recorded in
        docs/device_coverage.md).  Host partition clones keep host
        windows (one tiny device state per key would serialize)."""
        app = self.app_runtime
        if self.partition_key is not None or \
                getattr(app, "app", None) is None:
            return None
        from ..plan.dwin_compiler import (DEVICE_KINDS,
                                          DeviceWindowProcessor)
        from ..plan.planner import engine_mode
        mode = engine_mode(app.app)
        if mode == "host":
            return None
        # SiddhiQL's 'hoping' spelling maps onto the device hopping kernel
        hname = h.name.lower()
        if hname == "hoping":
            hname = "hopping"
        kind = next((k for k in DEVICE_KINDS
                     if k.lower() == hname), None) \
            if not h.namespace else None
        if kind is None:
            if mode == "device":
                # engine('device') is strict: no silent host fallback
                label = (f"#{h.namespace}:{h.name}" if h.namespace
                         else f"#window.{h.name}")
                raise SiddhiAppCreationError(
                    f"device window path: {label} has no device kernel")
            return None
        from ..plan.pipeline import resolve_depth
        try:
            depth = resolve_depth(app.app, [app.junction_of(definition.id)])
        except Exception:      # noqa: BLE001 — inner/fault stream ids
            depth = 0
        try:
            wp = DeviceWindowProcessor(app.app_ctx, definition, kind,
                                       h.params, compiler.compile,
                                       pipeline_depth=depth)
        except SiddhiAppCreationError:
            if mode == "device":
                raise
            return None
        # NOTE: dwin egress is deliberately NOT routed through the app's
        # EgressFuser.  Window steps (timer ticks especially) dispatch and
        # read back synchronously, so there is never a second runtime's
        # buffer to share the slab with — fusing would only add the
        # seal/rotate device ops per tick.  Fusion covers the per-block
        # pattern/filter/wagg/gagg egress (see plan/planner.py).
        self.backend = "device"
        self.backend_reason = ("hybrid: window state/evictions on device "
                               "(dwin kernel), selector host")
        return wp

    def _make_stream_function(self, h: StreamFunctionHandler, compiler):
        app = self.app_runtime
        low = h.name.lower()
        params = [compiler.compile(p) for p in h.params]
        if (h.namespace or "") == "" and low == "log":
            return LogStreamProcessor(params)
        ext = app.extension_registry.find_stream_processor(
            h.namespace or "", h.name) if app.extension_registry else None
        if ext is not None:
            return ext(params)
        raise SiddhiAppCreationError(
            f"Unknown stream function '#{h.name}'")

    def _chain_head(self, chain: List[Processor]) -> Processor:
        """Link chain → selector → rate limiter → output; return head."""
        full = chain + [self.selector, self.rate_limiter, self.output_processor]
        for a, b in zip(full, full[1:]):
            a.next = b
        return full[0]

    def _finish_chain(self, chain, scope, input_definition, factory):
        """Create selector / rate limiter / output (shared by all input kinds).
        Must be called before _chain_head."""
        q = self.query
        app = self.app_runtime
        target = getattr(q.output_stream, "target_id", "") or self.name
        self.selector = QuerySelector(q.selector, scope, input_definition,
                                      factory, output_id=target)
        self.output_definition = self.selector.output_definition
        if isinstance(q.input_stream, SingleInputStream):
            # table on/set expressions may qualify by the source stream name
            self.output_definition.source_alias = \
                q.input_stream.stream_ref or q.input_stream.stream_id
        self._finish_output_tail(factory)

    def _finish_output_tail(self, factory):
        """Rate limiter + output callback (shared by host and device
        chains); requires self.output_definition."""
        q = self.query
        app = self.app_runtime
        group_names = [v.attribute for v in q.selector.group_by]
        self.rate_limiter = build_rate_limiter(
            q.output_rate, app.app_ctx, group_names,
            windowed=self._query_is_windowed(q),
            has_aggregates=_selector_has_aggregates(q.selector))
        self.output_processor = self._make_output(q, factory)
        self.output_processor.query_name = self.name
        self.output_processor.app_ctx = app.app_ctx

    def _query_is_windowed(self, q: Query) -> bool:
        """Reference QueryParser marks a query 'windowed' when its (or either
        join side's) handler chain contains a window, or it reads a named
        window — drives snapshot-limiter dispatch
        (WrappedSnapshotOutputRateLimiter.java:86)."""
        app = self.app_runtime

        def single(s) -> bool:
            if not isinstance(s, SingleInputStream):
                return False
            if any(isinstance(h, WindowHandler) for h in s.handlers):
                return True
            return app.has_named_window(s.stream_id)

        ins = q.input_stream
        if isinstance(ins, JoinInputStream):
            return single(ins.left) or single(ins.right)
        return single(ins)

    def _finish_device_chain(self, output_definition: StreamDefinition,
                             factory):
        """Output tail for a device-compiled query (the select clause is
        folded into the device kernel's capture decode); returns the chain
        head the device runtime feeds."""
        self.output_definition = output_definition
        self._finish_output_tail(factory)
        self.rate_limiter.next = self.output_processor
        return self.rate_limiter

    def _make_output(self, q: Query, factory) -> OutputCallbackProcessor:
        app = self.app_runtime
        out = q.output_stream
        ef = out.events_for
        if isinstance(out, (DeleteStream, UpdateStream, UpdateOrInsertStream)) \
                and app.has_table(out.target_id):
            table = app.table_of(out.target_id)
            cc = table.compile_condition(out.on, self.output_definition,
                                         factory)
            if isinstance(out, DeleteStream):
                return DeleteTableCallback(table, cc, ef)
            cset = table.compile_set(out.set_assignments,
                                     self.output_definition, factory)
            if isinstance(out, UpdateOrInsertStream):
                return UpdateOrInsertTableCallback(table, cc, cset, ef)
            return UpdateTableCallback(table, cc, cset, ef)
        if isinstance(out, InsertIntoStream):
            if app.has_table(out.target_id):
                return InsertIntoTableCallback(app.table_of(out.target_id), ef)
            if app.has_named_window(out.target_id):
                return InsertIntoWindowCallback(
                    app.named_window_of(out.target_id), ef)
            junction = app.junction_of(out.target_id, out.is_inner,
                                       out.is_fault, self.partition_key,
                                       create_with=self.output_definition)
            target_def = junction.definition
            self._validate_output(target_def)
            return InsertIntoStreamCallback(junction, target_def, ef)
        return ReturnCallback(ef)

    def _validate_output(self, target_def: StreamDefinition):
        out_names = self.output_definition.attribute_names
        if len(out_names) != len(target_def.attributes):
            raise SiddhiAppCreationError(
                f"Query '{self.name}' output ({out_names}) does not match "
                f"stream '{target_def.id}' ({target_def.attribute_names})")

    # ------------------------------------------------------------ lifecycle

    def start(self):
        if self.state_runtime is not None:
            self.state_runtime.start()
        if self.device_runtime is not None and \
                hasattr(self.device_runtime, "start"):
            self.device_runtime.start()

    # ------------------------------------------------------------ callbacks

    def add_callback(self, cb):
        self.output_processor.query_callbacks.append(cb)

    # ------------------------------------------------------------ state

    def stateful_elements(self):
        """(element_id, obj) pairs registered with the snapshot service."""
        out = []
        if self.selector is not None:
            out.append((f"{self.name}:selector", self.selector))
        for i, w in enumerate(self.windows):
            out.append((f"{self.name}:window:{i}", w))
        if self.state_runtime is not None:
            out.append((f"{self.name}:state", self.state_runtime))
        if self.device_runtime is not None:
            out.append((f"{self.name}:state", self.device_runtime))
        if self.join_runtime is not None:
            for i, w in enumerate(self.join_runtime.windows):
                out.append((f"{self.name}:join:{i}", w))
        return out
