"""Processor chain primitives.

(reference: query/processor/Processor.java chain-of-responsibility;
query/processor/filter/FilterProcessor.java;
query/processor/stream/StreamFunctionProcessor.java.)

Processors receive columnar EventChunks and push results to `next`.  A filter
is a single vectorised boolean mask over the batch — the per-event expression
DFS of the reference collapses into one fused column program.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx
from .event import RESET, TIMER, EventChunk
from .stateschema import persistent_schema


@persistent_schema("processor-base", schema=None,
                   doc="abstract chain link: the default current_state "
                       "is the stateless None")
class Processor:
    def __init__(self):
        self.next: Optional[Processor] = None

    def process(self, chunk: EventChunk):
        raise NotImplementedError

    def send_next(self, chunk: EventChunk):
        if self.next is not None and not chunk.is_empty:
            self.next.process(chunk)

    def set_next(self, p: "Processor") -> "Processor":
        self.next = p
        return p

    # state hooks (overridden by stateful processors)
    def current_state(self) -> Optional[dict]:
        return None

    def restore_state(self, state: dict):
        pass


class FilterProcessor(Processor):
    """Boolean column program over the chunk; TIMER/RESET events always pass
    (they carry no data — reference FilterProcessor only sees data events, but
    our chunks are mixed)."""

    def __init__(self, condition: CompiledExpr):
        super().__init__()
        self.condition = condition

    def process(self, chunk: EventChunk):
        n = len(chunk)
        if n == 0:
            return
        ctx = EvalCtx(chunk.columns, chunk.timestamps, n)
        mask = np.asarray(self.condition.fn(ctx), bool)
        if mask.ndim == 0:
            mask = np.full(n, bool(mask))
        passthrough = (chunk.types == TIMER) | (chunk.types == RESET)
        mask = mask | passthrough
        if mask.all():
            self.send_next(chunk)
        else:
            self.send_next(chunk.mask(mask))


class StreamFunctionProcessor(Processor):
    """Per-event function appending computed attributes
    (reference query/processor/stream/StreamFunctionProcessor.java SPI).
    Concrete stream functions (e.g. `#log()`, extensions) subclass this."""

    def __init__(self, compiled_params, out_names, out_types):
        super().__init__()
        self.compiled_params = compiled_params
        self.out_names = out_names
        self.out_types = out_types

    def apply(self, chunk: EventChunk, param_values):
        raise NotImplementedError

    def process(self, chunk: EventChunk):
        ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
        params = [p.fn(ctx) for p in self.compiled_params]
        out_cols = self.apply(chunk, params)
        cols = dict(chunk.columns)
        cols.update(out_cols)
        names = chunk.names + [n for n in self.out_names if n not in chunk.names]
        self.send_next(EventChunk(names, chunk.timestamps, chunk.types, cols))


class LogStreamProcessor(StreamFunctionProcessor):
    """#log('prefix') — logs and passes through (reference
    query/processor/stream/LogStreamProcessor.java)."""

    def __init__(self, compiled_params):
        super().__init__(compiled_params, [], [])

    def process(self, chunk: EventChunk):
        import logging
        prefix = ""
        if self.compiled_params:
            ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
            v = self.compiled_params[0].fn(ctx)
            prefix = str(v if not isinstance(v, np.ndarray) else v[0])
        for ev in chunk.to_events():
            logging.getLogger("siddhi").info("%s %s", prefix, ev)
        self.send_next(chunk)
