"""Output rate limiting.

(reference: query/output/ratelimit/** — 19 classes: pass-through, per-event-
count first/last/all (+ group-by variants), per-time-window first/last/all
(+ group-by), and snapshot re-emission.)

Implemented as one processor per strategy sitting between QuerySelector and the
output callback.  Time-based limiters register with the app Scheduler; in
playback mode virtual time drives the flushes deterministically.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..query_api.query import OutputRate, OutputRateType
from .event import CURRENT, EXPIRED, EventChunk
from .processor import Processor


class PassThroughRateLimiter(Processor):
    def process(self, chunk: EventChunk):
        self.send_next(chunk)


class _EventCountLimiter(Processor):
    """`output {all|first|last} every N events`."""

    def __init__(self, n: int, mode: str, group_by_names: Optional[List[str]]):
        super().__init__()
        self.n = n
        self.mode = mode
        self.group_by_names = group_by_names or []
        self.counter = 0
        self.pending: List[EventChunk] = []
        self.last_per_group: Dict[Tuple, Tuple[EventChunk, int]] = {}

    def process(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        if self.mode == "all":
            self.pending.append(chunk)
            self.counter += len(chunk)
            if self.counter >= self.n:
                out = EventChunk.concat(self.pending)
                self.pending = []
                self.counter = 0
                self.send_next(out)
            return
        # first / last need per-event window positions
        for i in range(len(chunk)):
            row = chunk.slice(i, i + 1)
            pos = self.counter % self.n
            if self.mode == "first":
                if pos == 0:
                    if self.group_by_names:
                        key = self._key(chunk, i)
                        self.send_next(row)
                    else:
                        self.send_next(row)
                elif self.group_by_names:
                    key = self._key(chunk, i)
                    if key not in self.last_per_group:
                        self.last_per_group[key] = (row, self.counter)
                        self.send_next(row)
            else:  # last
                if self.group_by_names:
                    self.last_per_group[self._key(chunk, i)] = (row, self.counter)
                else:
                    self.last_per_group[()] = (row, self.counter)
            self.counter += 1
            if self.counter % self.n == 0:
                if self.mode == "last":
                    for (r, _) in self.last_per_group.values():
                        self.send_next(r)
                self.last_per_group.clear()

    def _key(self, chunk: EventChunk, i: int) -> Tuple:
        return tuple(chunk.columns[g][i] for g in self.group_by_names
                     if g in chunk.columns)


class _TimeLimiter(Processor):
    """`output {all|first|last} every T` — flush on scheduler ticks."""

    def __init__(self, ms: int, mode: str, app_ctx,
                 group_by_names: Optional[List[str]]):
        super().__init__()
        self.ms = ms
        self.mode = mode
        self.app_ctx = app_ctx
        self.group_by_names = group_by_names or []
        self.pending: List[EventChunk] = []
        self.first_sent: Dict[Tuple, bool] = {}
        self.last_rows: Dict[Tuple, EventChunk] = {}
        self._armed = False

    def _arm(self, now: int):
        if not self._armed:
            self._armed = True
            self.app_ctx.scheduler.notify_at(now + self.ms, self._flush)

    def process(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        now = int(chunk.timestamps[-1])
        if self.mode == "all":
            self.pending.append(chunk)
        elif self.mode == "first":
            for i in range(len(chunk)):
                key = self._key(chunk, i)
                if not self.first_sent.get(key):
                    self.first_sent[key] = True
                    self.send_next(chunk.slice(i, i + 1))
        else:  # last
            for i in range(len(chunk)):
                self.last_rows[self._key(chunk, i)] = chunk.slice(i, i + 1)
        self._arm(now)

    def _key(self, chunk, i):
        return tuple(chunk.columns[g][i] for g in self.group_by_names
                     if g in chunk.columns)

    def _flush(self, now: int):
        self._armed = False
        if self.mode == "all" and self.pending:
            out = EventChunk.concat(self.pending)
            self.pending = []
            self.send_next(out)
        elif self.mode == "first":
            self.first_sent.clear()
        elif self.mode == "last" and self.last_rows:
            rows = list(self.last_rows.values())
            self.last_rows.clear()
            self.send_next(EventChunk.concat(rows))
        # re-arm only when new events arrive (reference keeps a running timer;
        # arming lazily avoids idle wakeups)


class SnapshotRateLimiter(Processor):
    """`output snapshot every T`.

    Reference dispatch (ratelimit/snapshot/WrappedSnapshotOutputRateLimiter
    .java:86-125): windowed query WITHOUT aggregators re-emits the full
    current window contents each tick (WindowedPerSnapshotOutputRateLimiter
    .java:75-104 — CURRENT adds, EXPIRED removes the first equal event, RESET
    clears); queries with aggregators (or no window) re-emit the latest value
    per group-by key (GroupByPerSnapshotOutputRateLimiter / PerSnapshot…)."""

    def __init__(self, ms: int, app_ctx, group_by_names: Optional[List[str]],
                 windowed: bool = False, has_aggregates: bool = True):
        super().__init__()
        self.ms = ms
        self.app_ctx = app_ctx
        self.group_by_names = group_by_names or []
        self.window_mode = windowed and not has_aggregates
        self.snapshot: Dict[Tuple, EventChunk] = {}
        self.window_events: List[EventChunk] = []   # single-row chunks
        self._armed = False

    @staticmethod
    def _row_key(chunk: EventChunk, i: int) -> Tuple:
        return tuple(np.asarray(chunk.columns[c][i]).item()
                     for c in sorted(chunk.columns))

    def process(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        if self.window_mode:
            # the QuerySelector upstream masks chunks to CURRENT|EXPIRED, so
            # window tracking needs only add/remove (batch windows clear via
            # their per-row EXPIRED emission, never via RESET)
            for i in range(len(chunk)):
                t = chunk.types[i]
                if t == CURRENT:
                    self.window_events.append(chunk.slice(i, i + 1))
                elif t == EXPIRED:
                    key = self._row_key(chunk, i)
                    for j, row in enumerate(self.window_events):
                        if self._row_key(row, 0) == key:
                            del self.window_events[j]
                            break
        else:
            cur = chunk.only(CURRENT)
            for i in range(len(cur)):
                key = tuple(cur.columns[g][i] for g in self.group_by_names
                            if g in cur.columns)
                self.snapshot[key] = cur.slice(i, i + 1)
        now = int(chunk.timestamps[-1])
        if not self._armed:
            self._armed = True
            self.app_ctx.scheduler.notify_at(now + self.ms, self._tick)

    def _tick(self, now: int):
        rows = self.window_events if self.window_mode \
            else list(self.snapshot.values())
        if rows:
            out = EventChunk.concat(list(rows))
            out = out.with_timestamps(np.full(len(out), now, np.int64))
            self.send_next(out)
            self.app_ctx.scheduler.notify_at(now + self.ms, self._tick)
        else:
            self._armed = False


def build_rate_limiter(rate: Optional[OutputRate], app_ctx,
                       group_by_names: Optional[List[str]],
                       windowed: bool = False,
                       has_aggregates: bool = True) -> Processor:
    if rate is None:
        return PassThroughRateLimiter()
    mode = {OutputRateType.ALL: "all", OutputRateType.FIRST: "first",
            OutputRateType.LAST: "last"}.get(rate.type, "all")
    if rate.type == OutputRateType.SNAPSHOT:
        return SnapshotRateLimiter(rate.every_ms, app_ctx, group_by_names,
                                   windowed, has_aggregates)
    if rate.every_events is not None:
        return _EventCountLimiter(rate.every_events, mode, group_by_names)
    return _TimeLimiter(rate.every_ms, mode, app_ctx, group_by_names)
