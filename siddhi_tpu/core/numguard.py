"""Runtime numeric sentinels — the live half of the NS0xx verifier.

The static pass (analysis/ranges.py) predicts where arithmetic can go
wrong; this module watches whether it actually does.  When armed via
``SIDDHI_TPU_NUMGUARD=1``, the aggregation compilers check the arrays
they ALREADY fetch at the host rim (gagg/wagg retire paths, the iagg
slab sync) for non-finite values, exact-int magnitudes nearing the
2^31 overflow ceiling and count lanes nearing int32 saturation, and
``ops/ts32.rebase_offsets`` reports horizon headroom.  The grouped-agg
device step additionally emits a tiny sentinel plane — flags folded
from the ``gsum``/``gcnt`` planes the step already produces, so match
outputs stay bit-identical with the guard on or off (asserted by
tests/test_numguard.py).

Trips surface three ways:

* ``siddhi_numeric_*`` Prometheus series (core/statistics exposition)
* ``NS101`` incident bundles on the flight-recorder bus
  (``SIDDHI_TPU_FLIGHT``), rate-limited per site
* the ``numguard`` section of GET /stats

Off by default and zero-cost when off: every hook checks
:func:`numguard_enabled` before touching an array.  Mirrors the PR 13
lock-witness pattern (core/lockwitness.py): static verdict, runtime
witness, same catalog family.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

NUMGUARD_ENV = "SIDDHI_TPU_NUMGUARD"

#: magnitude fraction of a ceiling that counts as "near" — trips fire
#: BEFORE the wrap so an operator gets warning, not wreckage
NEAR_FRACTION = 0.9

#: exact-int ceiling of the gagg split-accumulator lanes
#: (ops/grouped_agg.INT_EXACT_MAX) and the int32 count planes
INT_CEIL = float(1 << 31)

#: f32 exact-integer cliff — the iagg naive-slab precision budget the
#: static NS003 verdict bounds statically
F32_EXACT = float(1 << 24)

#: max NS101 flight incidents per (site, kind) — sentinels keep
#: counting after that, the bus stays quiet
MAX_INCIDENTS_PER_SITE = 3

NUMERIC_TYPES = [
    ("siddhi_numeric_nonfinite_total", "counter",
     "Non-finite values caught by NUMGUARD in float accumulator lanes"),
    ("siddhi_numeric_int_near_overflow_total", "counter",
     "Exact-int accumulator magnitudes past 90% of the 2^31 ceiling"),
    ("siddhi_numeric_count_near_saturation_total", "counter",
     "int32 count-lane values past 90% of the 2^31 ceiling"),
    ("siddhi_numeric_precision_exceeded_total", "counter",
     "Naive-f32 slab sums past the 2^24 exact-integer budget (NS003 "
     "witnessed live)"),
    ("siddhi_numeric_ts_rebase_total", "counter",
     "ts32 horizon rebase events observed by NUMGUARD"),
    ("siddhi_numeric_ts_headroom_ms", "gauge",
     "Remaining int32-ms horizon headroom at the last ts32 rebase"),
    ("siddhi_numeric_sentinel_trips_total", "counter",
     "NS101 sentinel trips (per site and kind)"),
]


def numguard_enabled() -> bool:
    """Env opt-in, read per call (cheap) so tests can flip it."""
    return os.environ.get(NUMGUARD_ENV, "").strip().lower() in (
        "1", "true", "on", "yes")


class NumericSentinels:
    """Per-app trip counters.  Thread-safe; hooks run at the host rim
    (outside the jit) so everything here is plain numpy + a lock, the
    DeviceTelemetry bookkeeping pattern."""

    def __init__(self, app_name: str):
        self.app_name = app_name
        self._lock = threading.Lock()
        #: (site, kind) -> trip count
        self._trips: Dict[tuple, int] = {}
        #: (site, kind) -> NS101 incidents already emitted
        self._incidents: Dict[tuple, int] = {}
        self._rebase_total = 0
        self._headroom_ms: Optional[int] = None

    # ------------------------------------------------------------ hooks

    def observe_floats(self, site: str, arr) -> int:
        """Count non-finite entries in a float accumulator plane the
        caller already fetched.  Returns the trip count."""
        import numpy as np
        a = np.asarray(arr)
        if a.size == 0 or a.dtype.kind not in "fc":
            return 0
        n = int(np.count_nonzero(~np.isfinite(a)))
        if n:
            self._trip(site, "nonfinite", n,
                       {"values_nonfinite": n, "plane_size": int(a.size)})
        return n

    def observe_ints(self, site: str, arr,
                     ceil: float = INT_CEIL) -> int:
        """Exact-int accumulator magnitudes nearing their ceiling."""
        import numpy as np
        a = np.asarray(arr)
        if a.size == 0:
            return 0
        n = int(np.count_nonzero(np.abs(a.astype(np.float64))
                                 >= NEAR_FRACTION * ceil))
        if n:
            self._trip(site, "int_near_overflow", n,
                       {"lanes_near_ceiling": n, "ceiling": ceil})
        return n

    def observe_counts(self, site: str, arr) -> int:
        """int32 count lanes nearing 2^31 saturation."""
        import numpy as np
        a = np.asarray(arr)
        if a.size == 0:
            return 0
        n = int(np.count_nonzero(a.astype(np.float64)
                                 >= NEAR_FRACTION * INT_CEIL))
        if n:
            self._trip(site, "count_near_saturation", n,
                       {"lanes_near_ceiling": n})
        return n

    def observe_precision(self, site: str, arr,
                          budget: float = F32_EXACT) -> int:
        """Naive-f32 slab sums past the exact-integer budget — the live
        witness for the static NS003 verdict."""
        import numpy as np
        a = np.asarray(arr)
        if a.size == 0:
            return 0
        finite = np.abs(np.where(np.isfinite(
            a.astype(np.float64)), a, 0.0).astype(np.float64))
        n = int(np.count_nonzero(finite > budget))
        if n:
            self._trip(site, "precision_exceeded", n,
                       {"lanes_past_budget": n, "budget": budget})
        return n

    def observe_sentinel_plane(self, site: str, plane) -> int:
        """Fold a device-computed sentinel plane (the [3] int32 flag
        counts from ops/grouped_agg.sentinel_plane: int near-overflow,
        count near-saturation, non-finite float lanes)."""
        import numpy as np
        a = np.asarray(plane).reshape(-1)
        if a.size < 3:
            return 0
        near_int, near_cnt, nonfin = int(a[0]), int(a[1]), int(a[2])
        if near_int:
            self._trip(site, "int_near_overflow", near_int,
                       {"lanes_near_ceiling": near_int,
                        "source": "device_plane"})
        if near_cnt:
            self._trip(site, "count_near_saturation", near_cnt,
                       {"lanes_near_ceiling": near_cnt,
                        "source": "device_plane"})
        if nonfin:
            self._trip(site, "nonfinite", nonfin,
                       {"values_nonfinite": nonfin,
                        "source": "device_plane"})
        return near_int + near_cnt + nonfin

    def note_rebase(self, site: str, headroom_ms: int) -> None:
        """ts32 rebase observed; ``headroom_ms`` is the remaining
        horizon after the shift."""
        with self._lock:
            self._rebase_total += 1
            self._headroom_ms = int(headroom_ms)
        if headroom_ms <= 0:
            self._trip(site, "ts_horizon_exhausted", 1,
                       {"headroom_ms": int(headroom_ms)})

    # ------------------------------------------------------- internals

    def _trip(self, site: str, kind: str, n: int,
              detail: Dict[str, Any]) -> None:
        key = (site, kind)
        with self._lock:
            self._trips[key] = self._trips.get(key, 0) + n
            emitted = self._incidents.get(key, 0)
            emit = emitted < MAX_INCIDENTS_PER_SITE
            if emit:
                self._incidents[key] = emitted + 1
        if emit:
            try:
                from .flight import flight
                flight().emit("numeric_sentinel", app=self.app_name,
                              detail={"code": "NS101", "site": site,
                                      "kind": kind, "trips": n,
                                      **detail})
            except Exception:   # noqa: BLE001 — sentinel reporting must
                pass            # never make a numeric fault worse

    # -------------------------------------------------------- surfaces

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            trips = {f"{site}:{kind}": n
                     for (site, kind), n in sorted(self._trips.items())}
            return {"app": self.app_name,
                    "armed": numguard_enabled(),
                    "trips": trips,
                    "trips_total": sum(self._trips.values()),
                    "ts_rebase_total": self._rebase_total,
                    "ts_headroom_ms": self._headroom_ms}

    def prometheus_lines(self) -> List[str]:
        _KIND_SERIES = {
            "nonfinite": "siddhi_numeric_nonfinite_total",
            "int_near_overflow": "siddhi_numeric_int_near_overflow_total",
            "count_near_saturation":
                "siddhi_numeric_count_near_saturation_total",
            "precision_exceeded":
                "siddhi_numeric_precision_exceeded_total",
        }
        out: List[str] = []
        with self._lock:
            items = sorted(self._trips.items())
            rebase, headroom = self._rebase_total, self._headroom_ms
        from .statistics import _fmt_labels
        by_series: Dict[tuple, int] = {}
        for (site, kind), n in items:
            series = _KIND_SERIES.get(kind)
            if series:
                by_series[(series, site)] = \
                    by_series.get((series, site), 0) + n
            out.append(
                "siddhi_numeric_sentinel_trips_total"
                f"{_fmt_labels({'app': self.app_name, 'site': site, 'kind': kind})}"
                f" {n}")
        for (series, site), n in sorted(by_series.items()):
            out.append(
                f"{series}"
                f"{_fmt_labels({'app': self.app_name, 'site': site})} {n}")
        if rebase:
            out.append("siddhi_numeric_ts_rebase_total"
                       f"{_fmt_labels({'app': self.app_name})} {rebase}")
        if headroom is not None:
            out.append("siddhi_numeric_ts_headroom_ms"
                       f"{_fmt_labels({'app': self.app_name})} {headroom}")
        return out

    def reset(self) -> None:
        with self._lock:
            self._trips.clear()
            self._incidents.clear()
            self._rebase_total = 0
            self._headroom_ms = None


# ------------------------------------------------------------- registry

_REGISTRY: Dict[str, NumericSentinels] = {}
_REG_LOCK = threading.Lock()


def numeric_sentinels(app_name: str,
                      create: bool = True) -> Optional[NumericSentinels]:
    """Per-app sentinel holder; process-global like the flight recorder
    so rim hooks and the REST surface resolve the same instance."""
    with _REG_LOCK:
        s = _REGISTRY.get(app_name)
        if s is None and create:
            s = _REGISTRY[app_name] = NumericSentinels(app_name)
        return s


def all_numeric_sentinels() -> List[NumericSentinels]:
    with _REG_LOCK:
        return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def reset_numguard() -> None:
    """Test hook: drop every per-app holder."""
    with _REG_LOCK:
        _REGISTRY.clear()
