"""Stream junctions, input handlers and callbacks.

(reference: stream/StreamJunction.java — per-stream pub/sub hub with sync mode
and @Async disruptor ring-buffer mode, @OnError fault-stream routing;
stream/input/{InputManager,InputHandler,InputEntryValve,InputDistributor}.java;
stream/output/StreamCallback.java; query/output/callback/QueryCallback.java.)

TPU-native shape: receivers exchange columnar EventChunks, so one `send` can
carry a whole micro-batch.  @Async mode replaces the LMAX disruptor with a
bounded queue + worker thread that re-batches pending events into larger chunks
(the host-side analogue of double-buffered device feeding).
"""
from __future__ import annotations

import logging
import queue
import threading
import time
import traceback
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..query_api.annotation import find_annotation
from ..query_api.definition import StreamDefinition
from ..utils.errors import BufferOverflowError, SiddhiAppRuntimeException
from .context import SiddhiAppContext
from .event import CURRENT, EXPIRED, Event, EventChunk, LazyEvents
from .ledger import ledger as _ledger, ledger_enabled
from .hotpath import hot_path
from .lockwitness import maybe_wrap
from .profiling import rim_stats
from .threads import engine_thread_name
from .tracing import tracer as _tracer

log = logging.getLogger(__name__)

FAULT_PREFIX = "!"

_RIM = rim_stats()
_LED = _ledger()


class StreamCallback:
    """User callback attached to a stream (reference
    stream/output/StreamCallback.java).  Subclass and override `receive`.

    This is the legacy per-event compatibility shim: ``receive`` gets a
    list-like ``LazyEvents`` view of the delivered chunk that builds the
    ``Event`` objects on first element access — a callback that only
    counts or ignores its events stays on the zero-materialization fast
    path.  Subscribe a ``ColumnarStreamCallback`` instead to receive the
    columns themselves with no per-event decode at all."""

    def __init__(self, fn: Optional[Callable[[Sequence[Event]], None]] = None):
        self._fn = fn
        self.stream_definition: Optional[StreamDefinition] = None

    def receive(self, events: Sequence[Event]):
        if self._fn is not None:
            self._fn(events)

    # junction-facing
    def receive_chunk(self, chunk: EventChunk):
        ev = LazyEvents(chunk.only(CURRENT, EXPIRED))
        if ev:
            with _LED.span("publish"):
                self.receive(ev)


class ColumnarStreamCallback:
    """Columnar stream callback: receives the delivered ``EventChunk``
    itself (CURRENT/EXPIRED lanes), no per-event materialization — the
    egress counterpart of ``InputHandler.send_batch``.  Subclass and
    override ``receive``, or pass ``fn(chunk)``.  Registers through the
    same ``add_callback`` as the legacy ``StreamCallback``."""

    def __init__(self, fn: Optional[Callable[[EventChunk], None]] = None):
        self._fn = fn
        self.stream_definition: Optional[StreamDefinition] = None

    def receive(self, chunk: EventChunk):
        if self._fn is not None:
            self._fn(chunk)

    # junction-facing
    def receive_chunk(self, chunk: EventChunk):
        c = chunk.only(CURRENT, EXPIRED)
        if not c.is_empty:
            with _LED.span("publish"):
                self.receive(c)


class QueryCallback:
    """Per-query callback with (timestamp, current[], expired[]) signature
    (reference query/output/callback/QueryCallback.java)."""

    def __init__(self, fn: Optional[Callable[[int, Optional[List[Event]],
                                              Optional[List[Event]]], None]] = None):
        self._fn = fn

    def receive(self, timestamp: int, current: Optional[List[Event]],
                expired: Optional[List[Event]]):
        if self._fn is not None:
            self._fn(timestamp, current, expired)

    def receive_chunk(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        cur = LazyEvents(chunk.only(CURRENT))
        exp = LazyEvents(chunk.only(EXPIRED))
        if not cur and not exp:
            return
        ts = int(chunk.timestamps[-1])
        with _LED.span("publish"):
            self.receive(ts, cur or None, exp or None)


class _FlushBarrier:
    """Queue sentinel for StreamJunction.flush: one copy is enqueued per
    worker; workers rendezvous at an internal barrier (so every in-hand
    delivery has finished), then exactly one flushes the receivers and
    signals done.  Exact for any worker count."""

    def __init__(self, n_workers: int):
        self.sync = threading.Barrier(max(n_workers, 1))
        self.done = threading.Event()

    def __len__(self):          # rides the chunk queue
        return 0

    def arrive(self, flush_fn):
        try:
            i = self.sync.wait(timeout=600.0)
        except threading.BrokenBarrierError:
            i = 0               # a peer died (drain race): flush anyway
        if i == 0:
            try:
                flush_fn()
            finally:
                self.done.set()


class StreamJunction:
    """Pub/sub hub for one stream."""

    def __init__(self, definition: StreamDefinition,
                 app_ctx: SiddhiAppContext, fault_junction=None):
        self.definition = definition
        self.app_ctx = app_ctx
        self.receivers: List[Any] = []   # objects with receive_chunk(chunk)
        self.fault_junction: Optional[StreamJunction] = fault_junction
        self.on_error_action = "LOG"
        self.throughput_tracker = None
        # async config (reference @Async(buffer.size, workers, batch.size.max))
        self.is_async = False
        self.buffer_size = 1024
        self.workers = 1
        self.batch_size_max = 256
        # ingest protection (core/overload.py; None when the
        # SIDDHI_TPU_INGEST_GUARD kill switch is off)
        self.overload = None        # OverloadConfig for @Async admission
        self.validator = None       # IngestValidator from @quarantine(...)
        self._queue: Optional[queue.Queue] = None
        self._worker_threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._drain = threading.Event()
        self._flush_lock = maybe_wrap(
            threading.Lock(), "core.stream.StreamJunction._flush_lock")
        self._configure_from_annotations()

    @property
    def quiescent(self) -> bool:
        """No queued chunks and no delivery in flight (async mode).
        Queue.unfinished_tasks is atomic under the queue's own lock: a
        put increments it and the worker's task_done() (after delivery
        completes) decrements — no popped-but-undelivered window."""
        q = self._queue
        if not self.is_async or q is None:
            return True
        return q.unfinished_tasks == 0

    def queue_depth(self) -> int:
        """Chunks waiting in the @Async buffer right now — the
        BufferedEventsTracker supplier (core/statistics.py)."""
        q = self._queue
        return q.qsize() if q is not None else 0

    def _configure_from_annotations(self):
        from .overload import (IngestValidator, OverloadConfig,
                               QuarantineConfig, guard_enabled)
        guarded = guard_enabled()
        ann = find_annotation(self.definition.annotations, "async")
        if ann is not None:
            self.is_async = True
            self.buffer_size = int(ann.get("buffer.size", "1024"))
            self.workers = int(ann.get("workers", "1"))
            self.batch_size_max = int(ann.get("batch.size.max", "256"))
            if guarded:
                self.overload = OverloadConfig.from_annotation(
                    ann, self.buffer_size)
        q_ann = find_annotation(self.definition.annotations, "quarantine")
        if q_ann is not None and guarded:
            self.validator = IngestValidator(
                self.definition, QuarantineConfig.from_annotation(q_ann))
        on_err = find_annotation(self.definition.annotations, "onerror")
        if on_err is not None:
            self.on_error_action = (on_err.get("action", "LOG") or "LOG").upper()
            if self.on_error_action == "WAIT":
                from .resilience import RetryPolicy
                self.wait_policy = RetryPolicy.from_options(
                    on_err.as_dict(),
                    RetryPolicy(max_attempts=8, base_delay_s=0.01,
                                max_delay_s=0.5, budget_s=10.0))

    # ------------------------------------------------------------ lifecycle

    def start(self):
        if self.is_async and self._queue is None:
            self._queue = queue.Queue(maxsize=self.buffer_size)
            self._stop.clear()
            self._drain.clear()
            for i in range(self.workers):
                t = threading.Thread(
                    target=self._worker_loop, daemon=True,
                    name=engine_thread_name(
                        "siddhi-junction-", self.definition.id, i))
                t.start()
                self._worker_threads.append(t)

    def stop(self):
        """Drain-then-stop: every queued chunk is delivered before workers
        exit (the reference's shutdown drains the disruptor ring; setting
        the stop flag first would drop whatever is still queued).
        Sentinel-free: workers keep consuming until the queue is empty AND
        the drain flag is up, so no worker can starve another.

        The drain is bounded by a TOTAL deadline (@Async(drain.timeout.ms),
        default 600s — generous because a queued first delivery can hide a
        remote AOT compile).  A receiver wedged past the deadline gets a
        forced stop: the stop flag goes up, leftover queued chunks are
        discarded (counted as shed reason='drain_timeout') and barriers
        released, so shutdown cannot loop indefinitely on a dead consumer."""
        if self._queue is not None:
            q = self._queue
            self._drain.set()
            total_s = (self.overload.drain_timeout_s
                       if self.overload is not None else 600.0)
            deadline = time.monotonic() + total_s
            for t in self._worker_threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            wedged = [t for t in self._worker_threads if t.is_alive()]
            if wedged:
                self._stop.set()
                dropped = self._discard_queued(q, reason="drain_timeout")
                log.error(
                    "@Async drain on '%s' timed out after %.1fs with %d "
                    "wedged worker(s); force-stopped, dropping %d queued "
                    "event(s) (%s)", self.definition.id, total_s,
                    len(wedged), dropped, BufferOverflowError.__name__)
                for t in wedged:
                    t.join(timeout=0.5)
            self._worker_threads.clear()
            self._queue = None
        self._stop.set()

    def _discard_queued(self, q: queue.Queue, reason: str) -> int:
        """Empty `q`, releasing any flush barriers and counting dropped
        events as shed; returns the dropped-event count."""
        dropped = 0
        while True:
            try:
                item = q.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, _FlushBarrier):
                item.done.set()
            else:
                dropped += len(item)
            q.task_done()
        if dropped:
            m = self._ingest_metrics()
            if m is not None:
                m.ingest_shed_total.inc(dropped, stream=self.definition.id,
                                        reason=reason)
        return dropped

    def _worker_loop(self):
        """Re-batches queued chunks up to batch_size_max before delivery
        (reference util/event/handler/StreamHandler.java re-batching).
        When the queue goes idle (or on drain), flushes receivers that
        pipeline device work (plan/planner.py DevicePatternRuntime) so
        deferred matches never hang waiting for the next event."""
        q = self._queue     # local ref: stop() clears the attribute on a
        delivered = False   # forced drain-timeout stop while we may still
        while not self._stop.is_set():  # be wedged inside a receiver
            try:
                item = q.get(timeout=0.1)
            except queue.Empty:
                if delivered:
                    self._flush_receivers()
                    delivered = False
                if self._drain.is_set():
                    break       # drained: queue empty after drain request
                continue
            if isinstance(item, _FlushBarrier):
                delivered = False
                try:
                    item.arrive(self._flush_receivers)
                finally:
                    q.task_done()
                continue
            batch = [item]
            n = len(item)
            barrier = None
            while n < self.batch_size_max:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    break
                if isinstance(nxt, _FlushBarrier):
                    barrier = nxt
                    break
                batch.append(nxt)
                n += len(nxt)
            merged = EventChunk.concat(batch) if len(batch) > 1 else batch[0]
            if ledger_enabled():
                # queue stage: enqueue stamp -> this dequeue, per popped
                # chunk; the merged chunk restarts its timeline here so
                # _deliver's dispatch gap starts at the dequeue boundary
                now_ns = time.perf_counter_ns()
                for c in batch:
                    if c.ledger_ns is not None:
                        _LED.record("queue", now_ns - c.ledger_ns)
                merged.ledger_ns = now_ns
            try:
                self._deliver(merged)
                delivered = True
                if barrier is not None:
                    delivered = False
                    barrier.arrive(self._flush_receivers)
            finally:
                # one task_done per popped item: the batch's extra pops
                # and a trailing barrier pop all complete here
                for _ in range(len(batch) + (1 if barrier is not None
                                             else 0)):
                    q.task_done()
        if delivered:
            self._flush_receivers()

    def _flush_receivers(self):
        for r in list(self.receivers):
            f = getattr(r, "flush", None)
            if f is not None:
                try:
                    f()
                except Exception as e:  # noqa: BLE001 — @OnError boundary
                    self._handle_error(
                        EventChunk.empty(self.definition.attribute_names), e)

    def flush(self):
        """Synchronous flush: when this returns, every chunk already sent
        has been delivered and any pipelined device work retired (matches
        handed to callbacks).  Async mode rides one barrier copy per
        worker through the queue (exact for any worker count — workers
        rendezvous before one flushes); falls back to a direct receiver
        flush when the workers are gone (racing stop()/shutdown).  The
        wait is generous because a first delivery can hide a remote AOT
        compile."""
        q = self._queue
        workers = list(self._worker_threads)
        if threading.current_thread() in workers:
            # a worker calling flush() from inside its own delivery (e.g.
            # persist() from a callback) would wait forever for its own
            # barrier copy — its in-hand delivery IS finished from the
            # caller's perspective, so flush receivers directly
            self._flush_receivers()
            return
        if self.is_async and q is not None and workers and \
                not self._drain.is_set():
            # serialize concurrent flushes: two barriers' copies
            # interleaved across workers would stall both rendezvous
            with self._flush_lock:
                b = _FlushBarrier(len(workers))
                for _ in workers:
                    q.put(b)
                while not b.done.wait(timeout=1.0):
                    if not any(t.is_alive() for t in workers):
                        self._flush_receivers()   # stop() won the race
                        return
        else:
            self._flush_receivers()

    # ------------------------------------------------------------ sending

    def subscribe(self, receiver):
        if receiver not in self.receivers:
            self.receivers.append(receiver)

    def unsubscribe(self, receiver):
        if receiver in self.receivers:
            self.receivers.remove(receiver)

    def send(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        if self.throughput_tracker is not None:
            self.throughput_tracker.event_in(len(chunk))
        wd = getattr(self.app_ctx, "watchdog", None)
        if wd is not None:
            # any event movement counts as ingest progress: a dispatch
            # storm is, by definition, dispatching with none
            wd.note_progress(len(chunk))
        if chunk.ledger_ns is None and ledger_enabled():
            # internal producers (query output fan-in, fault routes)
            # start their timeline here: queue-wait / dispatch-gap
            # attribution needs a boundary stamp on every chunk
            chunk.ledger_ns = time.perf_counter_ns()
        if self.is_async and self._queue is not None:
            if self.overload is not None:
                self._admit(chunk)
            else:
                # kill switch off: legacy unbounded blocking put
                self._queue.put(chunk)
        else:
            self._deliver(chunk)

    # ------------------------------------------------------ admission control

    def saturation(self) -> float:
        """@Async buffer depth as a fraction of buffer.size (0.0 sync)."""
        q = self._queue
        if not self.is_async or q is None or self.buffer_size <= 0:
            return 0.0
        return q.qsize() / self.buffer_size

    def saturated(self) -> bool:
        """Above the high watermark right now (GET /health 'degraded')."""
        ov = self.overload
        if ov is None or self._queue is None:
            return False
        return self._queue.qsize() >= ov.high_chunks

    def _admit(self, chunk: EventChunk):
        """Policy-driven admission (@Async(overload=...)).  Every path is
        bounded: the engine can shed, store, or raise — never wedge."""
        q = self._queue
        ov = self.overload
        m = self._ingest_metrics()
        sid = self.definition.id
        n = len(chunk)
        if ov.policy == "SHED_OLDEST":
            self._shed_to_low(q, m)
        elif ov.policy == "SHED_NEW":
            if q.qsize() >= ov.high_chunks:
                if m is not None:
                    m.ingest_shed_total.inc(n, stream=sid, reason="shed_new")
                return
        elif ov.policy == "STORE":
            if q.qsize() >= ov.high_chunks:
                store = self._error_store()
                if store is not None:
                    from .resilience import make_entry
                    rt = getattr(self.app_ctx, "runtime", None)
                    store.store(make_entry(
                        rt.name if rt is not None else "", sid, "overload",
                        BufferOverflowError(
                            f"@Async buffer on '{sid}' above high watermark "
                            f"({q.qsize()}/{self.buffer_size} chunks)"),
                        chunk.to_events()))
                    if m is not None:
                        m.ingest_shed_total.inc(n, stream=sid,
                                                reason="stored")
                    return
                # no store configured: degrade to bounded BLOCK below
                # (the analyzer flags this config as SA062)
        try:
            q.put(chunk, timeout=ov.block_timeout_s)
        except queue.Full:
            if m is not None:
                m.ingest_overflow_total.inc(n, stream=sid)
            self._handle_error(chunk, BufferOverflowError(
                f"@Async buffer on '{sid}' still full after "
                f"{ov.block_timeout_s:.3f}s ({self.buffer_size} chunks, "
                f"policy {ov.policy})"))
        else:
            if m is not None:
                m.ingest_admitted_total.inc(n, stream=sid)

    def _shed_to_low(self, q: queue.Queue, m):
        """SHED_OLDEST: at/above the high watermark, evict queued chunks
        down to the low watermark (hysteresis).  Flush barriers ride
        through: they are re-enqueued behind the survivors, never shed."""
        ov = self.overload
        if q.qsize() < ov.high_chunks:
            return
        shed = 0
        while q.qsize() > ov.low_chunks:
            try:
                item = q.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, _FlushBarrier):
                # guaranteed room: we just popped an entry and only
                # producers racing us could have refilled it — the put
                # below can block at most momentarily
                q.put(item)
                q.task_done()
                continue
            shed += len(item)
            q.task_done()
        if shed and m is not None:
            m.ingest_shed_total.inc(shed, stream=self.definition.id,
                                    reason="shed_oldest")

    def _ingest_metrics(self):
        rt = getattr(self.app_ctx, "runtime", None)
        return getattr(rt, "ingest_metrics", None)

    @hot_path("per-block fan-out to every subscriber")
    def _deliver(self, chunk: EventChunk):
        tr = _tracer()
        led = _LED if ledger_enabled() else None
        if led is not None and chunk.ledger_ns is not None:
            # dispatch gap: boundary stamp (dequeue / junction entry) ->
            # delivery start; consumed so a re-routed chunk (fault
            # junction) does not double count
            led.record("dispatch", time.perf_counter_ns() - chunk.ledger_ns)
            chunk.ledger_ns = None
        for r in list(self.receivers):
            try:
                if tr.enabled:
                    with tr.span("callback" if isinstance(
                            r, (StreamCallback, QueryCallback))
                            else "deliver",
                            stream=self.definition.id, n=len(chunk),
                            receiver=type(r).__name__):
                        self._recv_one(r, chunk, led)
                else:
                    self._recv_one(r, chunk, led)
            except Exception as e:  # noqa: BLE001 — @OnError boundary
                self._handle_error(chunk, e, receiver=r)

    @staticmethod
    def _recv_one(r, chunk: EventChunk, led):
        if led is None:
            r.receive_chunk(chunk)
            return
        # dispatch stage (exclusive): junction fan-out + host-side query
        # processing; the device/decode/publish work nested inside the
        # receiver carries its own spans and is subtracted automatically
        with led.span("dispatch"):
            r.receive_chunk(chunk)

    def _handle_error(self, chunk: EventChunk, e: Exception, receiver=None):
        from .flight import flight
        rt = getattr(self.app_ctx, "runtime", None)
        app_name = rt.name if rt is not None else ""
        flight().note_error(app_name, self.definition.id, e)
        if isinstance(e, BufferOverflowError):
            # incident bus: an admission overflow means load shedding is
            # losing events — dump a bundle while the ring still shows
            # the blocks leading up to it
            flight().emit("buffer_overflow", app=app_name,
                          detail={"stream": self.definition.id,
                                  "error": str(e)}, runtime=rt)
        action = self.on_error_action
        if action == "WAIT" and receiver is not None:
            # bounded blocking until downstream recovers: retry THIS
            # receiver with backoff; on budget/attempt exhaustion fall
            # through to STORE (when configured) else LOG
            if self._wait_retry(chunk, e, receiver):
                return
            action = "STORE"
        if action == "STREAM" and self.fault_junction is not None:
            # route into !stream with an extra _error attribute
            fault_def = self.fault_junction.definition
            cols = dict(chunk.columns)
            cols["_error"] = np.asarray([repr(e)] * len(chunk), object)
            fchunk = EventChunk(fault_def.attribute_names, chunk.timestamps,
                                chunk.types, cols)
            self.fault_junction.send(fchunk)
            return
        if action == "STORE" and self._error_store() is not None:
            from .resilience import make_entry
            rt = getattr(self.app_ctx, "runtime", None)
            app_name = rt.name if rt is not None else ""
            self._error_store().store(make_entry(
                app_name, self.definition.id, "stream", e,
                chunk.to_events()))
            m = self._metrics()
            if m is not None:
                m.errors_stored_total.inc(len(chunk),
                                          stream=self.definition.id,
                                          origin="stream")
            return
        log.error("Error processing stream '%s': %s\n%s",
                  self.definition.id, e, traceback.format_exc())
        if not isinstance(e, BufferOverflowError):
            # uncaught junction exception (no @OnError route absorbed it)
            flight().emit("junction_exception", app=app_name,
                          detail={"stream": self.definition.id,
                                  "error": f"{type(e).__name__}: {e}"},
                          runtime=rt)
        for listener in self.app_ctx.exception_listeners:
            listener(e)

    def _error_store(self):
        rt = getattr(self.app_ctx, "runtime", None)
        return getattr(rt, "error_store", None)

    def _metrics(self):
        rt = getattr(self.app_ctx, "runtime", None)
        return getattr(rt, "resilience_metrics", None)

    def _wait_retry(self, chunk: EventChunk, first_err: Exception,
                    receiver) -> bool:
        """@OnError(action='WAIT'): block (bounded) re-offering the chunk
        to the failed receiver until it recovers.  Returns True when the
        delivery eventually succeeded."""
        policy = getattr(self, "wait_policy", None)
        if policy is None:
            from .resilience import RetryPolicy
            policy = self.wait_policy = RetryPolicy(
                max_attempts=8, base_delay_s=0.01, max_delay_s=0.5,
                budget_s=10.0)
        m = self._metrics()
        for delay in policy.delays():
            if self._stop.wait(delay):
                return False
            if m is not None:
                m.onerror_wait_retries_total.inc(stream=self.definition.id)
            try:
                receiver.receive_chunk(chunk)
                return True
            except Exception as e:  # noqa: BLE001 — keep waiting
                first_err = e
        log.error("@OnError(WAIT) on '%s' gave up after %d attempts: %s",
                  self.definition.id, policy.max_attempts, first_err)
        return False


class InputHandler:
    """User-facing ingestion for one stream (reference
    stream/input/InputHandler.java:51-85: send(Object[]), send(Event),
    send(Event[]) — here additionally columnar `send_batch`).

    ``send_batch`` is the native path: columns flow junction-ward with no
    row detour.  ``send`` is a thin row-normalizing shim that coerces its
    rows into the same chunk shape and joins the shared chunk core
    (``_send_chunk``) — validation, clock observation, delivery and
    playback advance are one code path for both."""

    def __init__(self, junction: StreamJunction, app_ctx: SiddhiAppContext):
        self.junction = junction
        self.app_ctx = app_ctx
        self.definition = junction.definition
        # fair-share quota (@app:quota, core/overload.py) cached at
        # construction: the registry registers during annotation parsing
        # — before any handler exists — so the hot path below never
        # takes the process-global FairShare lock
        rt = getattr(app_ctx, "runtime", None)
        self.quota = getattr(rt, "quota", None)
        if self.quota is not None:
            from .overload import fair_share
            self._fair = fair_share()

    def send(self, data, timestamp: Optional[int] = None):
        """send(Object[]) / send(Event) / send([Event,...]) /
        send([Object[],...]) — per-event compatibility shim over the
        columnar core."""
        self.app_ctx.thread_barrier.pass_through()
        t0 = time.perf_counter_ns()
        rows: List[Sequence[Any]]
        stamps: List[int]
        if isinstance(data, Event):
            rows, stamps = [data.data], [data.timestamp]
        elif isinstance(data, (list, tuple)) and data and \
                isinstance(data[0], Event):
            rows = [e.data for e in data]
            stamps = [e.timestamp for e in data]
        else:
            now = timestamp if timestamp is not None \
                else self.app_ctx.current_time()
            rows, stamps = [list(data)], [now]
        if timestamp is not None:
            stamps = [timestamp] * len(rows)
        width = len(self.definition.attributes)
        for r in rows:
            if len(r) != width:
                raise SiddhiAppRuntimeException(
                    f"Stream '{self.definition.id}' expects {width} "
                    f"attributes {self.definition.attribute_names}, got "
                    f"{len(r)}: {list(r)!r}")
        v = self.junction.validator
        if v is None:
            chunk = EventChunk.from_rows(self.definition, rows, stamps)
        else:
            # quarantine path: coerce (with per-row salvage), split off
            # poison, and only let ADMITTED timestamps advance the clock
            # — a wrap-poison stamp must not drag virtual time with it
            from .overload import route_rejects
            rejects = []
            try:
                chunk = EventChunk.from_rows(self.definition, rows, stamps)
            except (TypeError, ValueError):
                rows, stamps, bad = v.salvage_rows(rows, stamps)
                rejects.append((v.REASON_TYPE, bad))
                chunk = EventChunk.from_rows(self.definition, rows, stamps)
            chunk, chunk_rejects = v.filter_chunk(chunk)
            rejects.extend((reason, c.to_events())
                           for reason, c in chunk_rejects)
            if rejects:
                route_rejects(self.junction, rejects)
        self._send_chunk(chunk, t0)

    def send_batch(self, columns, timestamps=None):
        """Columnar native path: dict name→array (+ optional int64
        timestamps)."""
        self.app_ctx.thread_barrier.pass_through()
        t0 = time.perf_counter_ns()
        names = self.definition.attribute_names
        n = len(next(iter(columns.values())))
        if timestamps is None:
            timestamps = np.full(n, self.app_ctx.current_time(), np.int64)
        ts_arr = np.asarray(timestamps, np.int64)
        chunk = EventChunk.from_columns(names, ts_arr, dict(columns))
        v = self.junction.validator
        if v is not None:
            from .overload import route_rejects
            chunk, chunk_rejects = v.filter_chunk(chunk)
            if chunk_rejects:
                route_rejects(self.junction,
                              [(reason, c.to_events())
                               for reason, c in chunk_rejects])
        self._send_chunk(chunk, t0)

    def _quota_shed(self, shed: int) -> None:
        """Per-tenant shed accounting + ONE flight bundle per breach
        episode (the latch resets when a send fully admits again)."""
        qt = self.quota
        rt = getattr(self.app_ctx, "runtime", None)
        m = getattr(rt, "ingest_metrics", None)
        if m is not None:
            m.ingest_shed_total.inc(shed, stream=self.definition.id,
                                    reason="quota")
        if not qt.breach:
            qt.breach = True
            try:
                from .flight import flight
                flight().emit(
                    "quota_breach", app=qt.app_name,
                    detail={"stream": self.definition.id, "shed": shed,
                            "rate": qt.rate, "burst": qt.burst},
                    runtime=rt)
            except Exception:   # noqa: BLE001 — shedding must never raise
                log.exception("quota-breach flight emit failed")

    @hot_path("per-block ingest core: clock observe + deliver")
    def _send_chunk(self, chunk: EventChunk, t0: int) -> None:
        """Shared chunk core: observe the clock, deliver, advance
        playback.  ``t0`` is the caller's entry stamp — everything up to
        delivery is host-rim time (RimStats)."""
        n = len(chunk)
        if n == 0:
            _RIM.rim_ns += time.perf_counter_ns() - t0
            return
        qt = self.quota
        if qt is not None:
            # fair-share admission (@app:quota): shed the tail of the
            # chunk that exceeds this tenant's token budget — UNDER the
            # per-stream @Async overload policies, which still apply to
            # whatever is admitted here
            take = qt.admit(n)
            self._fair.note(qt.app_name, take, n - take)
            if take < n:
                self._quota_shed(n - take)
                if take == 0:
                    _RIM.rim_ns += time.perf_counter_ns() - t0
                    return
                chunk = chunk.mask(np.arange(n) < take)
                n = take
            elif qt.breach:
                qt.breach = False     # budget recovered: episode closed
        mx = int(chunk.timestamps.max())
        self.app_ctx.timestamp_generator.observe_event_time(mx)
        now = time.perf_counter_ns()
        _RIM.rim_ns += now - t0
        if ledger_enabled():
            # ingress stage (validate/encode up to delivery) + the
            # event-time lag watermark: max admitted timestamp vs the
            # playback clock when replaying history, else the wall clock
            clock_ms = (self.app_ctx.current_time()
                        if self.app_ctx.timestamp_generator.in_playback
                        else time.time() * 1000.0)
            _LED.note_ingress(self.app_ctx.name, self.definition.id,
                              mx, clock_ms, now - t0)
            chunk.ledger_ns = now
        with _tracer().span("ingest.chunk", stream=self.definition.id, n=n):
            self.junction.send(chunk)
        if self.app_ctx.timestamp_generator.in_playback:
            self.app_ctx.scheduler.advance_to(mx)
