"""In-memory tables with primary-key / index support and compiled conditions.

(reference: table/InMemoryTable.java + table/holder/{List,Index}EventHolder
(@PrimaryKey/@Index hash indexes), compiled-condition planning in
util/parser/CollectionExpressionParser.java + util/collection/executor/* —
index-scan vs exhaustive-scan plans, and table/record/* SPI for external
stores.)

Columnar design: rows live in numpy columns; a condition is compiled once into
a vectorised program evaluated over all table rows per probing stream event,
with a hash-index fast path when the condition is `table.pk == <stream expr>`.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx, Scope
from ..query_api.annotation import find_annotation
from ..query_api.definition import TableDefinition
from ..query_api.expression import (And, Compare, CompareOp, Expression,
                                    Variable)
from .event import EventChunk
from .stateschema import ListOf, MapOf, Struct, persistent_schema

STREAM_QUAL = "__stream__"


class CompiledTableCondition:
    """Compiled `on` condition: vectorised over table rows, with per-stream-row
    scalar bindings; equality fast paths on the primary key or on a secondary
    `@Index` attribute (reference: CollectionExpressionParser's index-scan vs
    exhaustive-scan CollectionExecutor plans, util/collection/executor/*)."""

    def __init__(self, fn: Optional[CompiledExpr],
                 pk_probe: Optional[List[Tuple[str, CompiledExpr]]] = None,
                 index_probe: Optional[Tuple[str, CompiledExpr]] = None):
        self.fn = fn
        self.pk_probe = pk_probe       # [(table_attr, stream_value_expr)]
        # (indexed_attr, stream_value_expr): hash-probe candidates, then
        # evaluate `fn` over the candidate subset only
        self.index_probe = index_probe


class CompiledSetUpdate:
    def __init__(self, assignments: List[Tuple[str, CompiledExpr]]):
        self.assignments = assignments


@persistent_schema("table",
                   schema=Struct(columns=MapOf("column"),
                                 timestamps=ListOf("int")))
class InMemoryTable:
    def __init__(self, definition: TableDefinition):
        self.definition = definition
        self.names = definition.attribute_names
        self.columns: Dict[str, list] = {n: [] for n in self.names}
        self.timestamps: List[int] = []
        self.lock = threading.RLock()
        pk_ann = find_annotation(definition.annotations, "primarykey")
        self.primary_key: List[str] = pk_ann.positional() if pk_ann else []
        idx_ann = find_annotation(definition.annotations, "index")
        self.index_attrs: List[str] = idx_ann.positional() if idx_ann else []
        self._pk_index: Dict[Tuple, int] = {}
        self._indexes: Dict[str, Dict[Any, List[int]]] = {
            a: {} for a in self.index_attrs}
        self._cols_cache: Optional[Dict[str, np.ndarray]] = None
        self._ts_cache: Optional[np.ndarray] = None

    # ------------------------------------------------------------ basics

    def __len__(self):
        return len(self.timestamps)

    def _invalidate(self):
        self._cols_cache = None
        self._ts_cache = None

    def _ts_array(self) -> np.ndarray:
        if self._ts_cache is None:
            self._ts_cache = np.asarray(self.timestamps, np.int64)
        return self._ts_cache

    def _materialise(self) -> Dict[str, np.ndarray]:
        if self._cols_cache is None:
            from .event import dtype_for
            out = {}
            for a in self.definition.attributes:
                dt = dtype_for(a.type)
                if dt is object:
                    arr = np.empty(len(self.timestamps), object)
                    arr[:] = self.columns[a.name]
                else:
                    arr = np.asarray(self.columns[a.name], dt)
                out[a.name] = arr
            self._cols_cache = out
        return self._cols_cache

    def _rebuild_indexes(self):
        self._pk_index.clear()
        for d in self._indexes.values():
            d.clear()
        for i in range(len(self.timestamps)):
            self._index_row(i)

    def _index_row(self, i: int):
        if self.primary_key:
            key = tuple(self.columns[a][i] for a in self.primary_key)
            self._pk_index[key] = i
        for a in self.index_attrs:
            self._indexes[a].setdefault(self.columns[a][i], []).append(i)

    # ------------------------------------------------------------ ops

    def insert(self, chunk: EventChunk):
        with self.lock:
            overwrote = False
            for i in range(len(chunk)):
                if self.primary_key:
                    key = tuple(_item(chunk.columns[a][i])
                                for a in self.primary_key)
                    if key in self._pk_index:
                        # primary-key clash: overwrite existing row (reference
                        # rejects; overwrite matches update-or-insert use)
                        r = self._pk_index[key]
                        for n in self.names:
                            self.columns[n][r] = _item(chunk.columns[n][i])
                        overwrote = True
                        continue
                for n in self.names:
                    self.columns[n].append(_item(chunk.columns[n][i]))
                self.timestamps.append(int(chunk.timestamps[i]))
                self._index_row(len(self.timestamps) - 1)
            if overwrote and self.index_attrs:
                # overwritten rows may have moved index buckets
                self._rebuild_indexes()
            self._invalidate()

    def all_rows_chunk(self) -> EventChunk:
        cols = self._materialise()
        n = len(self.timestamps)
        return EventChunk(self.names, self._ts_array(),
                          np.zeros(n, np.int8), dict(cols))

    def _match_rows(self, cond: Optional[CompiledTableCondition],
                    stream_chunk: Optional[EventChunk],
                    row_i: Optional[int]) -> np.ndarray:
        """Table-row indices matching `cond` for stream row `row_i`."""
        n = len(self.timestamps)
        if n == 0:
            return np.empty(0, np.int64)
        if cond is None or (cond.fn is None and not cond.pk_probe):
            return np.arange(n)
        qual = {}
        if stream_chunk is not None and row_i is not None:
            qual[(STREAM_QUAL, 0)] = {nm: _item(stream_chunk.columns[nm][row_i])
                                      for nm in stream_chunk.names}
        if cond.pk_probe is not None:
            sctx = EvalCtx({}, np.zeros(1, np.int64), 1, qualified=qual)
            key = tuple(_item(_scalar(ce.fn(sctx)))
                        for _, ce in cond.pk_probe)
            r = self._pk_index.get(key)
            return np.asarray([r] if r is not None else [], np.int64)
        cols = self._materialise()
        if cond.index_probe is not None:
            # hash-probe the secondary index, then run the full condition
            # over the candidate rows only (candidates are in ascending row
            # order, so results keep full-scan order)
            attr, ce = cond.index_probe
            sctx = EvalCtx({}, np.zeros(1, np.int64), 1, qualified=qual)
            key = _item(_scalar(ce.fn(sctx)))
            cand = self._indexes[attr].get(key)
            if not cand:
                return np.empty(0, np.int64)
            cand = np.asarray(cand, np.int64)
            cctx = EvalCtx({k: v[cand] for k, v in cols.items()},
                           self._ts_array()[cand],
                           len(cand), qualified=qual)
            m = np.asarray(cond.fn.fn(cctx), bool)
            if m.ndim == 0:
                m = np.full(len(cand), bool(m))
            return cand[np.flatnonzero(m)]
        ctx = EvalCtx(dict(cols), self._ts_array(), n, qualified=qual)
        m = np.asarray(cond.fn.fn(ctx), bool)
        if m.ndim == 0:
            m = np.full(n, bool(m))
        return np.flatnonzero(m)

    def find(self, cond: Optional[CompiledTableCondition],
             stream_chunk: Optional[EventChunk] = None,
             row_i: Optional[int] = None) -> EventChunk:
        with self.lock:
            idx = self._match_rows(cond, stream_chunk, row_i)
            return self.all_rows_chunk().take(idx)

    def delete(self, stream_chunk: EventChunk, cond: CompiledTableCondition):
        with self.lock:
            doomed = set()
            for i in range(len(stream_chunk)):
                doomed.update(self._match_rows(cond, stream_chunk, i).tolist())
            if not doomed:
                return
            keep = [i for i in range(len(self.timestamps)) if i not in doomed]
            for n in self.names:
                self.columns[n] = [self.columns[n][i] for i in keep]
            self.timestamps = [self.timestamps[i] for i in keep]
            self._rebuild_indexes()
            self._invalidate()

    def update(self, stream_chunk: EventChunk, cond: CompiledTableCondition,
               cset: CompiledSetUpdate):
        with self.lock:
            for i in range(len(stream_chunk)):
                rows = self._match_rows(cond, stream_chunk, i)
                if len(rows):
                    self._apply_set(rows, stream_chunk, i, cset)
                    if self.index_attrs:
                        # a SET may move rows between index buckets; later
                        # stream rows in this batch probe those buckets
                        self._rebuild_indexes()
            self._rebuild_indexes()
            self._invalidate()

    def update_or_insert(self, stream_chunk: EventChunk,
                         cond: CompiledTableCondition, cset: CompiledSetUpdate):
        with self.lock:
            for i in range(len(stream_chunk)):
                rows = self._match_rows(cond, stream_chunk, i)
                if len(rows):
                    self._apply_set(rows, stream_chunk, i, cset)
                    if self.index_attrs:
                        self._rebuild_indexes()
                else:
                    row = stream_chunk.slice(i, i + 1)
                    # insert maps same-named attributes
                    for n in self.names:
                        v = row.columns.get(n)
                        self.columns[n].append(_item(v[0]) if v is not None
                                               else None)
                    self.timestamps.append(int(row.timestamps[0]))
                    self._index_row(len(self.timestamps) - 1)
            self._rebuild_indexes()
            self._invalidate()

    def _apply_set(self, rows: np.ndarray, stream_chunk: EventChunk, i: int,
                   cset: CompiledSetUpdate):
        qual = {(STREAM_QUAL, 0): {nm: _item(stream_chunk.columns[nm][i])
                                   for nm in stream_chunk.names}}
        if cset.assignments:
            assigns = cset.assignments
        else:
            # no SET clause: overwrite same-named columns from the stream event
            assigns = None
        for r in rows.tolist():
            if assigns is None:
                for n in self.names:
                    if n in stream_chunk.columns:
                        self.columns[n][r] = _item(stream_chunk.columns[n][i])
            else:
                cols = self._materialise()
                rctx = EvalCtx({k: v[r:r + 1] for k, v in cols.items()},
                               np.asarray([self.timestamps[r]], np.int64), 1,
                               qualified=qual)
                for attr, ce in assigns:
                    self.columns[attr][r] = _item(_scalar(ce.fn(rctx)))
        self._invalidate()

    def contains_column(self, values, n: int) -> np.ndarray:
        """`expr in Table` membership (reference condition/InConditionExpressionExecutor)."""
        with self.lock:
            if isinstance(values, np.ndarray) and values.ndim > 0:
                vals = values
            else:
                vals = np.full(n, values)
            attr = self.primary_key[0] if len(self.primary_key) == 1 \
                else self.names[0]
            existing = set(self.columns[attr])
            return np.asarray([_item(v) in existing for v in vals], bool)

    # ------------------------------------------------------------ compile

    def _stream_scope(self, stream_def, shadow_table_attrs: bool) -> Scope:
        """Scope binding the probing stream's attributes as per-row scalars
        (qualified by stream id/alias; unqualified too, unless
        `shadow_table_attrs` and the table defines the same name)."""
        scope = Scope()
        if stream_def is not None:
            for a in stream_def.attributes:
                def g(ctx, name=a.name):
                    return ctx.qualified[(STREAM_QUAL, 0)][name]
                for qual in _stream_quals(stream_def, self.definition.id):
                    scope.add(qual, a.name, a.type, g)
                if not shadow_table_attrs or \
                        self.definition.index_of(a.name) < 0:
                    scope.add(None, a.name, a.type, g)
        return scope

    def compile_condition(self, on: Optional[Expression], stream_def,
                          factory) -> CompiledTableCondition:
        if on is None:
            return CompiledTableCondition(None)
        # stream attributes first; table attributes last: `T.x` (and
        # unqualified table columns) must resolve to the table even when
        # the flowing definition shares ids
        scope = self._stream_scope(stream_def, shadow_table_attrs=True)
        scope.add_primary(self.definition.id, None, self.definition)
        compiler = factory(scope)
        pk_probe = self._try_pk_probe(on, stream_def, factory)
        index_probe = None if pk_probe else \
            self._try_index_probe(on, stream_def, factory)
        return CompiledTableCondition(compiler.compile(on), pk_probe,
                                      index_probe)

    def _try_pk_probe(self, on: Expression, stream_def, factory):
        """Detect `table.pk == <stream expr>` (AND-combined for composite
        keys) → hash-index probe (reference: IndexEventHolder plans)."""
        if not self.primary_key:
            return None
        eqs: Dict[str, Expression] = {}

        def collect(e: Expression) -> bool:
            if isinstance(e, And):
                return collect(e.left) and collect(e.right)
            if isinstance(e, Compare) and e.op == CompareOp.EQ:
                for a, b in ((e.left, e.right), (e.right, e.left)):
                    if isinstance(a, Variable) and a.attribute in \
                            self.primary_key and not _mentions_table(
                                b, self.definition):
                        eqs[a.attribute] = b
                        return True
                return False
            return False

        if not collect(on) or set(eqs) != set(self.primary_key):
            return None
        compiler = factory(self._stream_scope(stream_def,
                                              shadow_table_attrs=False))
        return [(k, compiler.compile(v))
                for k, v in ((pk, eqs[pk]) for pk in self.primary_key)]

    def _try_index_probe(self, on: Expression, stream_def, factory):
        """Detect an AND-conjunct `table.indexed == <stream expr>` →
        secondary-index hash probe with residual filtering (reference:
        IndexEventHolder secondary indexes + CollectionExpressionParser's
        partial index plans)."""
        if not self.index_attrs:
            return None
        found: List[Tuple[str, Expression]] = []

        def collect(e: Expression):
            if isinstance(e, And):
                collect(e.left)
                collect(e.right)
                return
            if isinstance(e, Compare) and e.op == CompareOp.EQ:
                for a, b in ((e.left, e.right), (e.right, e.left)):
                    if isinstance(a, Variable) and \
                            a.attribute in self.index_attrs and \
                            a.stream_id in (None, self.definition.id) and \
                            not _mentions_table(b, self.definition):
                        found.append((a.attribute, b))
                        return

        collect(on)
        if not found:
            return None
        attr, value_expr = found[0]
        compiler = factory(self._stream_scope(stream_def,
                                              shadow_table_attrs=False))
        try:
            return (attr, compiler.compile(value_expr))
        except Exception:
            return None     # value expr needs table columns → full scan

    def compile_set(self, assignments, stream_def, factory) -> CompiledSetUpdate:
        out = []
        for a in assignments or []:
            scope = Scope()
            scope.add_primary(self.definition.id, None, self.definition)
            if stream_def is not None:
                for at in stream_def.attributes:
                    def g(ctx, name=at.name):
                        return ctx.qualified[(STREAM_QUAL, 0)][name]
                    for qual in _stream_quals(stream_def,
                                              self.definition.id):
                        scope.add(qual, at.name, at.type, g)
                    if self.definition.index_of(at.name) < 0:
                        scope.add(None, at.name, at.type, g)
            compiler = factory(scope)
            out.append((a.table_variable.attribute, compiler.compile(a.value)))
        return CompiledSetUpdate(out)

    # ------------------------------------------------------------ state

    def current_state(self):
        return {"columns": {k: list(v) for k, v in self.columns.items()},
                "timestamps": list(self.timestamps)}

    def restore_state(self, s):
        self.columns = {k: list(v) for k, v in s["columns"].items()}
        self.timestamps = list(s["timestamps"])
        self._rebuild_indexes()
        self._invalidate()


def _stream_quals(stream_def, table_id):
    """Qualifiers the `on`/`set` expressions may use for stream attributes:
    the flowing definition's id plus the query's source stream alias
    (set by QueryRuntime — reference matcher binds the input stream name).
    The table's own id never qualifies stream attributes."""
    quals = [stream_def.id]
    alias = getattr(stream_def, "source_alias", None)
    if alias and alias not in quals:
        quals.append(alias)
    return [q for q in quals if q != table_id]


def _item(v):
    return v.item() if hasattr(v, "item") else v


def _scalar(v):
    if isinstance(v, np.ndarray) and v.ndim > 0:
        return v[0]
    return v


def _mentions_table(e: Expression, table_def) -> bool:
    from ..query_api.expression import variables_of
    for v in variables_of(e):
        if v.stream_id == table_def.id:
            return True
        if v.stream_id is None and table_def.index_of(v.attribute) >= 0:
            return True
    return False
