"""Runtime lock-witness: acquisition-order validation for engine locks.

The static lock-graph (analysis/engine/lockgraph.py) proves what the
source *can* do; the witness watches what threads *actually* do.  When
armed, engine locks are wrapped at construction time via
:func:`maybe_wrap`; each wrapped lock reports first acquisitions and
final releases to a process-global :class:`LockWitness`, which keeps a
per-thread held-lock stack and a global observed-edge set.  Acquiring B
while holding A records the edge ``A -> B``; if the reverse edge has
been observed at runtime — or exists in the static graph — that is a
lock-order inversion (two threads can interleave into a deadlock) and an
``LW001`` incident bundle goes through the flight-recorder bus.  Holding
any witnessed lock longer than ``SIDDHI_TPU_LOCKWITNESS_HOLD_MS``
(default 100) reports ``LW002``.

Off by default and zero-cost when off: :func:`maybe_wrap` returns the
lock unchanged unless the witness is armed (programmatically, or via
``SIDDHI_TPU_LOCKWITNESS=1`` at lock-construction time).  The witness's
own mutex only guards its bookkeeping dictionaries and is never held
while an engine lock is being acquired, so the witness cannot introduce
an ordering of its own.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

WITNESS_ENV = "SIDDHI_TPU_LOCKWITNESS"
HOLD_ENV = "SIDDHI_TPU_LOCKWITNESS_HOLD_MS"
DEFAULT_HOLD_MS = 100.0


def witness_enabled() -> bool:
    """Env opt-in, read at lock-construction time (cold path)."""
    return os.environ.get(WITNESS_ENV, "").strip().lower() in (
        "1", "true", "on", "yes")


def _hold_threshold_ms() -> float:
    try:
        v = float(os.environ.get(HOLD_ENV, ""))
        return v if v > 0 else DEFAULT_HOLD_MS
    except (TypeError, ValueError):
        return DEFAULT_HOLD_MS


class LockWitness:
    """Observed-order recorder + validator.  Thread-safe; one global
    instance serves the engine (see :func:`witness`), tests may build
    private instances for seeded scenarios."""

    def __init__(self, hold_ms: Optional[float] = None,
                 static_edges: Optional[Iterable[Tuple[str, str]]] = None,
                 emit_incidents: bool = True):
        self.armed = False
        self.hold_ms = hold_ms if hold_ms is not None else _hold_threshold_ms()
        self.emit_incidents = emit_incidents
        self._mu = threading.Lock()         # guards the dicts below only
        self._tls = threading.local()       # .stack: List[str] held names
        self._edges: Dict[Tuple[str, str], str] = {}   # edge -> first thread
        self._inversions: List[Dict[str, Any]] = []
        self._holds: List[Dict[str, Any]] = []
        self._reported: Set[frozenset] = set()         # deduped emit pairs
        self._static: Set[Tuple[str, str]] = set(static_edges or ())

    # ------------------------------------------------------------ control

    def arm(self):
        self.armed = True

    def disarm(self):
        self.armed = False

    def reset(self):
        with self._mu:
            self._edges.clear()
            self._inversions.clear()
            self._holds.clear()
            self._reported.clear()

    def load_static_edges(self, edges: Iterable[Tuple[str, str]]):
        with self._mu:
            self._static.update(tuple(e) for e in edges)

    # ------------------------------------------------------------ reports

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)

    def inversions(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._inversions)

    def holds(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._holds)

    # ------------------------------------------------------------ wrapping

    def wrap(self, lock: Any, name: str) -> "_WitnessedLock":
        return _WitnessedLock(lock, name, self)

    # ------------------------------------------------------ lock callbacks

    def _stack(self) -> List[str]:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def _on_acquired(self, name: str):
        stack = self._stack()
        if stack:
            tname = threading.current_thread().name
            new_inversions = []
            with self._mu:
                for held in stack:
                    if held == name:
                        continue
                    edge = (held, name)
                    if edge not in self._edges:
                        self._edges[edge] = tname
                    rev = (name, held)
                    if rev in self._edges or rev in self._static:
                        pair = frozenset(edge)
                        if pair not in self._reported:
                            self._reported.add(pair)
                            inv = {"code": "LW001",
                                   "first": list(rev), "second": list(edge),
                                   "thread": tname,
                                   "other_thread": self._edges.get(rev),
                                   "static": rev in self._static}
                            self._inversions.append(inv)
                            new_inversions.append(inv)
            for inv in new_inversions:      # emit outside _mu
                self._emit("lock_inversion", inv)
        stack.append(name)

    def _on_release(self, name: str, t0_ns: Optional[int]):
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                break
        if t0_ns is None:
            return
        held_ms = (time.perf_counter_ns() - t0_ns) / 1e6
        if held_ms > self.hold_ms:
            rec = {"code": "LW002", "lock": name,
                   "held_ms": round(held_ms, 3),
                   "threshold_ms": self.hold_ms,
                   "thread": threading.current_thread().name}
            with self._mu:
                self._holds.append(rec)
            self._emit("lock_hold", rec)

    def _emit(self, kind: str, detail: Dict[str, Any]):
        if not self.emit_incidents:
            return
        try:
            from .flight import flight
            flight().emit(kind, detail=detail)
        except Exception:  # noqa: BLE001 — witness must never take the app down
            pass


class _WitnessedLock:
    """Transparent wrapper: same acquire/release/context protocol as the
    wrapped Lock/RLock.  Tracks per-thread depth so reentrant
    re-acquisitions don't double-report, and stays balanced even if the
    witness is disarmed while a lock is held."""

    __slots__ = ("_lock", "_name", "_w", "_tls")

    def __init__(self, lock: Any, name: str, w: LockWitness):
        self._lock = lock
        self._name = name
        self._w = w
        self._tls = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            d = getattr(self._tls, "depth", 0)
            self._tls.depth = d + 1
            if d == 0:
                self._tls.armed_entry = self._w.armed
                if self._w.armed:
                    self._tls.t0 = time.perf_counter_ns()
                    self._w._on_acquired(self._name)
        return ok

    def release(self):
        d = getattr(self._tls, "depth", 1) - 1
        self._tls.depth = d
        if d == 0 and getattr(self._tls, "armed_entry", False):
            t0 = getattr(self._tls, "t0", None)
            self._tls.t0 = None
            if self._w.armed:
                self._w._on_release(self._name, t0)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    @property
    def name(self) -> str:
        return self._name


# ------------------------------------------------------------------ global

_GLOBAL: Optional[LockWitness] = None
_GLOBAL_MU = threading.Lock()


def witness() -> LockWitness:
    global _GLOBAL
    w = _GLOBAL
    if w is None:
        with _GLOBAL_MU:
            w = _GLOBAL
            if w is None:
                w = _GLOBAL = LockWitness()
    return w


def arm(static_edges: Optional[Iterable[Tuple[str, str]]] = None,
        hold_ms: Optional[float] = None) -> LockWitness:
    w = witness()
    if static_edges is not None:
        w.load_static_edges(static_edges)
    if hold_ms is not None:
        w.hold_ms = hold_ms
    w.arm()
    return w


def disarm():
    w = _GLOBAL
    if w is not None:
        w.disarm()


def maybe_wrap(lock: Any, name: str) -> Any:
    """Construction-time hook: wrap `lock` when the witness is armed (or
    the env knob is on), else hand it back untouched — the off path is a
    plain attribute check plus one function call, nothing per-acquire."""
    w = _GLOBAL
    if w is not None and w.armed:
        return w.wrap(lock, name)
    if witness_enabled():
        return arm().wrap(lock, name)
    return lock
