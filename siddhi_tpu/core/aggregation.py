"""Incremental aggregation: `define aggregation ... aggregate by ts every
sec ... year`.

Reference model (siddhi-core aggregation/): AggregationRuntime.java:67-199
builds a per-duration IncrementalExecutor chain (SECONDS→…→YEARS) of
in-memory buckets keyed by (bucket_start, group key); composite functions are
decomposed into incremental bases (avg → sum+count, stdDev → sum+sumSq+count,
IncrementalAttributeAggregator SPI) recombined at query time; `find()` merges
buckets for `within <range> per <duration>` queries
(IncrementalAggregateCompileCondition).

Columnar design here: every duration keeps a dict bucket store updated from
event micro-batches; a query-side `find_chunk` materialises the requested
duration's buckets in-range as one EventChunk (AGG_TIMESTAMP + group-by +
recombined outputs), which joins/store-queries then treat like any other
buffer.  On the TPU path bucket stores become fixed slab tensors updated with
segment-sums (ops/).
"""
from __future__ import annotations

from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx, ExprCompiler, Scope
from ..query_api import Filter
from ..query_api.definition import (DURATION_MS, AggregationDefinition,
                                    Attribute, AttrType, StreamDefinition)
from ..query_api.expression import AttributeFunction, Constant, TimeConstant
from ..utils.errors import SiddhiAppCreationError, StoreQueryCreationError
from .event import CURRENT, EventChunk
from .stateschema import MapOf, Struct, persistent_schema

AGG_TS = "AGG_TIMESTAMP"

# composite → incremental bases (reference IncrementalAttributeAggregator
# implementations: Avg/Sum/Count/Min/Max/StdDev IncrementalAttributeAggregator)
_DECOMPOSE = {
    "sum": ("sum",),
    "count": ("count",),
    "avg": ("sum", "count"),
    "min": ("min",),
    "max": ("max",),
    "stddev": ("sum", "sumsq", "count"),
    # distinct value-set per bucket, |set| on read (reference
    # DistinctCountIncrementalAttributeAggregator); host-only lane
    "distinctcount": ("distinct",),
}


class _OutputSpec:
    """One select attribute of the aggregation definition."""

    __slots__ = ("name", "kind", "bases", "arg", "out_type", "group_idx")

    def __init__(self, name, kind, bases, arg, out_type, group_idx=None):
        self.name = name
        self.kind = kind          # 'agg' | 'last' | 'group'
        self.bases = bases        # base slot indices ('agg'/'last')
        self.arg = arg            # CompiledExpr (agg argument / last expr)
        self.out_type = out_type
        self.group_idx = group_idx  # index into group key tuple ('group')


@persistent_schema("aggregation",
                   schema=Struct(buckets=MapOf("bucket-store")))
class AggregationRuntime:
    def __init__(self, ad: AggregationDefinition, app_runtime):
        self.ad = ad
        self.app = app_runtime
        stream = ad.basic_single_input_stream
        self.stream_id = stream.stream_id
        self.input_definition = app_runtime.definition_of(self.stream_id)

        scope = Scope()
        scope.add_primary(self.stream_id, stream.stream_ref,
                          self.input_definition)
        compiler = ExprCompiler(scope, np,
                                app_runtime.app_ctx.script_functions,
                                app_runtime.extension_registry)
        self.filters: List[CompiledExpr] = [
            compiler.compile(h.expr) for h in stream.handlers
            if isinstance(h, Filter)]

        # group-by executors
        self.group_exprs: List[CompiledExpr] = [
            compiler.compile(v) for v in ad.selector.group_by]
        self.group_names: List[str] = [v.attribute
                                       for v in ad.selector.group_by]

        # decompose select attributes
        self.base_fns: List[str] = []      # base op per slot: sum/count/...
        self.base_args: List[Optional[CompiledExpr]] = []
        self.outputs: List[_OutputSpec] = []
        out_attrs: List[Attribute] = [Attribute(AGG_TS, AttrType.LONG)]
        for oa in ad.selector.attributes:
            e = oa.expr
            if isinstance(e, AttributeFunction) and \
                    e.name.lower() in _DECOMPOSE:
                fname = e.name.lower()
                arg = compiler.compile(e.args[0]) if e.args else None
                slots = []
                for b in _DECOMPOSE[fname]:
                    slots.append(len(self.base_fns))
                    self.base_fns.append(b)
                    self.base_args.append(arg)
                t = (AttrType.DOUBLE if fname in ("avg", "stddev")
                     else (arg.type if arg is not None else AttrType.LONG))
                if fname in ("count", "distinctcount"):
                    t = AttrType.LONG
                if fname == "sum" and arg is not None and arg.type in (
                        AttrType.INT, AttrType.LONG):
                    t = AttrType.LONG
                elif fname == "sum":
                    t = AttrType.DOUBLE
                self.outputs.append(_OutputSpec(oa.rename, "agg", slots,
                                                arg, t))
                out_attrs.append(Attribute(oa.rename, t))
            else:
                ce = compiler.compile(e)
                gname = getattr(e, "attribute", None)
                if gname in self.group_names:
                    gi = self.group_names.index(gname)
                    self.outputs.append(_OutputSpec(oa.rename, "group", None,
                                                    None, ce.type,
                                                    group_idx=gi))
                else:
                    # non-grouped passthrough: per-bucket last value
                    # (reference incremental 'last' semantics)
                    slot = len(self.base_fns)
                    self.base_fns.append("last")
                    self.base_args.append(ce)
                    self.outputs.append(_OutputSpec(oa.rename, "last",
                                                    [slot], ce, ce.type))
                out_attrs.append(Attribute(oa.rename, ce.type))
        self.output_definition = StreamDefinition(ad.id, out_attrs)

        # external-time attribute
        self.by_attr = ad.aggregate_attribute
        self.durations = list(ad.time_periods)
        for d in self.durations:
            if d not in DURATION_MS:
                raise SiddhiAppCreationError(f"Bad duration '{d}'")
        # bucket stores: duration → {(bucket_ts, key): [base values]}
        self.buckets: Dict[str, Dict[Tuple[int, Tuple], List[Any]]] = {
            d: {} for d in self.durations}

        junction = app_runtime.junction_of(self.stream_id)
        junction.subscribe(self)
        self._setup_purging()

    # ------------------------------------------------------------ purging

    _DEFAULT_RETENTION = {"sec": 120_000, "min": 86_400_000,
                          "hour": 30 * 86_400_000, "day": 365 * 86_400_000,
                          "month": None, "year": None}   # None = keep all

    def _setup_purging(self):
        """@purge(enable, interval, @retentionPeriod(sec=..., min=...))
        (reference aggregation/IncrementalDataPurging.java)."""
        from ..query_api import find_annotation
        ann = find_annotation(self.ad.annotations, "purge")
        if ann is None or str(ann.get("enable", "true")).lower() != "true":
            self.retention = None
            return
        from .runtime import _parse_time_str
        interval = _parse_time_str(ann.get("interval", "15 min"))
        self.retention = dict(self._DEFAULT_RETENTION)
        rp = find_annotation(ann.annotations, "retentionperiod") or \
            find_annotation(ann.annotations, "retentionPeriod")
        if rp is not None:
            for k, v in rp.as_dict().items():
                kk = k.lower().rstrip("s")
                if kk in self.retention:
                    self.retention[kk] = (None if str(v).lower() == "all"
                                          else _parse_time_str(v))
        ctx = self.app.app_ctx

        def fire(now):
            self.purge(now)
            ctx.scheduler.notify_at(now + interval, fire)
        ctx.scheduler.notify_at(
            ctx.timestamp_generator.current_time() + interval, fire)

    def purge(self, now: int):
        if self.retention is None:
            return
        for dur in self.durations:
            keep_ms = self.retention.get(dur)
            if keep_ms is None:
                continue
            store = self.buckets[dur]
            cutoff = now - keep_ms
            for b in [b for b in store if b[0] < cutoff]:
                del store[b]

    # ------------------------------------------------------------ ingestion

    def _prepare_chunk(self, chunk: EventChunk):
        """Shared ingest head: filters → (ts_col, key_cols, base_vals, n)
        or None when the chunk is fully filtered."""
        chunk = chunk.only(CURRENT)
        n = len(chunk)
        if n == 0:
            return None
        ctx = EvalCtx(chunk.columns, chunk.timestamps, n)
        for f in self.filters:
            m = np.asarray(f.fn(ctx), bool)
            if m.ndim == 0:
                m = np.full(n, bool(m))
            if not m.all():
                chunk = chunk.mask(m)
                n = len(chunk)
                if n == 0:
                    return None
                ctx = EvalCtx(chunk.columns, chunk.timestamps, n)
        # event time column
        if self.by_attr is not None:
            ts_col = np.asarray(chunk.columns[self.by_attr], np.int64)
        else:
            ts_col = chunk.timestamps
        key_cols = [np.asarray(g.fn(ctx)) for g in self.group_exprs]
        base_vals = []
        for _fn, arg in zip(self.base_fns, self.base_args):
            if arg is None:
                base_vals.append(None)
            else:
                v = arg.fn(ctx)
                v = np.broadcast_to(np.asarray(v), (n,)) \
                    if np.asarray(v).ndim == 0 else np.asarray(v)
                base_vals.append(v)
        return ts_col, key_cols, base_vals, n

    def receive_chunk(self, chunk: EventChunk):
        prep = self._prepare_chunk(chunk)
        if prep is None:
            return
        ts_col, key_cols, base_vals, n = prep
        for i in range(n):
            key = tuple(_py(kc[i]) for kc in key_cols)
            ts = int(ts_col[i])
            for dur in self.durations:
                step = DURATION_MS[dur]
                b = (ts - ts % step, key)
                store = self.buckets[dur]
                slots = store.get(b)
                if slots is None:
                    slots = [_init_of(fn) for fn in self.base_fns]
                    store[b] = slots
                for si, fn in enumerate(self.base_fns):
                    v = base_vals[si]
                    slots[si] = _update(fn, slots[si],
                                        None if v is None else _py(v[i]))

    # ------------------------------------------------------------ query side

    def find_chunk(self, within, per, probe_chunk=None) -> EventChunk:
        """Materialise buckets of duration `per` within the time range as an
        EventChunk (reference IncrementalAggregateCompileCondition.find).
        `within`/`per` may be Variables referencing the probing stream's
        attributes (`within i.startTime, i.endTime per i.perValue` —
        Aggregation1TestCase test6); they resolve against probe_chunk's
        first row."""
        from ..query_api.expression import Variable
        probe_row = None
        within_items = list(within) if isinstance(within, (tuple, list)) \
            else [within]
        if probe_chunk is not None and len(probe_chunk) and any(
                isinstance(p, Variable)
                for p in within_items + [per] if p is not None):
            probe_row = {nm: _py(probe_chunk.columns[nm][0])
                         for nm in probe_chunk.names}
        dur = _eval_per(per, probe_row)
        if dur not in self.buckets:
            raise StoreQueryCreationError(
                f"Aggregation '{self.ad.id}' has no '{dur}' duration "
                f"(has {self.durations})")
        lo, hi = _eval_within(within, probe_row)
        rows = [(b_ts, key, slots)
                for (b_ts, key), slots in self.buckets[dur].items()
                if lo <= b_ts < hi]
        rows.sort(key=lambda r: r[0])
        k = len(rows)
        names = self.output_definition.attribute_names
        cols: Dict[str, np.ndarray] = {}
        cols[AGG_TS] = np.asarray([r[0] for r in rows], np.int64)
        for gi, gname in enumerate(self.group_names):
            arr = np.empty(k, object)
            for i, r in enumerate(rows):
                arr[i] = r[1][gi]
            cols[gname] = arr
        for o in self.outputs:
            if o.name in cols:
                continue
            arr = np.empty(k, object)
            for i, (_b_ts, key, slots) in enumerate(rows):
                if o.kind == "group":
                    arr[i] = key[o.group_idx]
                elif o.kind == "last":
                    arr[i] = slots[o.bases[0]]
                else:
                    arr[i] = _recombine(o, self.base_fns, slots)
            cols[o.name] = arr
        ts = cols[AGG_TS]
        return EventChunk(names, ts, np.zeros(k, np.int8), cols)

    # ------------------------------------------------------------ snapshot

    def current_state(self):
        return {
            "buckets": {d: [[list(b), list(map(_jsonable, slots))]
                            for b, slots in store.items()]
                        for d, store in self.buckets.items()},
        }

    def restore_state(self, s):
        self.buckets = {
            d: {(int(b[0]), tuple(b[1])): list(slots)
                for b, slots in recs}
            for d, recs in s["buckets"].items()}


# ---------------------------------------------------------------- helpers

def _py(v):
    return v.item() if isinstance(v, np.generic) else v


def _jsonable(v):
    return _py(v)


def _init_of(fn: str):
    if fn == "distinct":
        return set()
    return None if fn in ("min", "max") else 0


def _update(fn: str, acc, v):
    if fn == "count":
        return (acc or 0) + 1
    if v is None:
        return acc
    if fn == "last":
        return v
    if fn == "sum":
        return (acc or 0) + v
    if fn == "sumsq":
        return (acc or 0) + v * v
    if fn == "min":
        return v if acc is None else min(acc, v)
    if fn == "max":
        return v if acc is None else max(acc, v)
    if fn == "distinct":
        acc = set() if acc is None else acc
        acc.add(v)
        return acc
    raise SiddhiAppCreationError(f"Unknown base fn {fn}")


def _recombine(o: _OutputSpec, base_fns, slots):
    vals = [slots[i] for i in o.bases]
    kinds = [base_fns[i] for i in o.bases]
    if kinds == ["distinct"]:
        return len(vals[0] or ())
    if len(vals) == 1:
        return vals[0]
    d = dict(zip(kinds, vals))
    if set(kinds) == {"sum", "count"}:
        return (d["sum"] / d["count"]) if d["count"] else None
    if set(kinds) == {"sum", "sumsq", "count"}:
        n = d["count"]
        if not n:
            return None
        mean = d["sum"] / n
        return max(d["sumsq"] / n - mean * mean, 0.0) ** 0.5
    return vals[0]


def _probe_value(v, probe_row):
    """Resolve a Variable against the probing stream's row."""
    from ..query_api.expression import Variable
    if isinstance(v, Variable) and probe_row is not None and \
            v.attribute in probe_row:
        return probe_row[v.attribute]
    return v


def _eval_per(per, probe_row=None) -> str:
    if per is None:
        raise StoreQueryCreationError("aggregation query needs `per`")
    per = _probe_value(per, probe_row)
    if isinstance(per, Constant):
        word = str(per.value)
    elif isinstance(per, str):
        word = per
    else:
        raise StoreQueryCreationError(f"Unsupported per expression {per!r}")
    from ..compiler.parser import Parser
    try:
        return Parser._norm_duration(word)
    except Exception:
        # `per` may now flow from event data (per i.perValue): a bad value
        # is a store-query error, not a parse-time one
        raise StoreQueryCreationError(
            f"Bad per duration {word!r}") from None


_DATE_FORMATS = ["%Y-%m-%d %H:%M:%S %z", "%Y-%m-%d %H:%M:%S",
                 "%Y-%m-%d"]


def _parse_time_point(v) -> int:
    if isinstance(v, TimeConstant):
        return int(v.value)
    if isinstance(v, Constant):
        v = v.value
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, str):
        s = v.strip()
        for fmt in _DATE_FORMATS:
            try:
                dt = datetime.strptime(s, fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=timezone.utc)
                return int(dt.timestamp() * 1000)
            except ValueError:
                continue
    raise StoreQueryCreationError(f"Cannot parse time point {v!r}")


def _eval_within(within, probe_row=None) -> Tuple[int, int]:
    if within is None:
        return (-2**62, 2**62)
    if isinstance(within, (tuple, list)):
        items = [w for w in within if w is not None]
    else:
        items = [within]
    items = [_probe_value(w, probe_row) for w in items]
    if len(items) == 2:
        return (_parse_time_point(items[0]), _parse_time_point(items[1]))
    w = items[0]
    # single value: a wildcard date pattern "2014-**-** ..." covering a range
    wv = w.value if isinstance(w, Constant) else w
    if isinstance(wv, str) and "**" in wv:
        s = wv.strip()
        # the range comes from the date prefix before the first wildcard
        prefix = s.split("**")[0].rstrip("-: ")
        try:
            if len(prefix) == 4:            # "2014"
                lo = datetime(int(prefix), 1, 1, tzinfo=timezone.utc)
                hi = datetime(int(prefix) + 1, 1, 1, tzinfo=timezone.utc)
            elif len(prefix) == 7:          # "2014-02"
                y, mth = int(prefix[:4]), int(prefix[5:7])
                lo = datetime(y, mth, 1, tzinfo=timezone.utc)
                hi = datetime(y + (mth == 12), mth % 12 + 1, 1,
                              tzinfo=timezone.utc)
            elif len(prefix) == 10:         # "2014-02-15"
                y, mth, dd = (int(prefix[:4]), int(prefix[5:7]),
                              int(prefix[8:10]))
                lo = datetime(y, mth, dd, tzinfo=timezone.utc)
                hi = datetime.fromtimestamp(lo.timestamp() + 86400,
                                            tz=timezone.utc)
            else:
                raise ValueError(s)
            return (int(lo.timestamp() * 1000), int(hi.timestamp() * 1000))
        except ValueError:
            raise StoreQueryCreationError(
                f"Bad within pattern {s!r}") from None
    t = _parse_time_point(w)
    return (t, 2**62)
