"""Sources, sinks, mappers and the in-memory transport.

(reference: stream/input/source/{Source,SourceMapper}.java lifecycle with
backoff retry, stream/output/sink/{Sink,SinkMapper}.java, InMemory transport
util/transport/InMemoryBroker.java, sink option {{templates}} via
TemplateBuilder/OptionHolder, distributed sinks
stream/output/sink/distributed/*.)

Wired from `@source(type='inMemory', topic='t', @map(type='passThrough'))` /
`@sink(...)` annotations on stream definitions.
"""
from __future__ import annotations

import json
import logging
import re
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..query_api.annotation import Annotation, find_all, find_annotation
from ..utils.errors import (ConnectionUnavailableError, MappingFailedError,
                            SiddhiAppCreationError)
from .event import CURRENT, Event, EventChunk, LazyEvents, dtype_for
from .hotpath import hot_path
from .ledger import ledger as _ledger
from .resilience import (CircuitBreaker, RetryPolicy, SinkRetryWorker,
                         make_entry)

log = logging.getLogger(__name__)


# ===================================================================== broker

class InMemoryBroker:
    """Global topic bus (reference util/transport/InMemoryBroker.java)."""

    _subscribers: Dict[str, List[Any]] = {}
    _lock = threading.Lock()

    @classmethod
    def subscribe(cls, subscriber):
        """subscriber: object with .topic and .on_message(obj)."""
        with cls._lock:
            cls._subscribers.setdefault(subscriber.topic, []).append(subscriber)

    @classmethod
    def unsubscribe(cls, subscriber):
        with cls._lock:
            subs = cls._subscribers.get(subscriber.topic, [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, obj):
        for s in list(cls._subscribers.get(topic, [])):
            s.on_message(obj)


# ===================================================================== mappers

def _vals_to_column(attr_type, vals) -> np.ndarray:
    """Python value list → one attribute column, same dtype/None policy as
    ``EventChunk.from_rows`` (object lane for string/object, None → 0)."""
    dt = dtype_for(attr_type)
    if dt is object:
        arr = np.empty(len(vals), object)
        for i, v in enumerate(vals):
            arr[i] = v
        return arr
    try:
        return np.asarray(vals, dtype=dt)
    except (TypeError, ValueError):
        return np.asarray([0 if v is None else v for v in vals], dtype=dt)


class SourceMapper:
    """format → Event[] (reference stream/input/source/SourceMapper.java)."""

    def __init__(self, definition, options: Dict[str, str]):
        self.definition = definition
        self.options = options

    def map(self, obj) -> List[Event]:
        raise NotImplementedError

    def map_batch(self, obj):
        """Columnar counterpart of ``map``: payload → (timestamps,
        name→column dict) for ``InputHandler.send_batch`` — no per-event
        Event objects.  ``None`` means this mapper (or this payload shape)
        has no columnar path and the caller falls back to ``map``."""
        return None


class PassThroughSourceMapper(SourceMapper):
    def map(self, obj) -> List[Event]:
        if isinstance(obj, EventChunk):
            # chunk published by a columnar sink looping back in-memory
            return obj.only(CURRENT).to_events()
        if isinstance(obj, Event):
            return [obj]
        if isinstance(obj, (list, tuple)):
            if obj and isinstance(obj[0], Event):
                return list(obj)
            now = int(time.time() * 1000)
            if obj and isinstance(obj[0], (list, tuple)):
                return [Event(now, list(r)) for r in obj]   # batch of rows
            return [Event(now, list(obj))]
        raise MappingFailedError(f"passThrough cannot map {type(obj)}")

    def map_batch(self, obj):
        if not self.definition.attributes:
            return None
        if isinstance(obj, EventChunk):
            # zero-copy re-ingest of a columnar sink's chunk payload
            cur = obj.only(CURRENT)
            return cur.timestamps, cur.columns
        if isinstance(obj, (list, tuple)) and obj \
                and isinstance(obj[0], (list, tuple)):
            now = int(time.time() * 1000)
            cols = {a.name: _vals_to_column(a.type, [r[j] for r in obj])
                    for j, a in enumerate(self.definition.attributes)}
            return np.full(len(obj), now, np.int64), cols
        return None   # single event / row: per-event shim is fine


class JsonSourceMapper(SourceMapper):
    """{"event": {attr: value, ...}} or a list of such (reference
    siddhi-map-json extension behaviour)."""

    def map(self, obj) -> List[Event]:
        data = json.loads(obj) if isinstance(obj, (str, bytes)) else obj
        if isinstance(data, dict):
            data = [data]
        out = []
        for item in data:
            payload = item.get("event", item)
            row = [payload.get(a.name) for a in self.definition.attributes]
            out.append(Event(int(item.get("timestamp",
                                          time.time() * 1000)), row))
        return out

    def map_batch(self, obj):
        """Vectorized decode: one json.loads for the whole payload, then
        column-at-a-time extraction straight into numpy lanes."""
        if not self.definition.attributes:
            return None
        data = json.loads(obj) if isinstance(obj, (str, bytes)) else obj
        if isinstance(data, dict):
            data = [data]
        if not (isinstance(data, list) and data
                and all(isinstance(it, dict) for it in data)):
            return None
        now = int(time.time() * 1000)
        payloads = [it.get("event", it) for it in data]
        ts = np.asarray([int(it.get("timestamp", now)) for it in data],
                        np.int64)
        cols = {a.name: _vals_to_column(a.type,
                                        [p.get(a.name) for p in payloads])
                for a in self.definition.attributes}
        return ts, cols


class SinkMapper:
    def __init__(self, definition, options: Dict[str, str]):
        self.definition = definition
        self.options = options

    def map(self, events: List[Event]):
        raise NotImplementedError

    def map_chunk(self, chunk: EventChunk):
        """Chunk-level counterpart of ``map``: serialize a columnar batch
        without materializing Event objects.  ``None`` means no chunk path
        — the sink falls back to ``to_events()`` + ``map``."""
        return None


class PassThroughSinkMapper(SinkMapper):
    def map(self, events: List[Event]):
        return events

    def map_chunk(self, chunk: EventChunk):
        return chunk      # zero-copy: the chunk itself is the payload


class JsonSinkMapper(SinkMapper):
    def map(self, events: List[Event]):
        names = [a.name for a in self.definition.attributes]
        return json.dumps([{"event": dict(zip(names, e.data)),
                            "timestamp": e.timestamp} for e in events])

    def map_chunk(self, chunk: EventChunk):
        names = [a.name for a in self.definition.attributes]
        ts = chunk.timestamps.tolist()
        cols = [chunk.columns[n].tolist() for n in names]
        return json.dumps([{"event": dict(zip(names, row)), "timestamp": t}
                           for t, row in zip(ts, zip(*cols))])


class TextSinkMapper(SinkMapper):
    def map(self, events: List[Event]):
        names = [a.name for a in self.definition.attributes]
        return "\n".join(
            ", ".join(f"{n}:{v}" for n, v in zip(names, e.data))
            for e in events)

    def map_chunk(self, chunk: EventChunk):
        names = [a.name for a in self.definition.attributes]
        cols = [chunk.columns[n].tolist() for n in names]
        return "\n".join(
            ", ".join(f"{n}:{v}" for n, v in zip(names, row))
            for row in zip(*cols))


SOURCE_MAPPERS = {"passthrough": PassThroughSourceMapper,
                  "json": JsonSourceMapper}
SINK_MAPPERS = {"passthrough": PassThroughSinkMapper,
                "json": JsonSinkMapper, "text": TextSinkMapper}


# ===================================================================== source

class SourceHandler:
    """HA hook between a source and its input handler: an outer platform
    subclasses this to gate events on passive nodes (reference
    stream/input/source/SourceHandler.java + SourceHandlerManager — the
    active/passive coordination SPI)."""

    def handle(self, events):
        """Return the events to forward (possibly filtered), or None to
        drop (passive node)."""
        return events


class SinkHandler:
    """HA hook before a sink publishes (reference
    stream/output/sink/SinkHandler.java)."""

    def handle(self, payload, event):
        """Return the payload to publish, or None to suppress."""
        return payload


class SourceHandlerManager:
    def generate_source_handler(self, source) -> SourceHandler:
        return SourceHandler()


class SinkHandlerManager:
    def generate_sink_handler(self, sink) -> SinkHandler:
        return SinkHandler()


class Source:
    """Base source with connect-retry lifecycle
    (reference Source.connectWithRetry:128-157 + BackoffRetryCounter).

    The old fixed ``RETRIES`` ladder is replaced by a per-source
    ``RetryPolicy`` (exponential backoff + jitter) configurable through
    ``retry.*`` annotation options."""

    def __init__(self, stream_def, options: Dict[str, str],
                 mapper: SourceMapper, input_handler):
        self.stream_def = stream_def
        self.options = options
        self.mapper = mapper
        self.input_handler = input_handler
        self.connected = False
        self.retry_policy = RetryPolicy.from_options(options)
        self._stop_retry = threading.Event()

    def connect(self):
        raise NotImplementedError

    def disconnect(self):
        pass

    def connect_with_retry(self):
        delays = [0.0] + self.retry_policy.delays()
        for i, delay in enumerate(delays):
            if delay:
                if self._stop_retry.wait(delay):
                    return
            try:
                self.connect()
                self.connected = True
                return
            except ConnectionUnavailableError as e:
                log.warning("source connect failed (attempt %d): %s", i + 1, e)
        log.error("source for %s could not connect", self.stream_def.id)

    def shutdown(self):
        self._stop_retry.set()
        try:
            self.disconnect()
        finally:
            self.connected = False

    def deliver(self, obj):
        handler = getattr(self, "handler", None)
        if handler is None:
            # columnar fast path: mapper decodes straight to columns and
            # the batch enters the junction without Event materialization.
            # An attached HA handler speaks Event[] — it keeps the shim.
            try:
                batch = self.mapper.map_batch(obj)
            except MappingFailedError as e:
                log.error("mapping failed on %s: %s", self.stream_def.id, e)
                return
            if batch is not None:
                ts, cols = batch
                if len(ts):
                    self.input_handler.send_batch(cols, timestamps=ts)
                return
        try:
            events = self.mapper.map(obj)
        except MappingFailedError as e:
            log.error("mapping failed on %s: %s", self.stream_def.id, e)
            return
        if handler is not None and events:
            events = handler.handle(events)
        if events:
            self.input_handler.send(events)


class InMemorySource(Source):
    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.topic = self.options.get("topic", self.stream_def.id)

    def connect(self):
        InMemoryBroker.subscribe(self)

    def disconnect(self):
        InMemoryBroker.unsubscribe(self)

    def on_message(self, obj):
        self.deliver(obj)


# ===================================================================== sink

_TEMPLATE_RE = re.compile(r"\{\{(\w+)\}\}")


class Sink:
    """Base sink; junction subscriber publishing mapped events
    (reference Sink.java:49-167).

    Publish resilience: the first attempt runs inline on the junction
    thread; a ``ConnectionUnavailableError`` hands the payload to this
    sink's bounded retry worker (exponential backoff, off-thread) and a
    ``CircuitBreaker`` turns a persistently dead endpoint into fast-fail
    (events → error store when one is configured, else a counted drop).
    Knobs ride the ``@sink`` annotation: ``retry.max.attempts``,
    ``retry.base.delay.ms``, ``retry.max.delay.ms``, ``retry.multiplier``,
    ``retry.budget.ms``, ``retry.queue.size``,
    ``circuit.failure.threshold``, ``circuit.reset.ms``."""

    def __init__(self, stream_def, options: Dict[str, str], mapper: SinkMapper):
        self.stream_def = stream_def
        self.options = options
        self.mapper = mapper
        self.connected = False
        self.retry_policy = RetryPolicy.from_options(options)
        self.breaker = CircuitBreaker.from_options(options)
        self._retry_capacity = int(options.get("retry.queue.size", "1024"))
        self._retry_worker_inst = None
        self._retry_lock = threading.Lock()
        self._stop_retry = threading.Event()
        self._runtime = None      # set by attach_sources_and_sinks

    # ---- runtime binding (error store + metrics) ----------------------

    def bind_runtime(self, app_runtime):
        self._runtime = app_runtime
        m = self.resilience
        if m is not None:
            sid = self.stream_def.id
            m.circuit_state.set_fn(
                lambda b=self.breaker: b.state_code, sink=sid)

            def _on_transition(old, new, m=m, sid=sid, rt=app_runtime):
                m.circuit_transitions_total.inc(sink=sid, to=new)
                if new == "open":
                    # incident bus: a sink fast-failing is exactly the
                    # moment the operator wants the recent flight ring
                    from .flight import flight
                    flight().emit("circuit_open",
                                  app=getattr(rt, "name", ""),
                                  detail={"sink": sid, "from": old},
                                  runtime=rt)
            self.breaker.on_transition = _on_transition

    @property
    def app_name(self) -> str:
        return self._runtime.name if self._runtime is not None else ""

    @property
    def error_store(self):
        return getattr(self._runtime, "error_store", None)

    @property
    def resilience(self):
        return getattr(self._runtime, "resilience_metrics", None)

    # dynamic option templating: topic='{{symbol}}' resolved per event
    def resolve_option(self, key: str, event: Event) -> Optional[str]:
        raw = self.options.get(key)
        if raw is None:
            return None
        names = [a.name for a in self.stream_def.attributes]

        def sub(m):
            try:
                return str(event.data[names.index(m.group(1))])
            except ValueError:
                return m.group(0)
        return _TEMPLATE_RE.sub(sub, raw)

    def connect(self):
        pass

    def disconnect(self):
        pass

    def connect_with_retry(self):
        delays = [0.0] + self.retry_policy.delays()
        for i, delay in enumerate(delays):
            if delay:
                # interruptible backoff (mirrors Source.connect_with_retry):
                # a time.sleep here pinned shutdown for the full remaining
                # ladder — CE003's one real engine hit
                if self._stop_retry.wait(delay):
                    return
            try:
                self.connect()
                self.connected = True
                return
            except ConnectionUnavailableError as e:
                log.warning("sink connect failed (attempt %d): %s", i + 1, e)

    def shutdown(self):
        self._stop_retry.set()
        worker = self._retry_worker_inst
        if worker is not None:
            # graceful drain: let pending retry ladders run their natural
            # backoff course (they self-terminate on max_attempts/budget)
            # so a transiently-down endpoint still gets every attempt;
            # only then interrupt, giving stragglers one final attempt.
            worker.join(timeout=5.0)
            worker.stop()
        try:
            self.disconnect()
        finally:
            self.connected = False

    def publish(self, payload, event: Event):
        raise NotImplementedError

    def publish_chunk(self, payload, chunk: EventChunk):
        """Chunk-level publish counterpart.  The default adapts to the
        per-event ``publish`` with a first-row representative Event —
        options are static on this path, so the event argument is only a
        template placeholder.  Batch-native transports override this."""
        ts, row = chunk.row(0)
        self.publish(payload, Event(ts, row))

    # junction-facing
    @hot_path("per-block egress: map + publish")
    def receive_chunk(self, chunk: EventChunk):
        cur = chunk.only(CURRENT)
        if cur.is_empty:
            # nothing publishable (all-EXPIRED/TIMER traffic): return
            # before any Event materialization
            return
        with _ledger().span("publish"):
            self._receive_cur(cur)

    def _receive_cur(self, cur: EventChunk):
        if self._is_dynamic():
            # per-event {{attr}} option templating forces the event path
            for e in cur.to_events():
                self._publish_with_retry(self.mapper.map([e]), e, [e])
            return
        payload = self.mapper.map_chunk(cur)
        if payload is None:     # mapper has no chunk path
            events = cur.to_events()
            self._publish_with_retry(self.mapper.map(events), events[0],
                                     events)
            return
        self._publish_with_retry(payload, None, LazyEvents(cur), chunk=cur)

    def _is_dynamic(self) -> bool:
        return any(isinstance(v, str) and _TEMPLATE_RE.search(v)
                   for v in self.options.values())

    def _publish_any(self, payload, target):
        """Publish dispatch shared with the retry worker: ``target`` is
        the representative Event (per-event path) or the EventChunk."""
        if isinstance(target, EventChunk):
            self.publish_chunk(payload, target)
        else:
            self.publish(payload, target)

    def _publish_with_retry(self, payload, event, events=None, chunk=None):
        """First attempt inline; failures go to the off-thread retry
        worker so the junction never blocks on a sick endpoint."""
        handler = getattr(self, "handler", None)
        if handler is not None:
            if event is None and chunk is not None:
                # the HA SPI speaks per-event: hand it a first-row
                # representative (cold: only when a handler is attached)
                ts, row = chunk.row(0)
                event = Event(ts, row)
            payload = handler.handle(payload, event)
            if payload is None:
                return
        events = events if events is not None else [event]
        if not self.breaker.allow():
            # OPEN circuit: fast-fail without touching the endpoint
            self._terminal_failure(events, ConnectionUnavailableError(
                f"circuit open for sink on {self.stream_def.id}"))
            return
        target = chunk if chunk is not None else event
        try:
            self._publish_any(payload, target)
            self.breaker.record_success()
        except ConnectionUnavailableError as e:
            self.connected = False
            self.breaker.record_failure()
            m = self.resilience
            if m is not None:
                m.sink_publish_failed_total.inc(sink=self.stream_def.id)
            log.warning("sink publish failed on %s (queued for retry): %s",
                        self.stream_def.id, e)
            if not self._retry_worker().submit(payload, target, events, e):
                self._terminal_failure(events, e)

    def _retry_worker(self) -> SinkRetryWorker:
        with self._retry_lock:
            if self._retry_worker_inst is None:
                m = self.resilience
                sid = self.stream_def.id

                def on_retry(task, m=m, sid=sid):
                    if m is not None:
                        m.sink_retry_total.inc(sink=sid)

                self._retry_worker_inst = SinkRetryWorker(
                    name=sid,
                    publish_fn=self._publish_any,
                    policy=self.retry_policy,
                    breaker=self.breaker,
                    on_exhausted=lambda task: self._terminal_failure(
                        task.events, task.last_error, attempts=task.attempt),
                    on_retry=on_retry,
                    capacity=self._retry_capacity)
            return self._retry_worker_inst

    def _terminal_failure(self, events, error, attempts: int = 0):
        """All retries spent (or circuit open / queue full): error store
        when configured, otherwise a counted, logged drop."""
        store = self.error_store
        m = self.resilience
        sid = self.stream_def.id
        if store is not None:
            store.store(make_entry(self.app_name, sid, "sink",
                                   error or ConnectionUnavailableError(
                                       "publish failed"),
                                   events, attempts=attempts))
            if m is not None:
                m.errors_stored_total.inc(len(events), stream=sid,
                                          origin="sink")
        else:
            if m is not None:
                m.sink_dropped_total.inc(len(events), sink=sid)
            log.error("sink for %s dropped %d events after retries: %s",
                      sid, len(events), error)


class InMemorySink(Sink):
    def publish(self, payload, event: Event):
        topic = self.resolve_option("topic", event) or self.stream_def.id
        InMemoryBroker.publish(topic, payload)


class LogSink(Sink):
    """@sink(type='log') (reference LogSink.java)."""

    def publish(self, payload, event: Event):
        prefix = self.options.get("prefix", self.stream_def.id)
        log.info("%s : %s", prefix, payload)


SOURCES = {"inmemory": InMemorySource}
SINKS = {"inmemory": InMemorySink, "log": LogSink}


# ============================================================ distributed sinks

class DistributionStrategy:
    """(reference stream/output/sink/distributed/DistributionStrategy.java +
    RoundRobin/Broadcast/Partitioned implementations)."""

    def __init__(self, n: int):
        self.n = n

    def destinations_for(self, event: Event, key=None) -> List[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def __init__(self, n):
        super().__init__(n)
        self._i = 0

    def destinations_for(self, event, key=None):
        d = self._i % self.n
        self._i += 1
        return [d]


class BroadcastStrategy(DistributionStrategy):
    def destinations_for(self, event, key=None):
        return list(range(self.n))


class PartitionedStrategy(DistributionStrategy):
    def __init__(self, n, key_index: int):
        super().__init__(n)
        self.key_index = key_index

    def destinations_for(self, event, key=None):
        return [hash(event.data[self.key_index]) % self.n]


class DistributedSink(Sink):
    """Multi-destination sink wrapper (reference
    util/transport/{Single,Multi}ClientDistributedSink.java)."""

    def __init__(self, stream_def, options, mapper, destinations: List[Sink],
                 strategy: DistributionStrategy):
        super().__init__(stream_def, options, mapper)
        self.destinations = destinations
        self.strategy = strategy

    def connect(self):
        for d in self.destinations:
            d.connect_with_retry()

    def disconnect(self):
        for d in self.destinations:
            d.disconnect()

    def receive_chunk(self, chunk: EventChunk):
        cur = chunk.only(CURRENT)
        if cur.is_empty:
            return      # all-EXPIRED/TIMER: nothing to materialize
        with _ledger().span("publish"):
            self._publish_cur(cur)

    def _publish_cur(self, cur: EventChunk):
        if isinstance(self.strategy, BroadcastStrategy) and self.destinations \
                and not any(d._is_dynamic() for d in self.destinations):
            # broadcast with static options fans the mapped chunk to every
            # destination — destinations share the mapper config, so probe
            # the chunk path once
            payload = self.destinations[0].mapper.map_chunk(cur)
            if payload is not None:
                lazy = LazyEvents(cur)
                for d in self.destinations:
                    d._publish_with_retry(payload, None, lazy, chunk=cur)
                return
        # routed strategies pick destinations per event
        for e in cur.to_events():
            for di in self.strategy.destinations_for(e):
                self.destinations[di]._publish_with_retry(
                    self.destinations[di].mapper.map([e]), e)


# ===================================================================== wiring

def attach_sources_and_sinks(app_runtime):
    """Scan stream definitions for @source/@sink annotations."""
    ctx = app_runtime.siddhi_context
    shm = getattr(ctx, "source_handler_manager", None)
    khm = getattr(ctx, "sink_handler_manager", None)
    for sid, d in list(app_runtime.stream_definitions.items()):
        for ann in find_all(d.annotations, "source"):
            src = _build_source(app_runtime, d, ann)
            if shm is not None:
                src.handler = shm.generate_source_handler(src)
            app_runtime.sources.append(src)
        for ann in find_all(d.annotations, "sink"):
            sink = _build_sink(app_runtime, d, ann)
            if khm is not None:
                sink.handler = khm.generate_sink_handler(sink)
            sink.bind_runtime(app_runtime)
            for dest in getattr(sink, "destinations", []):
                dest.bind_runtime(app_runtime)
            app_runtime.sinks.append(sink)
            app_runtime.junctions[sid].subscribe(sink)


def _map_options(ann: Annotation) -> (str, Dict[str, str]):
    m = find_annotation(ann.annotations, "map")
    if m is None:
        return "passthrough", {}
    return (m.get("type", "passThrough") or "passThrough").lower(), m.as_dict()


def _build_source(app_runtime, d, ann: Annotation) -> Source:
    stype = (ann.get("type", "inMemory") or "inMemory").lower()
    opts = ann.as_dict()
    map_type, map_opts = _map_options(ann)
    mapper_cls = SOURCE_MAPPERS.get(map_type)
    if mapper_cls is None:
        raise SiddhiAppCreationError(f"Unknown source mapper '{map_type}'")
    mapper = mapper_cls(d, map_opts)
    handler = app_runtime.get_input_handler(d.id)
    cls = SOURCES.get(stype)
    if cls is None and app_runtime.extension_registry is not None:
        cls = app_runtime.extension_registry.find_source(stype)
    if cls is None:
        raise SiddhiAppCreationError(f"Unknown source type '{stype}'")
    return cls(d, opts, mapper, handler)


def _build_sink(app_runtime, d, ann: Annotation) -> Sink:
    stype = (ann.get("type", "inMemory") or "inMemory").lower()
    opts = ann.as_dict()
    map_type, map_opts = _map_options(ann)
    mapper_cls = SINK_MAPPERS.get(map_type)
    if mapper_cls is None:
        raise SiddhiAppCreationError(f"Unknown sink mapper '{map_type}'")
    mapper = mapper_cls(d, map_opts)
    dist = find_annotation(ann.annotations, "distribution")
    cls = SINKS.get(stype)
    if cls is None and app_runtime.extension_registry is not None:
        cls = app_runtime.extension_registry.find_sink(stype)
    if cls is None:
        raise SiddhiAppCreationError(f"Unknown sink type '{stype}'")
    if dist is not None:
        dests = []
        for dest_ann in find_all(dist.annotations, "destination"):
            dopts = dict(opts)
            dopts.update(dest_ann.as_dict())
            dests.append(cls(d, dopts, mapper_cls(d, map_opts)))
        strategy_name = (dist.get("strategy", "roundRobin") or "").lower()
        if strategy_name == "broadcast":
            strategy = BroadcastStrategy(len(dests))
        elif strategy_name == "partitioned":
            key = dist.get("partitionKey", d.attributes[0].name)
            idx = d.index_of(key)
            strategy = PartitionedStrategy(len(dests), max(idx, 0))
        else:
            strategy = RoundRobinStrategy(len(dests))
        return DistributedSink(d, opts, mapper, dests, strategy)
    return cls(d, opts, mapper)
