"""Sources, sinks, mappers and the in-memory transport.

(reference: stream/input/source/{Source,SourceMapper}.java lifecycle with
backoff retry, stream/output/sink/{Sink,SinkMapper}.java, InMemory transport
util/transport/InMemoryBroker.java, sink option {{templates}} via
TemplateBuilder/OptionHolder, distributed sinks
stream/output/sink/distributed/*.)

Wired from `@source(type='inMemory', topic='t', @map(type='passThrough'))` /
`@sink(...)` annotations on stream definitions.
"""
from __future__ import annotations

import json
import logging
import re
import threading
import time
from typing import Any, Dict, List, Optional


from ..query_api.annotation import Annotation, find_all, find_annotation
from ..utils.errors import (ConnectionUnavailableError, MappingFailedError,
                            SiddhiAppCreationError)
from .event import CURRENT, Event, EventChunk
from .resilience import (CircuitBreaker, RetryPolicy, SinkRetryWorker,
                         make_entry)

log = logging.getLogger(__name__)


# ===================================================================== broker

class InMemoryBroker:
    """Global topic bus (reference util/transport/InMemoryBroker.java)."""

    _subscribers: Dict[str, List[Any]] = {}
    _lock = threading.Lock()

    @classmethod
    def subscribe(cls, subscriber):
        """subscriber: object with .topic and .on_message(obj)."""
        with cls._lock:
            cls._subscribers.setdefault(subscriber.topic, []).append(subscriber)

    @classmethod
    def unsubscribe(cls, subscriber):
        with cls._lock:
            subs = cls._subscribers.get(subscriber.topic, [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, obj):
        for s in list(cls._subscribers.get(topic, [])):
            s.on_message(obj)


# ===================================================================== mappers

class SourceMapper:
    """format → Event[] (reference stream/input/source/SourceMapper.java)."""

    def __init__(self, definition, options: Dict[str, str]):
        self.definition = definition
        self.options = options

    def map(self, obj) -> List[Event]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    def map(self, obj) -> List[Event]:
        if isinstance(obj, Event):
            return [obj]
        if isinstance(obj, (list, tuple)):
            if obj and isinstance(obj[0], Event):
                return list(obj)
            now = int(time.time() * 1000)
            if obj and isinstance(obj[0], (list, tuple)):
                return [Event(now, list(r)) for r in obj]   # batch of rows
            return [Event(now, list(obj))]
        raise MappingFailedError(f"passThrough cannot map {type(obj)}")


class JsonSourceMapper(SourceMapper):
    """{"event": {attr: value, ...}} or a list of such (reference
    siddhi-map-json extension behaviour)."""

    def map(self, obj) -> List[Event]:
        data = json.loads(obj) if isinstance(obj, (str, bytes)) else obj
        if isinstance(data, dict):
            data = [data]
        out = []
        for item in data:
            payload = item.get("event", item)
            row = [payload.get(a.name) for a in self.definition.attributes]
            out.append(Event(int(item.get("timestamp",
                                          time.time() * 1000)), row))
        return out


class SinkMapper:
    def __init__(self, definition, options: Dict[str, str]):
        self.definition = definition
        self.options = options

    def map(self, events: List[Event]):
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, events: List[Event]):
        return events


class JsonSinkMapper(SinkMapper):
    def map(self, events: List[Event]):
        names = [a.name for a in self.definition.attributes]
        return json.dumps([{"event": dict(zip(names, e.data)),
                            "timestamp": e.timestamp} for e in events])


class TextSinkMapper(SinkMapper):
    def map(self, events: List[Event]):
        names = [a.name for a in self.definition.attributes]
        return "\n".join(
            ", ".join(f"{n}:{v}" for n, v in zip(names, e.data))
            for e in events)


SOURCE_MAPPERS = {"passthrough": PassThroughSourceMapper,
                  "json": JsonSourceMapper}
SINK_MAPPERS = {"passthrough": PassThroughSinkMapper,
                "json": JsonSinkMapper, "text": TextSinkMapper}


# ===================================================================== source

class SourceHandler:
    """HA hook between a source and its input handler: an outer platform
    subclasses this to gate events on passive nodes (reference
    stream/input/source/SourceHandler.java + SourceHandlerManager — the
    active/passive coordination SPI)."""

    def handle(self, events):
        """Return the events to forward (possibly filtered), or None to
        drop (passive node)."""
        return events


class SinkHandler:
    """HA hook before a sink publishes (reference
    stream/output/sink/SinkHandler.java)."""

    def handle(self, payload, event):
        """Return the payload to publish, or None to suppress."""
        return payload


class SourceHandlerManager:
    def generate_source_handler(self, source) -> SourceHandler:
        return SourceHandler()


class SinkHandlerManager:
    def generate_sink_handler(self, sink) -> SinkHandler:
        return SinkHandler()


class Source:
    """Base source with connect-retry lifecycle
    (reference Source.connectWithRetry:128-157 + BackoffRetryCounter).

    The old fixed ``RETRIES`` ladder is replaced by a per-source
    ``RetryPolicy`` (exponential backoff + jitter) configurable through
    ``retry.*`` annotation options."""

    def __init__(self, stream_def, options: Dict[str, str],
                 mapper: SourceMapper, input_handler):
        self.stream_def = stream_def
        self.options = options
        self.mapper = mapper
        self.input_handler = input_handler
        self.connected = False
        self.retry_policy = RetryPolicy.from_options(options)
        self._stop_retry = threading.Event()

    def connect(self):
        raise NotImplementedError

    def disconnect(self):
        pass

    def connect_with_retry(self):
        delays = [0.0] + self.retry_policy.delays()
        for i, delay in enumerate(delays):
            if delay:
                if self._stop_retry.wait(delay):
                    return
            try:
                self.connect()
                self.connected = True
                return
            except ConnectionUnavailableError as e:
                log.warning("source connect failed (attempt %d): %s", i + 1, e)
        log.error("source for %s could not connect", self.stream_def.id)

    def shutdown(self):
        self._stop_retry.set()
        try:
            self.disconnect()
        finally:
            self.connected = False

    def deliver(self, obj):
        try:
            events = self.mapper.map(obj)
        except MappingFailedError as e:
            log.error("mapping failed on %s: %s", self.stream_def.id, e)
            return
        handler = getattr(self, "handler", None)
        if handler is not None and events:
            events = handler.handle(events)
        if events:
            self.input_handler.send(events)


class InMemorySource(Source):
    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.topic = self.options.get("topic", self.stream_def.id)

    def connect(self):
        InMemoryBroker.subscribe(self)

    def disconnect(self):
        InMemoryBroker.unsubscribe(self)

    def on_message(self, obj):
        self.deliver(obj)


# ===================================================================== sink

_TEMPLATE_RE = re.compile(r"\{\{(\w+)\}\}")


class Sink:
    """Base sink; junction subscriber publishing mapped events
    (reference Sink.java:49-167).

    Publish resilience: the first attempt runs inline on the junction
    thread; a ``ConnectionUnavailableError`` hands the payload to this
    sink's bounded retry worker (exponential backoff, off-thread) and a
    ``CircuitBreaker`` turns a persistently dead endpoint into fast-fail
    (events → error store when one is configured, else a counted drop).
    Knobs ride the ``@sink`` annotation: ``retry.max.attempts``,
    ``retry.base.delay.ms``, ``retry.max.delay.ms``, ``retry.multiplier``,
    ``retry.budget.ms``, ``retry.queue.size``,
    ``circuit.failure.threshold``, ``circuit.reset.ms``."""

    def __init__(self, stream_def, options: Dict[str, str], mapper: SinkMapper):
        self.stream_def = stream_def
        self.options = options
        self.mapper = mapper
        self.connected = False
        self.retry_policy = RetryPolicy.from_options(options)
        self.breaker = CircuitBreaker.from_options(options)
        self._retry_capacity = int(options.get("retry.queue.size", "1024"))
        self._retry_worker_inst = None
        self._retry_lock = threading.Lock()
        self._runtime = None      # set by attach_sources_and_sinks

    # ---- runtime binding (error store + metrics) ----------------------

    def bind_runtime(self, app_runtime):
        self._runtime = app_runtime
        m = self.resilience
        if m is not None:
            sid = self.stream_def.id
            m.circuit_state.set_fn(
                lambda b=self.breaker: b.state_code, sink=sid)

            def _on_transition(old, new, m=m, sid=sid, rt=app_runtime):
                m.circuit_transitions_total.inc(sink=sid, to=new)
                if new == "open":
                    # incident bus: a sink fast-failing is exactly the
                    # moment the operator wants the recent flight ring
                    from .flight import flight
                    flight().emit("circuit_open",
                                  app=getattr(rt, "name", ""),
                                  detail={"sink": sid, "from": old},
                                  runtime=rt)
            self.breaker.on_transition = _on_transition

    @property
    def app_name(self) -> str:
        return self._runtime.name if self._runtime is not None else ""

    @property
    def error_store(self):
        return getattr(self._runtime, "error_store", None)

    @property
    def resilience(self):
        return getattr(self._runtime, "resilience_metrics", None)

    # dynamic option templating: topic='{{symbol}}' resolved per event
    def resolve_option(self, key: str, event: Event) -> Optional[str]:
        raw = self.options.get(key)
        if raw is None:
            return None
        names = [a.name for a in self.stream_def.attributes]

        def sub(m):
            try:
                return str(event.data[names.index(m.group(1))])
            except ValueError:
                return m.group(0)
        return _TEMPLATE_RE.sub(sub, raw)

    def connect(self):
        pass

    def disconnect(self):
        pass

    def connect_with_retry(self):
        delays = [0.0] + self.retry_policy.delays()
        for i, delay in enumerate(delays):
            if delay:
                time.sleep(delay)
            try:
                self.connect()
                self.connected = True
                return
            except ConnectionUnavailableError as e:
                log.warning("sink connect failed (attempt %d): %s", i + 1, e)

    def shutdown(self):
        worker = self._retry_worker_inst
        if worker is not None:
            # graceful drain: let pending retry ladders run their natural
            # backoff course (they self-terminate on max_attempts/budget)
            # so a transiently-down endpoint still gets every attempt;
            # only then interrupt, giving stragglers one final attempt.
            worker.join(timeout=5.0)
            worker.stop()
        try:
            self.disconnect()
        finally:
            self.connected = False

    def publish(self, payload, event: Event):
        raise NotImplementedError

    # junction-facing
    def receive_chunk(self, chunk: EventChunk):
        events = chunk.only(CURRENT).to_events()
        if not events:
            return
        if self._is_dynamic():
            for e in events:
                self._publish_with_retry(self.mapper.map([e]), e, [e])
        else:
            self._publish_with_retry(self.mapper.map(events), events[0],
                                     events)

    def _is_dynamic(self) -> bool:
        return any(isinstance(v, str) and _TEMPLATE_RE.search(v)
                   for v in self.options.values())

    def _publish_with_retry(self, payload, event, events=None):
        """First attempt inline; failures go to the off-thread retry
        worker so the junction never blocks on a sick endpoint."""
        handler = getattr(self, "handler", None)
        if handler is not None:
            payload = handler.handle(payload, event)
            if payload is None:
                return
        events = events if events is not None else [event]
        if not self.breaker.allow():
            # OPEN circuit: fast-fail without touching the endpoint
            self._terminal_failure(events, ConnectionUnavailableError(
                f"circuit open for sink on {self.stream_def.id}"))
            return
        try:
            self.publish(payload, event)
            self.breaker.record_success()
        except ConnectionUnavailableError as e:
            self.connected = False
            self.breaker.record_failure()
            m = self.resilience
            if m is not None:
                m.sink_publish_failed_total.inc(sink=self.stream_def.id)
            log.warning("sink publish failed on %s (queued for retry): %s",
                        self.stream_def.id, e)
            if not self._retry_worker().submit(payload, event, events, e):
                self._terminal_failure(events, e)

    def _retry_worker(self) -> SinkRetryWorker:
        with self._retry_lock:
            if self._retry_worker_inst is None:
                m = self.resilience
                sid = self.stream_def.id

                def on_retry(task, m=m, sid=sid):
                    if m is not None:
                        m.sink_retry_total.inc(sink=sid)

                self._retry_worker_inst = SinkRetryWorker(
                    name=sid,
                    publish_fn=self.publish,
                    policy=self.retry_policy,
                    breaker=self.breaker,
                    on_exhausted=lambda task: self._terminal_failure(
                        task.events, task.last_error, attempts=task.attempt),
                    on_retry=on_retry,
                    capacity=self._retry_capacity)
            return self._retry_worker_inst

    def _terminal_failure(self, events, error, attempts: int = 0):
        """All retries spent (or circuit open / queue full): error store
        when configured, otherwise a counted, logged drop."""
        store = self.error_store
        m = self.resilience
        sid = self.stream_def.id
        if store is not None:
            store.store(make_entry(self.app_name, sid, "sink",
                                   error or ConnectionUnavailableError(
                                       "publish failed"),
                                   events, attempts=attempts))
            if m is not None:
                m.errors_stored_total.inc(len(events), stream=sid,
                                          origin="sink")
        else:
            if m is not None:
                m.sink_dropped_total.inc(len(events), sink=sid)
            log.error("sink for %s dropped %d events after retries: %s",
                      sid, len(events), error)


class InMemorySink(Sink):
    def publish(self, payload, event: Event):
        topic = self.resolve_option("topic", event) or self.stream_def.id
        InMemoryBroker.publish(topic, payload)


class LogSink(Sink):
    """@sink(type='log') (reference LogSink.java)."""

    def publish(self, payload, event: Event):
        prefix = self.options.get("prefix", self.stream_def.id)
        log.info("%s : %s", prefix, payload)


SOURCES = {"inmemory": InMemorySource}
SINKS = {"inmemory": InMemorySink, "log": LogSink}


# ============================================================ distributed sinks

class DistributionStrategy:
    """(reference stream/output/sink/distributed/DistributionStrategy.java +
    RoundRobin/Broadcast/Partitioned implementations)."""

    def __init__(self, n: int):
        self.n = n

    def destinations_for(self, event: Event, key=None) -> List[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def __init__(self, n):
        super().__init__(n)
        self._i = 0

    def destinations_for(self, event, key=None):
        d = self._i % self.n
        self._i += 1
        return [d]


class BroadcastStrategy(DistributionStrategy):
    def destinations_for(self, event, key=None):
        return list(range(self.n))


class PartitionedStrategy(DistributionStrategy):
    def __init__(self, n, key_index: int):
        super().__init__(n)
        self.key_index = key_index

    def destinations_for(self, event, key=None):
        return [hash(event.data[self.key_index]) % self.n]


class DistributedSink(Sink):
    """Multi-destination sink wrapper (reference
    util/transport/{Single,Multi}ClientDistributedSink.java)."""

    def __init__(self, stream_def, options, mapper, destinations: List[Sink],
                 strategy: DistributionStrategy):
        super().__init__(stream_def, options, mapper)
        self.destinations = destinations
        self.strategy = strategy

    def connect(self):
        for d in self.destinations:
            d.connect_with_retry()

    def disconnect(self):
        for d in self.destinations:
            d.disconnect()

    def receive_chunk(self, chunk: EventChunk):
        events = chunk.only(CURRENT).to_events()
        for e in events:
            for di in self.strategy.destinations_for(e):
                self.destinations[di]._publish_with_retry(
                    self.destinations[di].mapper.map([e]), e)


# ===================================================================== wiring

def attach_sources_and_sinks(app_runtime):
    """Scan stream definitions for @source/@sink annotations."""
    ctx = app_runtime.siddhi_context
    shm = getattr(ctx, "source_handler_manager", None)
    khm = getattr(ctx, "sink_handler_manager", None)
    for sid, d in list(app_runtime.stream_definitions.items()):
        for ann in find_all(d.annotations, "source"):
            src = _build_source(app_runtime, d, ann)
            if shm is not None:
                src.handler = shm.generate_source_handler(src)
            app_runtime.sources.append(src)
        for ann in find_all(d.annotations, "sink"):
            sink = _build_sink(app_runtime, d, ann)
            if khm is not None:
                sink.handler = khm.generate_sink_handler(sink)
            sink.bind_runtime(app_runtime)
            for dest in getattr(sink, "destinations", []):
                dest.bind_runtime(app_runtime)
            app_runtime.sinks.append(sink)
            app_runtime.junctions[sid].subscribe(sink)


def _map_options(ann: Annotation) -> (str, Dict[str, str]):
    m = find_annotation(ann.annotations, "map")
    if m is None:
        return "passthrough", {}
    return (m.get("type", "passThrough") or "passThrough").lower(), m.as_dict()


def _build_source(app_runtime, d, ann: Annotation) -> Source:
    stype = (ann.get("type", "inMemory") or "inMemory").lower()
    opts = ann.as_dict()
    map_type, map_opts = _map_options(ann)
    mapper_cls = SOURCE_MAPPERS.get(map_type)
    if mapper_cls is None:
        raise SiddhiAppCreationError(f"Unknown source mapper '{map_type}'")
    mapper = mapper_cls(d, map_opts)
    handler = app_runtime.get_input_handler(d.id)
    cls = SOURCES.get(stype)
    if cls is None and app_runtime.extension_registry is not None:
        cls = app_runtime.extension_registry.find_source(stype)
    if cls is None:
        raise SiddhiAppCreationError(f"Unknown source type '{stype}'")
    return cls(d, opts, mapper, handler)


def _build_sink(app_runtime, d, ann: Annotation) -> Sink:
    stype = (ann.get("type", "inMemory") or "inMemory").lower()
    opts = ann.as_dict()
    map_type, map_opts = _map_options(ann)
    mapper_cls = SINK_MAPPERS.get(map_type)
    if mapper_cls is None:
        raise SiddhiAppCreationError(f"Unknown sink mapper '{map_type}'")
    mapper = mapper_cls(d, map_opts)
    dist = find_annotation(ann.annotations, "distribution")
    cls = SINKS.get(stype)
    if cls is None and app_runtime.extension_registry is not None:
        cls = app_runtime.extension_registry.find_sink(stype)
    if cls is None:
        raise SiddhiAppCreationError(f"Unknown sink type '{stype}'")
    if dist is not None:
        dests = []
        for dest_ann in find_all(dist.annotations, "destination"):
            dopts = dict(opts)
            dopts.update(dest_ann.as_dict())
            dests.append(cls(d, dopts, mapper_cls(d, map_opts)))
        strategy_name = (dist.get("strategy", "roundRobin") or "").lower()
        if strategy_name == "broadcast":
            strategy = BroadcastStrategy(len(dests))
        elif strategy_name == "partitioned":
            key = dist.get("partitionKey", d.attributes[0].name)
            idx = d.index_of(key)
            strategy = PartitionedStrategy(len(dests), max(idx, 0))
        else:
            strategy = RoundRobinStrategy(len(dests))
        return DistributedSink(d, opts, mapper, dests, strategy)
    return cls(d, opts, mapper)
