"""Flight recorder + incident bundles.

The engine's most important state lives on-device in batched carries —
after a fault, counters alone cannot reconstruct what the automata were
doing.  This module is the black-box layer production streaming stacks
(and training stacks) carry: an always-cheap bounded ring of per-block
structured records, plus an incident hook bus that dumps a full bundle
(recent ring + metrics snapshot + Chrome-trace spans + analyzer/plan
report + env/config) when something trips.

  * ``FlightRecorder.record_block`` — called by every device runtime's
    ingest path (plan/planner.py, next to ``record_app_block``): block
    id, stream, batch size, per-kernel dispatch/scan-tick deltas from
    ``KernelProfiler``, junction queue depth/saturation, scheduler
    fires, device telemetry, last errors.  A deque append under a lock —
    O(1), no device work, no allocation beyond the record dict.
  * ``FlightRecorder.emit`` — the incident bus.  Wired triggers:
    watchdog trips (WD001, core/overload.py), circuit-breaker OPEN
    transitions (core/source_sink.py), quarantine bursts over
    ``SIDDHI_TPU_FLIGHT_QUARANTINE_BURST`` rejects, ingest
    ``BufferOverflowError`` and uncaught junction exceptions
    (core/stream.py).  ``POST /siddhi/apps/{app}/debug/bundle`` emits on
    demand.  Bundles are kept in memory for ``GET /incidents`` /
    ``GET /incidents/{id}/bundle`` and written as JSON under
    ``SIDDHI_TPU_FLIGHT_DIR`` (default: <tmp>/siddhi_tpu_flight).

Kill switch: ``SIDDHI_TPU_FLIGHT=0`` disables both the ring and the
bus.  Knobs: ``SIDDHI_TPU_FLIGHT_RING`` (ring capacity, default 256),
``SIDDHI_TPU_FLIGHT_KEEP`` (retained bundles, default 16).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .hotpath import hot_path

log = logging.getLogger(__name__)

#: Kill switch for the whole flight-recorder subsystem.
FLIGHT_ENV = "SIDDHI_TPU_FLIGHT"
#: Ring capacity (per-block records kept).
RING_ENV = "SIDDHI_TPU_FLIGHT_RING"
#: Bundle dump directory.
DIR_ENV = "SIDDHI_TPU_FLIGHT_DIR"
#: Retained bundles (memory AND directory pruning).
KEEP_ENV = "SIDDHI_TPU_FLIGHT_KEEP"
#: Quarantine rejects in one routing call that count as a burst.
QUARANTINE_BURST_ENV = "SIDDHI_TPU_FLIGHT_QUARANTINE_BURST"

DEFAULT_RING = 256
DEFAULT_KEEP = 16
DEFAULT_QUARANTINE_BURST = 50


# record_block asks "am I on?" once per ingest block; os.environ.get
# pays ~0.9 us per call (key encode + value decode), so the check rides
# the same direct-``_data`` read as core/ledger.py's ledger_enabled —
# still re-read per call, so flipping SIDDHI_TPU_FLIGHT mid-process
# keeps working.  Falls back to the public API if the internals move.
_ENV_DATA = getattr(os.environ, "_data", None)
_FLIGHT_KEY = (os.environ.encodekey(FLIGHT_ENV)
               if _ENV_DATA is not None and hasattr(os.environ, "encodekey")
               else FLIGHT_ENV)
if _ENV_DATA is not None and _FLIGHT_KEY not in _ENV_DATA and \
        FLIGHT_ENV in os.environ:
    _ENV_DATA = None        # key codec mismatch: use the public API

_PARSED: Dict[Any, bool] = {}       # raw env value -> parsed verdict


def flight_enabled() -> bool:
    if _ENV_DATA is not None:
        raw = _ENV_DATA.get(_FLIGHT_KEY)
    else:
        raw = os.environ.get(FLIGHT_ENV)
    if raw is None:
        return True
    v = _PARSED.get(raw)
    if v is None:
        s = os.fsdecode(raw) if isinstance(raw, bytes) else raw
        v = s.strip().lower() not in ("0", "false", "off", "no")
        _PARSED[raw] = v
    return v


def _env_int(key: str, default: int) -> int:
    try:
        v = int(os.environ.get(key, ""))
        return v if v > 0 else default
    except (TypeError, ValueError):
        return default


def quarantine_burst_threshold() -> int:
    return _env_int(QUARANTINE_BURST_ENV, DEFAULT_QUARANTINE_BURST)


def bundle_dir() -> str:
    d = os.environ.get(DIR_ENV, "").strip()
    if d:
        return d
    import tempfile
    return os.path.join(tempfile.gettempdir(), "siddhi_tpu_flight")


def _jsonable(v):
    """Best-effort JSON coercion: numpy scalars/arrays → python."""
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        pass
    if hasattr(v, "tolist"):
        return v.tolist()
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set)):
        return [_jsonable(x) for x in v]
    return repr(v)


class FlightRecorder:
    """Process-global bounded ring of per-block records + incident bus.

    Everything is host-side and lock-guarded; the hot path
    (``record_block``) is one dict build and one deque append."""

    def __init__(self, capacity: Optional[int] = None,
                 keep: Optional[int] = None):
        self.capacity = capacity or _env_int(RING_ENV, DEFAULT_RING)
        self.keep = keep or _env_int(KEEP_ENV, DEFAULT_KEEP)
        self._lock = threading.RLock()
        self._ring: "deque" = deque(maxlen=self.capacity)
        self._errors: "deque" = deque(maxlen=32)
        self._incidents: List[Dict[str, Any]] = []
        self._bundles: Dict[str, Dict[str, Any]] = {}
        self._seq = 0
        self._inc_seq = 0

    @property
    def enabled(self) -> bool:
        return flight_enabled()

    # ------------------------------------------------------------ ring

    @hot_path("per-block flight-ring append")
    def record_block(self, app: str, stream: str = "", batch: int = 0,
                     dispatches: int = 0, scan_ticks: int = 0,
                     junction=None, scheduler=None,
                     telemetry=None, extra: Optional[dict] = None) -> None:
        """One ingest block's structured record.  Called by the device
        runtimes' ingest paths; cheap enough to stay always-on."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {
            "block": self._seq, "t": time.time(), "app": app,
            "stream": stream, "batch": int(batch),
            "dispatches": int(dispatches), "scan_ticks": int(scan_ticks),
        }
        if junction is not None:
            try:
                rec["queue_depth"] = int(junction.queue_depth())
                rec["saturation"] = float(junction.saturation())
            except Exception:   # noqa: BLE001 — recording must never raise
                pass
        if scheduler is not None:
            rec["scheduler_fires"] = int(getattr(scheduler, "fires", 0))
        if telemetry is not None:
            rec["telemetry"] = _jsonable(telemetry)
        if extra:
            rec.update(_jsonable(extra))
        with self._lock:
            self._seq += 1
            rec["block"] = self._seq
            if self._errors:
                rec["last_error"] = self._errors[-1]
            self._ring.append(rec)

    def record_compile(self, kind: str, signature: str, trigger: str,
                       blocked_s: float) -> None:
        """One XLA compile on the ring — the same timeline as the ingest
        blocks, so a bundle shows exactly which compile interleaved with
        (or blocked) which block.  Called by plan/shapes.py."""
        if not self.enabled:
            return
        rec = {"t": time.time(), "compile": signature, "kernel": kind,
               "trigger": trigger, "blocked_s": round(blocked_s, 4)}
        with self._lock:
            self._seq += 1
            rec["block"] = self._seq
            self._ring.append(rec)

    def note_error(self, app: str, where: str, err: BaseException) -> None:
        """Track the most recent errors so block records and bundles can
        carry them (stream junction delivery failures, sink errors)."""
        if not self.enabled:
            return
        with self._lock:
            self._errors.append({"t": time.time(), "app": app,
                                 "where": where,
                                 "error": f"{type(err).__name__}: {err}"})

    def ring(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    # ------------------------------------------------------------ bus

    def emit(self, kind: str, app: str = "", detail: Optional[dict] = None,
             runtime=None) -> Optional[Dict[str, Any]]:
        """Incident: build a bundle from the current ring + observability
        surfaces, retain it for the REST endpoints, and dump it as JSON
        under ``bundle_dir()``.  Returns the bundle (None when the
        recorder is disabled).  Never raises — incident handling must not
        make a fault worse."""
        if not self.enabled:
            return None
        with self._lock:
            self._inc_seq += 1
            bid = f"inc-{self._inc_seq:04d}"
        bundle: Dict[str, Any] = {
            "id": bid, "kind": kind, "app": app, "time": time.time(),
            "detail": _jsonable(detail or {}),
            "ring": self.ring(),
            "errors": list(self._errors),
            "env": {k: v for k, v in os.environ.items()
                    if k.startswith("SIDDHI_TPU_") or
                    k in ("JAX_PLATFORMS",)},
            "config": {"ring_capacity": self.capacity,
                       "bundles_kept": self.keep,
                       "bundle_dir": bundle_dir()},
        }
        try:
            from .profiling import profiler
            prof = profiler()
            bundle["kernels"] = prof.snapshot()
            bundle["metrics"] = prof.prometheus_lines()
        except Exception:   # noqa: BLE001
            log.exception("flight bundle: kernel snapshot failed")
        try:
            from .tracing import tracer
            # drop an incident marker so the span timeline shows WHERE
            # the trip happened, then embed the (bounded) trace
            tracer().instant(f"incident.{kind}", cat="incident",
                             id=bid, app=app)
            bundle["trace"] = tracer().to_dict(limit=20_000)
        except Exception:   # noqa: BLE001
            log.exception("flight bundle: trace export failed")
        if runtime is not None:
            try:
                sm = runtime.app_ctx.statistics_manager
                if sm is not None:
                    bundle["statistics"] = sm.snapshot()
                dt = getattr(runtime, "device_telemetry", None)
                if dt is not None:
                    bundle.setdefault("statistics", {})["telemetry"] = \
                        dt.snapshot()
                im = getattr(runtime, "ingest_metrics", None)
                if im is not None:
                    bundle.setdefault("metrics", []).extend(
                        im.prometheus_lines())
                rm = getattr(runtime, "resilience_metrics", None)
                if rm is not None:
                    bundle.setdefault("metrics", []).extend(
                        rm.prometheus_lines())
                analysis = getattr(runtime, "analysis", None)
                if analysis is not None:
                    bundle["analysis"] = analysis.as_dicts()
                    plan = getattr(analysis, "plan", None)
                    if plan is not None:
                        bundle["plan"] = plan.as_dict()
                wd = getattr(runtime, "watchdog", None)
                if wd is not None and wd.incidents:
                    bundle["watchdog_incidents"] = list(wd.incidents)
            except Exception:   # noqa: BLE001
                log.exception("flight bundle: runtime snapshot failed")
        bundle = _jsonable(bundle)
        with self._lock:
            self._incidents.append({"id": bid, "kind": kind, "app": app,
                                    "time": bundle["time"]})
            self._bundles[bid] = bundle
            # retention: oldest bundles age out (summaries stay listed)
            for inc in self._incidents:
                if len(self._bundles) <= self.keep:
                    break
                self._bundles.pop(inc["id"], None)
        self._dump(bundle)
        log.error("flight incident %s (%s) on app '%s': bundle dumped to "
                  "%s", bid, kind, app, bundle_dir())
        return bundle

    def _dump(self, bundle: Dict[str, Any]) -> None:
        try:
            d = bundle_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{bundle['id']}.json")
            with open(path, "w") as f:
                json.dump(bundle, f, indent=1)
            kept = sorted(p for p in os.listdir(d)
                          if p.startswith("inc-") and p.endswith(".json"))
            for p in kept[:-self.keep]:
                os.unlink(os.path.join(d, p))
        except Exception:   # noqa: BLE001 — dumping must never raise
            log.exception("flight bundle dump failed")

    # ------------------------------------------------------------ REST

    def incidents(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._incidents)

    def bundle(self, incident_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._bundles.get(incident_id)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._errors.clear()
            self._incidents.clear()
            self._bundles.clear()
            self._seq = 0
            self._inc_seq = 0


_GLOBAL = FlightRecorder()


def flight() -> FlightRecorder:
    return _GLOBAL
