"""Timestamp generation + playback virtual time.

(reference: util/timestamp/TimestampGeneratorImpl.java — wall clock by default;
in @app:playback mode currentTime() returns the last seen event timestamp,
optionally advanced by an idle-time heartbeat.)
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class TimestampGenerator:
    def __init__(self):
        self._playback = False
        self._last_event_time = -1
        self._idle_time_ms: Optional[int] = None
        self._increment_ms: Optional[int] = None
        self._listeners: List[Callable[[int], None]] = []
        self._heartbeat: Optional[threading.Timer] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------ config
    def enable_playback(self, idle_time_ms: Optional[int] = None,
                        increment_ms: Optional[int] = None):
        self._playback = True
        self._idle_time_ms = idle_time_ms
        self._increment_ms = increment_ms
        self._arm_heartbeat()

    @property
    def in_playback(self) -> bool:
        return self._playback

    # ------------------------------------------------------------ use
    def current_time(self) -> int:
        if self._playback:
            return self._last_event_time
        return int(time.time() * 1000)

    def observe_event_time(self, ts: int):
        if self._playback:
            with self._lock:
                if ts > self._last_event_time:
                    self._last_event_time = ts
            self._arm_heartbeat()

    def add_time_change_listener(self, fn: Callable[[int], None]):
        self._listeners.append(fn)

    def _arm_heartbeat(self):
        if not self._playback or self._idle_time_ms is None:
            return
        if self._heartbeat is not None:
            self._heartbeat.cancel()

        def tick():
            with self._lock:
                self._last_event_time += (self._increment_ms or 0)
                now = self._last_event_time
            for fn in list(self._listeners):
                fn(now)
            self._arm_heartbeat()
        self._heartbeat = threading.Timer(self._idle_time_ms / 1000.0, tick)
        self._heartbeat.daemon = True
        self._heartbeat.start()

    def shutdown(self):
        if self._heartbeat is not None:
            self._heartbeat.cancel()
            self._heartbeat = None
