"""Timestamp generation + playback virtual time.

(reference: util/timestamp/TimestampGeneratorImpl.java — wall clock by default;
in @app:playback mode currentTime() returns the last seen event timestamp,
optionally advanced by an idle-time heartbeat.)
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from .lockwitness import maybe_wrap
from .threads import engine_thread_name


class TimestampGenerator:
    def __init__(self):
        self._playback = False
        self._last_event_time = -1
        self._idle_time_ms: Optional[int] = None
        self._increment_ms: Optional[int] = None
        self._listeners: List[Callable[[int], None]] = []
        self._heartbeat: Optional[threading.Timer] = None
        self._stopped = False
        self._lock = maybe_wrap(
            threading.Lock(), "core.timestamp.TimestampGenerator._lock")

    # ------------------------------------------------------------ config
    def enable_playback(self, idle_time_ms: Optional[int] = None,
                        increment_ms: Optional[int] = None):
        self._playback = True
        self._idle_time_ms = idle_time_ms
        self._increment_ms = increment_ms
        self._arm_heartbeat()

    @property
    def in_playback(self) -> bool:
        return self._playback

    # ------------------------------------------------------------ use
    def current_time(self) -> int:
        if self._playback:
            return self._last_event_time
        return int(time.time() * 1000)

    def observe_event_time(self, ts: int):
        if self._playback:
            with self._lock:
                if ts > self._last_event_time:
                    self._last_event_time = ts
            self._arm_heartbeat()

    def add_time_change_listener(self, fn: Callable[[int], None]):
        self._listeners.append(fn)

    def _arm_heartbeat(self):
        if not self._playback or self._idle_time_ms is None:
            return

        def tick():
            with self._lock:
                if self._stopped:
                    return
                self._last_event_time += (self._increment_ms or 0)
                now = self._last_event_time
            for fn in list(self._listeners):
                fn(now)
            self._arm_heartbeat()

        # Timer swap rides _lock: two racing observe_event_time callers
        # used to cancel/replace unguarded and orphan a live timer, and a
        # tick in flight across shutdown() would re-arm forever.
        with self._lock:
            if self._stopped:
                return
            if self._heartbeat is not None:
                self._heartbeat.cancel()
            t = threading.Timer(self._idle_time_ms / 1000.0, tick)
            t.daemon = True
            t.name = engine_thread_name("siddhi-heartbeat")
            self._heartbeat = t
            t.start()

    def shutdown(self):
        with self._lock:
            self._stopped = True
            if self._heartbeat is not None:
                self._heartbeat.cancel()
                self._heartbeat = None
