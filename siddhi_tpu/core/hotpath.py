"""Hot-path registry: mark per-event / per-block functions for lint.

``@hot_path("...")`` is a zero-cost marker — it registers the function's
dotted name and hands the function back untouched.  The engine hot-path
lint (analysis/engine/hotpath.py) discovers decorated functions purely
from the AST, so ``python -m siddhi_tpu.analyze --engine`` never imports
the decorated modules (the no-jax guarantee); this runtime registry
exists so tests can cross-check that the static scan found exactly the
functions the engine actually marked.

The reason string is part of the contract: it should say *why* the
function is hot (per-event, per-block, per-span), because that decides
which CE1xx checks are proportionate.
"""
from __future__ import annotations

from typing import Callable, Dict, TypeVar

F = TypeVar("F", bound=Callable)

#: dotted name -> reason, filled at import time by @hot_path sites.
_REGISTRY: Dict[str, str] = {}


def hot_path(reason: str) -> Callable[[F], F]:
    def mark(fn: F) -> F:
        _REGISTRY[f"{fn.__module__}.{fn.__qualname__}"] = reason
        return fn
    return mark


def registry() -> Dict[str, str]:
    return dict(_REGISTRY)
