"""Persistent-state schema registry: the typed model behind SC0xx.

Every ``current_state()`` implementer declares *what it persists* with
one class decorator::

    @persistent_schema("nfa-engine", version=1,
                       schema=Struct(carry=Carry(), base_ts=Scalar("opt_int"),
                                     n_partitions=Scalar("int"),
                                     str_decoder=ListOf("str")),
                       dims={"S": "exact", "K": "ladder", "P": "free",
                             "R": "exact", "C": "exact"})
    class CompiledPatternNFA: ...

The declaration is a tiny node language (:class:`Struct`, :class:`Carry`,
:class:`Scalar`, ...) whose canonical render is digested into a stable
schema fingerprint.  ``SnapshotService`` embeds each element's
*description* (name, version, digest, live dim values, resolved carry
leaves) in the snapshot envelope at persist time, and
:func:`verify_compat` diffs the embedded descriptions against the live
runtime's BEFORE any ``restore_state`` runs — so an incompatible restore
is a typed ``CannotRestoreStateError`` naming an SC0xx code and the
field-level diff, never a jax shape error three frames deep.

Dim kinds are the compatibility policy:

  ``exact``   plan-determined (NFA state count S, capture rows R) —
              restore requires equality;
  ``ladder``  elastic by power-of-two growth (key-lane capacity K) —
              snapshot and live values must differ by an integer 2^n
              factor (SC004 otherwise);
  ``free``    adopted wholesale by restore_state (partition lanes P,
              ring capacity) — never compared;
  ``shards``  the per-shard section count — must match exactly and is
              tied to the pinned FNV-1a routing digest (SC005).

Like core/hotpath.py, the decorator is a zero-cost marker feeding two
consumers: the runtime registry here (snapshot envelopes, restore
verification) and the static AST scan in analysis/state_schema.py
(``analyze --schema``, jax-free).  This module itself must stay
importable without jax: numpy + hashlib only.
"""
from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional, Tuple, TypeVar

import numpy as np

C = TypeVar("C", bound=type)

#: sentinel distinguishing "declared field absent from payload" from None
_ABSENT = object()

SCHEMA_ENVELOPE_VERSION = 2


# ============================================================== node language

class SchemaNode:
    """Base of the declaration language.  ``spec()`` is the canonical
    static render (digested); ``resolve()`` flattens a live payload into
    ``path -> descriptor`` strings for field-level diffs."""

    def spec(self) -> str:
        raise NotImplementedError

    def resolve(self, payload, path: str, out: Dict[str, str],
                findings: List[Tuple[str, str]], decl_name: str) -> None:
        raise NotImplementedError

    def __repr__(self):
        return self.spec()


class Scalar(SchemaNode):
    """A host-side scalar slot.  Renders from the *declared* kind, never
    the live value — an Optional[int] that happens to be None at persist
    time must not diff against one that holds 7."""

    def __init__(self, kind: str):
        self.kind = kind

    def spec(self):
        return self.kind

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = self.kind


class Chunk(SchemaNode):
    """A serialized EventChunk (columnar buffers dict)."""

    def spec(self):
        return "chunk"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = "chunk"


class Opt(SchemaNode):
    def __init__(self, inner: SchemaNode):
        self.inner = inner

    def spec(self):
        return f"opt<{self.inner.spec()}>"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = f"opt<{self.inner.spec()}>"


class ListOf(SchemaNode):
    def __init__(self, kind: str):
        self.kind = kind

    def spec(self):
        return f"list<{self.kind}>"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = f"list<{self.kind}>"


class MapOf(SchemaNode):
    def __init__(self, kind: str):
        self.kind = kind

    def spec(self):
        return f"map<{self.kind}>"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = f"map<{self.kind}>"


class Carry(SchemaNode):
    """A dict of named device arrays (the jitted step's carry).  Leaves
    resolve LIVE — name set and dtypes come from the actual payload, so
    a telemetry-plane toggle or a dtype change shows up as a field diff
    (SC001), while shapes are covered by the dim table instead."""

    def spec(self):
        return "carry{...}"

    def resolve(self, payload, path, out, findings, decl_name):
        if payload is _ABSENT or payload is None:
            out[path] = "carry{...}"       # static mode / missing slot
            return
        if not isinstance(payload, dict):
            out[path] = f"carry!{type(payload).__name__}"
            return
        for k in sorted(payload):
            a = np.asarray(payload[k])
            out[f"{path}.{k}"] = f"ndarray<{a.dtype},ndim={a.ndim}>"


class CarryTuple(SchemaNode):
    """A NamedTuple carry persisted as a positional list of arrays —
    leaves resolve live by index (plane count + dtype diffs)."""

    def spec(self):
        return "carry[...]"

    def resolve(self, payload, path, out, findings, decl_name):
        if payload is _ABSENT or payload is None:
            out[path] = "carry[...]"
            return
        if not isinstance(payload, (list, tuple)):
            out[path] = f"carry!{type(payload).__name__}"
            return
        for i, v in enumerate(payload):
            a = np.asarray(v)
            out[f"{path}.{i}"] = f"ndarray<{a.dtype},ndim={a.ndim}>"


class Struct(SchemaNode):
    """A dict payload with a fixed field set."""

    def __init__(self, **fields: SchemaNode):
        self.fields = dict(sorted(fields.items()))

    def spec(self):
        inner = ",".join(f"{k}:{v.spec()}" for k, v in self.fields.items())
        return f"{{{inner}}}"

    def resolve(self, payload, path, out, findings, decl_name):
        pay = payload if isinstance(payload, dict) else None
        for k, sub in self.fields.items():
            p = f"{path}.{k}" if path else k
            v = _ABSENT if pay is None else pay.get(k, _ABSENT)
            sub.resolve(v, p, out, findings, decl_name)
        if pay is not None:
            for k in pay:
                if k not in self.fields:
                    p = f"{path}.{k}" if path else k
                    out[p] = "undeclared"
                    findings.append((
                        "SC002",
                        f"payload key '{k}' is not described by schema "
                        f"'{decl_name}' — the declaration is stale"))


class Sub(SchemaNode):
    """Delegate the whole description to a decorated sub-object (e.g.
    NamedWindow persists exactly its wrapped window processor's state)."""

    def __init__(self, attr: str):
        self.attr = attr

    def spec(self):
        return f"sub<{self.attr}>"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = f"sub<{self.attr}>"


class Keyed(SchemaNode):
    """A keyed device runtime's payload: either one flat
    ``{field: engine_state, key_lanes}`` slab or a per-shard list
    ``{"shards": [{field, key_lanes}, ...]}`` keyed by the pinned FNV-1a
    routing.  The shard count becomes the ``shards`` dim (kind
    ``shards`` → SC005 on mismatch) and the engine's own description
    nests under ``sub``."""

    def __init__(self, field: str):
        self.field = field

    def spec(self):
        return f"keyed<{self.field}>"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = f"keyed<{self.field}>"


class PartitionState(SchemaNode):
    """PartitionRuntime payload: device mode persists per-query element
    states (each described by its own schema, nested under ``sub`` keyed
    ``qname/eid``); host mode persists a dynamic per-key instance map."""

    def spec(self):
        return "partition"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = "partition"


class Any_(SchemaNode):
    """Escape hatch: structure intentionally undeclared; the SC003
    portable-payload scan still applies."""

    def spec(self):
        return "any"

    def resolve(self, payload, path, out, findings, decl_name):
        out[path] = "any"


# ============================================================== declarations

class SchemaDecl:
    """One class's declared persistent-state schema."""

    def __init__(self, name: str, version: int, schema: Optional[SchemaNode],
                 dims: Dict[str, str], doc: str = ""):
        self.name = name
        self.version = version
        self.schema = schema
        self.dims = dict(sorted((dims or {}).items()))
        self.doc = doc

    def digest(self) -> str:
        """Stable fingerprint of the declared layout (name + node spec +
        dim kinds).  Version is deliberately excluded: SC010 is exactly
        'digest moved while version did not'."""
        spec = "-" if self.schema is None else self.schema.spec()
        dims = ",".join(f"{k}:{v}" for k, v in self.dims.items())
        raw = f"{self.name}|{spec}|{dims}"
        return hashlib.sha256(raw.encode()).hexdigest()[:12]

    def as_dict(self) -> dict:
        return {"name": self.name, "version": self.version,
                "digest": self.digest(),
                "spec": "-" if self.schema is None else self.schema.spec(),
                "dims": dict(self.dims)}

    def __repr__(self):
        return (f"SchemaDecl({self.name!r}, v{self.version}, "
                f"{self.digest()})")


#: dotted class name -> SchemaDecl, filled at import time by decorators.
_REGISTRY: Dict[str, SchemaDecl] = {}


def persistent_schema(name: str, *, version: int = 1,
                      schema: Optional[SchemaNode],
                      dims: Optional[Dict[str, str]] = None,
                      doc: str = "") -> Callable[[C], C]:
    """Class decorator declaring what the class's ``current_state()``
    persists.  ``schema=None`` declares the class stateless (its
    current_state returns None).  Zero runtime cost — registers the
    declaration and hands the class back untouched; the static scan in
    analysis/state_schema.py re-derives the exact same declaration from
    the AST without importing the decorated (jax-laden) module."""
    decl = SchemaDecl(name, version, schema, dims, doc)

    def mark(cls: C) -> C:
        cls.__state_schema__ = decl
        _REGISTRY[f"{cls.__module__}.{cls.__qualname__}"] = decl
        return cls
    return mark


def registry() -> Dict[str, SchemaDecl]:
    return dict(_REGISTRY)


def decl_of(cls: type) -> Tuple[Optional[SchemaDecl], Optional[type]]:
    """The SchemaDecl governing ``cls``'s persistent state: the one
    declared ON the class that *defines* current_state in the MRO.  A
    subclass overriding current_state without its own declaration is
    undeclared (SC002) even if a base is decorated — the override may
    persist a different payload."""
    for c in cls.__mro__:
        if "current_state" in c.__dict__:
            return c.__dict__.get("__state_schema__"), c
    return None, None


# ======================================================= portable-payload scan

#: leaf types a snapshot payload may contain and remain restorable by any
#: build of the engine (SC003 otherwise): plain data, no live objects.
_PORTABLE_LEAVES = (np.ndarray, np.generic, int, float, complex, str,
                    bool, bytes, bytearray, type(None))

_SCAN_CAP = 20000     # bounded walk: snapshots can be large


def portable_scan(payload: Any, path: str = "") -> List[Tuple[str, str]]:
    """Walk a payload and flag values that would raw-pickle a class
    instance (restorable only by the exact same build — SC003)."""
    findings: List[Tuple[str, str]] = []
    budget = [_SCAN_CAP]

    def walk(v, p):
        if budget[0] <= 0:
            return
        budget[0] -= 1
        if isinstance(v, _PORTABLE_LEAVES):
            return
        if isinstance(v, dict):
            for k, x in v.items():
                walk(x, f"{p}.{k}" if p else str(k))
            return
        if isinstance(v, (list, tuple, set, frozenset)):
            for i, x in enumerate(v):
                walk(x, f"{p}[{i}]")
            return
        t = type(v)
        findings.append((
            "SC003",
            f"field '{p or '<root>'}' holds a raw {t.__module__}."
            f"{t.__qualname__} instance — non-portable pickle payload "
            f"(only plain data and ndarrays survive engine rebuilds)"))
    walk(payload, path)
    return findings


# ============================================================== descriptions

def _live_dims(el) -> Dict[str, Any]:
    fn = getattr(el, "schema_dims", None)
    if fn is None:
        return {}
    try:
        return {k: v for k, v in fn().items()}
    except Exception:     # noqa: BLE001 — a dim probe must never
        return {}         # take down persist/describe


def describe_element(el, payload=_ABSENT) -> Optional[dict]:
    """Describe one element's persistent state: the declaration resolved
    against a live payload (persist/restore time) or statically
    (``payload`` omitted — the cheap creation-time report path).
    Returns None for declared-stateless elements."""
    decl, owner = decl_of(type(el))
    cls = type(el)
    if decl is None:
        return {"name": f"{cls.__module__}.{cls.__qualname__}",
                "version": 0, "digest": "", "dims": {}, "dimkinds": {},
                "fields": {}, "sub": None,
                "findings": [(
                    "SC002",
                    f"{cls.__module__}.{cls.__qualname__} defines "
                    f"current_state but declares no persistent schema")]}
    if decl.schema is None:
        return None
    node = decl.schema
    if isinstance(node, Sub):
        target = getattr(el, node.attr, None)
        if target is None:
            return None
        return describe_element(target, payload)
    findings: List[Tuple[str, str]] = []
    sub = None
    fields: Dict[str, str] = {}
    dims = _live_dims(el)
    if isinstance(node, Keyed):
        sub, nshards = _describe_keyed(el, node, payload)
        dims["shards"] = nshards
        fields["key_lanes"] = "map<key,lane>"
        dimkinds = dict(decl.dims)
        dimkinds["shards"] = "shards"
    elif isinstance(node, PartitionState):
        sub = _describe_partition(el, payload)
        dimkinds = dict(decl.dims)
        if sub is None:
            fields["keys"] = "map<key,query-state>"
    else:
        node.resolve(None if payload is _ABSENT else payload, "",
                     fields, findings, decl.name)
        dimkinds = dict(decl.dims)
    if payload is not _ABSENT and payload is not None:
        findings.extend(portable_scan(payload))
    return {"name": decl.name, "version": decl.version,
            "digest": decl.digest(), "dims": dims, "dimkinds": dimkinds,
            "fields": fields, "sub": sub, "findings": findings}


def _describe_keyed(el, node: Keyed, payload):
    """(engine sub-description, shard count) for a keyed runtime."""
    live_shards = getattr(el, "shards", None)
    if payload is _ABSENT:                 # static mode: live topology
        if live_shards:
            return (describe_element(live_shards[0].engine),
                    len(live_shards))
        engine = getattr(el, node.field, None)
        return (None if engine is None else describe_element(engine), 0)
    if not isinstance(payload, dict):
        return None, 0
    snap_shards = payload.get("shards")
    if snap_shards is not None:
        engine = (live_shards[0].engine if live_shards
                  else getattr(el, node.field, None))
        sub = None
        if engine is not None and snap_shards:
            sub = describe_element(engine, snap_shards[0].get(node.field))
        return sub, len(snap_shards)
    engine = getattr(el, node.field, None)
    if engine is None:
        return None, 0
    return describe_element(engine, payload.get(node.field)), 0


def _describe_partition(el, payload):
    """Device-mode partitions nest one description per ``qname/eid``;
    host mode returns None (dynamic per-key instances, fields only)."""
    device = (getattr(el, "device_mode", False) if payload is _ABSENT
              else isinstance(payload, dict) and "device" in payload)
    if not device:
        return None
    sub: Dict[str, dict] = {}
    for qname, qr in getattr(el, "device_query_runtimes", {}).items():
        section = (_ABSENT if payload is _ABSENT
                   else (payload.get("device", {}) or {}).get(qname, {}))
        for eid, obj in qr.stateful_elements():
            slice_ = (section if section is _ABSENT
                      else section.get(eid, _ABSENT))
            d = describe_element(obj, slice_)
            if d is not None:
                sub[f"{qname}/{eid}"] = d
    return sub


# ============================================================== verification

def _on_ladder(a, b) -> bool:
    """True when a and b differ by an integer power-of-two factor (the
    grow ladder doubles capacity; any legitimate pair of snapshots of
    the same app sits a 2^n ratio apart)."""
    try:
        a, b = int(a), int(b)
    except (TypeError, ValueError):
        return a == b
    if a <= 0 or b <= 0:
        return a == b
    lo, hi = min(a, b), max(a, b)
    if hi % lo:
        return False
    r = hi // lo
    return (r & (r - 1)) == 0


def shard_mismatch_message(have: int, want: int,
                           digest: Optional[str] = None) -> str:
    """Shared SC005 text: the planner's restore guard and the envelope
    verifier must tell the same story (expected-vs-found counts + the
    pinned routing digest the key→shard assignment hangs off)."""
    if digest is None:
        try:
            from ..parallel.shards import routing_digest
            digest = routing_digest()
        except Exception:     # noqa: BLE001 — message helper
            digest = "?"
    return (f"sharded snapshot carries {want} shard slab(s) but the "
            f"runtime has {have} — key→shard routing is modular in the "
            f"shard count (FNV-1a routing digest {digest}); restore "
            f"requires the same SIDDHI_TPU_SHARDS the snapshot was "
            f"taken with")


def compare_descriptions(eid: str, snap: Optional[dict],
                         live: Optional[dict],
                         findings: List[Tuple[str, str]]) -> None:
    """Field-level diff of one element's snapshot vs live description."""
    if snap is None or live is None:
        return
    for f in snap.get("findings", []) or []:
        if f[0] == "SC003":
            findings.append((f[0], f"{eid}: {f[1]}"))
    if snap.get("name") != live.get("name"):
        findings.append((
            "SC001", f"{eid}: snapshot persists schema "
            f"'{snap.get('name')}' but the live element declares "
            f"'{live.get('name')}' — the element was planned onto a "
            f"different engine path"))
        return
    if snap.get("version") != live.get("version"):
        findings.append((
            "SC001", f"{eid}: schema '{snap.get('name')}' version "
            f"{snap.get('version')} (snapshot) vs {live.get('version')} "
            f"(live) — declared evolution requires migration, not a "
            f"raw restore"))
        return
    if snap.get("digest") != live.get("digest"):
        findings.append((
            "SC010", f"{eid}: schema '{snap.get('name')}' "
            f"v{snap.get('version')} layout digest {snap.get('digest')} "
            f"(snapshot) vs {live.get('digest')} (live) — the layout "
            f"changed without a version bump"))
    kinds = dict(snap.get("dimkinds", {}) or {})
    kinds.update(live.get("dimkinds", {}) or {})
    sd = snap.get("dims", {}) or {}
    ld = live.get("dims", {}) or {}
    for d in sorted(set(sd) | set(ld)):
        kind = kinds.get(d, "exact")
        a, b = sd.get(d), ld.get(d)
        if a is None or b is None or kind == "free":
            continue
        if kind == "exact":
            if a != b:
                findings.append((
                    "SC001", f"{eid}: dim {d}={a} (snapshot) vs "
                    f"{d}={b} (live) — fixed by the plan, restore "
                    f"requires equality"))
        elif kind == "ladder":
            if not _on_ladder(a, b):
                findings.append((
                    "SC004", f"{eid}: elastic dim {d}={a} (snapshot) "
                    f"vs {d}={b} (live) is off the grow ladder — "
                    f"capacities grow by doubling, so compatible "
                    f"values differ by a power-of-two factor"))
        elif kind == "shards":
            if a != b:
                findings.append(("SC005",
                                 f"{eid}: " +
                                 shard_mismatch_message(b, a)))
    sf = snap.get("fields", {}) or {}
    lf = live.get("fields", {}) or {}
    if sf and lf:
        for p in sorted(set(sf) | set(lf)):
            x, y = sf.get(p), lf.get(p)
            if x is None:
                findings.append((
                    "SC001", f"{eid}: live field '{p}' ({y}) has no "
                    f"counterpart in the snapshot"))
            elif y is None:
                findings.append((
                    "SC001", f"{eid}: snapshot field '{p}' ({x}) has "
                    f"no counterpart in the live schema"))
            elif x != y:
                findings.append((
                    "SC001", f"{eid}: field '{p}' is {x} in the "
                    f"snapshot but {y} live"))
    ss, ls = snap.get("sub"), live.get("sub")
    if ss is None and ls is None:
        return
    if ss is None or ls is None:
        findings.append((
            "SC001", f"{eid}: nested schema present on only one side "
            f"(snapshot {'has' if ss is not None else 'lacks'} it) — "
            f"device/host or sharded/flat layout changed"))
        return
    if "name" in ss and "name" in ls:       # Keyed engine description
        compare_descriptions(f"{eid}/engine", ss, ls, findings)
        return
    for k in sorted(set(ss) | set(ls)):     # partition sub-element map
        a, b = ss.get(k), ls.get(k)
        if a is None:
            findings.append((
                "SC001", f"{eid}/{k}: live partition element has no "
                f"section in the snapshot"))
        elif b is None:
            findings.append((
                "SC001", f"{eid}/{k}: snapshot carries a partition "
                f"section for an element missing from this runtime"))
        else:
            compare_descriptions(f"{eid}/{k}", a, b, findings)


def verify_compat(snap_descs: Dict[str, dict], live_descs: Dict[str, dict],
                  *, incremental: bool = False,
                  snap_routing: Optional[str] = None,
                  live_routing: Optional[str] = None
                  ) -> List[Tuple[str, str]]:
    """All SC0xx findings blocking a restore of ``snap_descs`` into a
    runtime described by ``live_descs``.  Incremental snapshots only
    carry changed elements, so presence is checked one-way for them."""
    findings: List[Tuple[str, str]] = []
    snap_descs = snap_descs or {}
    live_descs = live_descs or {}
    if snap_routing and live_routing and snap_routing != live_routing:
        findings.append((
            "SC005", f"routing digest drift: snapshot taken under "
            f"FNV-1a routing {snap_routing} but this runtime routes "
            f"with {live_routing} — every per-shard section would land "
            f"on the wrong shard"))
    for eid in sorted(snap_descs):
        if eid not in live_descs:
            findings.append((
                "SC001", f"{eid}: snapshot carries persistent state "
                f"for an element that does not exist in this runtime"))
            continue
        compare_descriptions(eid, snap_descs[eid], live_descs[eid],
                             findings)
    if not incremental:
        for eid in sorted(live_descs):
            if eid not in snap_descs:
                findings.append((
                    "SC001", f"{eid}: live element persists state but "
                    f"the snapshot has no section for it"))
    return findings


# ============================================================== envelope v2

def build_envelope(state: Dict[str, Any], descs: Dict[str, dict],
                   routing: Optional[str], *,
                   incremental: bool = False,
                   prev: Optional[str] = None) -> dict:
    env: Dict[str, Any] = {"v": SCHEMA_ENVELOPE_VERSION,
                           "schema": descs, "routing": routing,
                           "state": state}
    if incremental:
        env["__incremental__"] = True
        env["prev"] = prev
    return env


def parse_envelope(obj) -> Tuple[Dict[str, Any], Optional[dict],
                                 Optional[str], bool, Optional[str]]:
    """(state, schema descs | None, routing, incremental, prev) from a
    loaded snapshot — legacy pre-schema pickles pass through with
    ``descs=None`` (nothing to verify against)."""
    if isinstance(obj, dict) and obj.get("v") == SCHEMA_ENVELOPE_VERSION:
        return (obj.get("state", {}), obj.get("schema") or {},
                obj.get("routing"), bool(obj.get("__incremental__")),
                obj.get("prev"))
    if isinstance(obj, dict) and obj.get("__incremental__"):
        return obj.get("state", {}), None, None, True, None
    return obj if isinstance(obj, dict) else {}, None, None, False, None
