"""Named-thread registry for the engine's host rim.

Every thread (or timer) the engine starts carries a ``siddhi-`` prefixed
name minted through :func:`engine_thread_name`, so a leaked thread in a
test teardown — or a stack dump from a wedged production process — is
attributable to the component that started it without guessing from the
target function.  The registry below is the single source of truth; the
concurrency auditor (analysis/engine/lockgraph.py, CE008) statically
rejects ``threading.Thread``/``Timer`` construction sites that do not
name their thread, and the tier-1 thread-leak sentinel
(tests/conftest.py) uses :func:`engine_threads` to report leftovers per
test file.
"""
from __future__ import annotations

import threading
from typing import Dict, List

#: prefix -> owning component + its lifecycle contract (who joins it).
#: Adding a thread to the engine means adding its prefix here first —
#: tests/test_engine_lint.py asserts every live siddhi- thread matches.
ENGINE_THREAD_PREFIXES: Dict[str, str] = {
    "siddhi-junction-": "core/stream.py StreamJunction @Async workers; "
                        "stop() drain-joins them (bounded by "
                        "drain.timeout.ms)",
    "siddhi-retry-": "core/resilience.py SinkRetryWorker; stop() "
                     "interrupts backoff and joins (bounded)",
    "siddhi-stats-reporter": "core/statistics.py periodic reporter; "
                             "stop_reporting() joins (bounded 5s)",
    "siddhi-rest": "service/rest.py HTTP server; stop() shuts the "
                   "server down",
    "siddhi-sched-timer": "core/scheduler.py one-shot re-armed Timer; "
                          "shutdown() cancels",
    "siddhi-heartbeat": "core/timestamp.py playback idle-time Timer; "
                        "shutdown() cancels and disarms re-arming",
    "siddhi-prewarm": "plan/shapes.py AOT shape-ladder worker; transient "
                      "(exits when the ladder queue drains), "
                      "prewarm_join() waits for idle + thread exit",
}


def engine_thread_name(prefix: str, *parts: object) -> str:
    """Mint a thread name under a registered prefix.  Unregistered
    prefixes raise immediately — the registry must stay exhaustive for
    leak attribution to work."""
    if prefix not in ENGINE_THREAD_PREFIXES:
        raise ValueError(
            f"thread prefix {prefix!r} is not in ENGINE_THREAD_PREFIXES; "
            f"register it in core/threads.py so leaks stay attributable")
    if not parts:
        return prefix.rstrip("-") if prefix.endswith("-") else prefix
    return prefix + "-".join(str(p) for p in parts) if prefix.endswith("-") \
        else prefix + "-" + "-".join(str(p) for p in parts)


def engine_threads(include_daemon: bool = True) -> List[threading.Thread]:
    """Live engine threads (name starts with ``siddhi-``)."""
    return [t for t in threading.enumerate()
            if t.name.startswith("siddhi-")
            and (include_daemon or not t.daemon)]


def attribute(thread_name: str) -> str:
    """Owning-component line for a thread name, or 'unregistered'."""
    for prefix, owner in ENGINE_THREAD_PREFIXES.items():
        if thread_name == prefix or thread_name.startswith(prefix):
            return owner
    return "unregistered (not in ENGINE_THREAD_PREFIXES)"
