"""Timer-event scheduler.

(reference: util/Scheduler.java — `notifyAt(t)` queue backed by a
ScheduledExecutorService that injects TIMER StreamEvents into processor chains;
playback-aware so virtual time drives expiry deterministically.)

Each stateful processor that needs time-based wakeups (time windows, absent
patterns, cron triggers, output rate timers) registers a target callable; the
scheduler calls `target.on_timer(ts)` when wall clock (or playback virtual
time) passes the requested instant.
"""
from __future__ import annotations

import heapq
import threading
from typing import Callable, List, Optional, Tuple

from .lockwitness import maybe_wrap
from .threads import engine_thread_name
from .timestamp import TimestampGenerator


class Scheduler:
    #: Optional core/overload.py DispatchWatchdog.  When set, every fire
    #: is consulted (`allow`) so a runaway re-arm loop trips instead of
    #: spinning forever, and registrations for disarmed targets are
    #: dropped at the door.
    watchdog = None

    def __init__(self, ts_gen: TimestampGenerator):
        self._ts_gen = ts_gen
        self._heap: List[Tuple[int, int, Callable[[int], None]]] = []
        self._seq = 0
        self._lock = maybe_wrap(
            threading.RLock(), "core.scheduler.Scheduler._lock")
        self._timer: Optional[threading.Timer] = None
        self._stopped = False
        #: cumulative fired-target count (flight-recorder block records)
        self.fires = 0
        if ts_gen.in_playback:
            ts_gen.add_time_change_listener(self._on_virtual_time)

    def notify_at(self, ts: int, target: Callable[[int], None]):
        wd = self.watchdog
        if wd is not None and wd.is_disarmed(target):
            return
        with self._lock:
            heapq.heappush(self._heap, (int(ts), self._seq, target))
            self._seq += 1
            if not self._ts_gen.in_playback:
                self._arm()

    # ------------------------------------------------------------ real time

    def _arm(self):
        if self._stopped or not self._heap:
            return
        next_ts = self._heap[0][0]
        delay = max(0.0, (next_ts - self._ts_gen.current_time()) / 1000.0)
        if self._timer is not None:
            self._timer.cancel()
        self._timer = threading.Timer(delay, self._fire)
        self._timer.daemon = True
        self._timer.name = engine_thread_name("siddhi-sched-timer")
        self._timer.start()

    def _fire(self):
        now = self._ts_gen.current_time()
        due = []
        with self._lock:
            while self._heap and self._heap[0][0] <= now:
                due.append(heapq.heappop(self._heap))
        wd = self.watchdog
        for _ts, _, target in due:
            if wd is not None and not wd.allow(target, now):
                continue
            self.fires += 1
            try:
                target(now)
            except Exception:  # noqa: BLE001 — scheduler thread must survive
                import logging
                logging.getLogger(__name__).exception("timer target failed")
        with self._lock:
            self._arm()

    # ------------------------------------------------------------ playback

    def _on_virtual_time(self, now: int):
        self.advance_to(now)

    def advance_to(self, now: int):
        """Fire all timers due at or before `now` (playback / test use)."""
        while True:
            due = []
            with self._lock:
                while self._heap and self._heap[0][0] <= now:
                    due.append(heapq.heappop(self._heap))
            if not due:
                return
            wd = self.watchdog
            for ts, _, target in due:
                if wd is not None and not wd.allow(target, ts):
                    continue
                self.fires += 1
                target(ts)

    def shutdown(self):
        self._stopped = True
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._heap.clear()
