"""Output callbacks: route selector results to streams/tables/callbacks.

(reference: query/output/callback/*.java — InsertIntoStreamCallback,
InsertIntoTableCallback, DeleteTableCallback, UpdateTableCallback,
UpdateOrInsertTableCallback + QueryCallback split of current/expired.)
"""
from __future__ import annotations

from typing import List


from ..query_api.query import OutputEventsFor
from .event import CURRENT, EXPIRED, EventChunk
from .processor import Processor


class OutputCallbackProcessor(Processor):
    """Terminal processor adapting the selector's output chunk to the query's
    output action + any registered QueryCallbacks."""

    def __init__(self, events_for: OutputEventsFor):
        super().__init__()
        self.events_for = events_for
        self.query_callbacks: List = []
        self.query_name = ""          # set by QueryRuntime (debugger OUT)
        self.app_ctx = None

    def _filter_for_action(self, chunk: EventChunk) -> EventChunk:
        if self.events_for == OutputEventsFor.CURRENT:
            return chunk.only(CURRENT)
        if self.events_for == OutputEventsFor.EXPIRED:
            return chunk.only(EXPIRED)
        return chunk.only(CURRENT, EXPIRED)

    def notify_callbacks(self, chunk: EventChunk):
        for cb in self.query_callbacks:
            cb.receive_chunk(chunk)

    def process(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        dbg = getattr(self.app_ctx, "debugger", None) if self.app_ctx \
            else None
        if dbg is not None:
            dbg.check(self.query_name, dbg.OUT, chunk)
        self.notify_callbacks(chunk)
        self.emit(self._filter_for_action(chunk))

    def emit(self, chunk: EventChunk):
        raise NotImplementedError


class ReturnCallback(OutputCallbackProcessor):
    """Query with no insert target — callbacks only."""

    def emit(self, chunk: EventChunk):
        pass


class InsertIntoStreamCallback(OutputCallbackProcessor):
    """Re-publishes into a stream junction; expired events are converted to
    CURRENT on insertion (reference InsertIntoStreamCallback.java:59-71)."""

    def __init__(self, junction, target_definition, events_for):
        super().__init__(events_for)
        self.junction = junction
        self.target_definition = target_definition

    def emit(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        out = chunk.rename(self.target_definition.attribute_names) \
            if chunk.names != self.target_definition.attribute_names else chunk
        out = out.with_types(CURRENT)
        self.junction.send(out)


class InsertIntoTableCallback(OutputCallbackProcessor):
    def __init__(self, table, events_for):
        super().__init__(events_for)
        self.table = table

    def emit(self, chunk: EventChunk):
        if not chunk.is_empty:
            self.table.insert(chunk)


class DeleteTableCallback(OutputCallbackProcessor):
    def __init__(self, table, compiled_condition, events_for):
        super().__init__(events_for)
        self.table = table
        self.compiled_condition = compiled_condition

    def emit(self, chunk: EventChunk):
        if not chunk.is_empty:
            self.table.delete(chunk, self.compiled_condition)


class UpdateTableCallback(OutputCallbackProcessor):
    def __init__(self, table, compiled_condition, compiled_set, events_for):
        super().__init__(events_for)
        self.table = table
        self.compiled_condition = compiled_condition
        self.compiled_set = compiled_set

    def emit(self, chunk: EventChunk):
        if not chunk.is_empty:
            self.table.update(chunk, self.compiled_condition, self.compiled_set)


class UpdateOrInsertTableCallback(UpdateTableCallback):
    def emit(self, chunk: EventChunk):
        if not chunk.is_empty:
            self.table.update_or_insert(chunk, self.compiled_condition,
                                        self.compiled_set)


class InsertIntoWindowCallback(OutputCallbackProcessor):
    """Insert into a named window (reference InsertIntoWindowCallback.java)."""

    def __init__(self, window, events_for):
        super().__init__(events_for)
        self.window = window

    def emit(self, chunk: EventChunk):
        if not chunk.is_empty:
            self.window.add(chunk.with_types(CURRENT))
