"""Partition runtime: per-key isolated query instances.

Host-oracle mirror of the reference (partition/PartitionRuntime.java:255-308 —
on the first event with a new key every query runtime + inner junction is
cloned for that key; partition/PartitionStreamReceiver.java:83-153 — per-event
key evaluation and routing to `<streamId>+key` local junctions; @purge idle-key
cleanup).  The TPU path replaces per-key clones with a partition-axis in the
state tensors (parallel/, SURVEY.md §2.8) — this runtime is the semantic spec
for it.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx, Scope
from ..query_api import (Partition, Query, RangePartitionType,
                         ValuePartitionType, find_annotation)
from ..query_api.definition import StreamDefinition
from ..utils.errors import DefinitionNotExistError, SiddhiAppCreationError
from .event import EventChunk
from .query_runtime import QueryRuntime
from .stateschema import PartitionState, persistent_schema
from .stream import StreamJunction


class _PartitionInstance:
    """One key's isolated clone group: local junctions + query runtimes.

    Presents the SiddhiAppRuntime surface QueryRuntime builds against,
    delegating everything non-local to the parent app runtime."""

    def __init__(self, pr: "PartitionRuntime", key: str,
                 template: bool = False):
        self.pr = pr
        self.parent = pr.app_runtime
        self.key = key
        self.local_junctions: Dict[str, StreamJunction] = {}
        self.local_definitions: Dict[str, StreamDefinition] = {}
        self.query_runtimes: Dict[str, QueryRuntime] = {}
        self.last_used = self.app_ctx.timestamp_generator.current_time()
        # local entry junction for each partitioned/broadcast input stream
        for sid in pr.partitioned_streams:
            d = self.parent.definition_of(sid)
            self.local_definitions[sid] = d
            self.local_junctions[sid] = StreamJunction(d, self.app_ctx)
        for i, q in enumerate(pr.partition.queries):
            name = q.name or f"{pr.name}_query_{i}"
            qr = QueryRuntime(q, self, name, partition_key=key)
            self.query_runtimes[name] = qr
            if not template:
                for cb in pr.pending_callbacks.get(name, []):
                    qr.add_callback(cb)
        if template:
            return  # built only to materialise output stream definitions
        for j in self.local_junctions.values():
            j.start()
        for qr in self.query_runtimes.values():
            qr.start()

    # ---- SiddhiAppRuntime surface used by QueryRuntime ----

    @property
    def app_ctx(self):
        return self.parent.app_ctx

    @property
    def extension_registry(self):
        return self.parent.extension_registry

    @property
    def aggregations(self):
        return self.parent.aggregations

    @property
    def tables(self):
        return self.parent.tables

    def latency_tracker_for(self, query_name):
        return self.parent.latency_tracker_for(query_name)

    def has_table(self, tid):
        return self.parent.has_table(tid)

    def table_of(self, tid):
        return self.parent.table_of(tid)

    def has_named_window(self, wid):
        return self.parent.has_named_window(wid)

    def named_window_of(self, wid):
        return self.parent.named_window_of(wid)

    def definition_of(self, stream_id: str, is_inner=False, is_fault=False):
        if is_inner or stream_id in self.local_definitions:
            d = self.local_definitions.get(stream_id)
            if d is None:
                raise DefinitionNotExistError(
                    f"No inner stream '#{stream_id}' in partition")
            return d
        return self.parent.definition_of(stream_id, is_inner, is_fault)

    def junction_of(self, stream_id: str, is_inner=False, is_fault=False,
                    partition_key=None, create_with=None) -> StreamJunction:
        if is_inner:
            j = self.local_junctions.get("#" + stream_id)
            if j is None:
                if create_with is None:
                    raise DefinitionNotExistError(
                        f"No inner stream '#{stream_id}' in partition")
                d = StreamDefinition(stream_id, list(create_with.attributes))
                self.local_definitions["#" + stream_id] = d
                self.local_definitions[stream_id] = d
                j = StreamJunction(d, self.app_ctx)
                j.start()
                self.local_junctions["#" + stream_id] = j
            return j
        if stream_id in self.local_junctions:
            return self.local_junctions[stream_id]
        return self.parent.junction_of(stream_id, is_inner, is_fault,
                                       partition_key, create_with)

    # ---- routing ----

    def send(self, stream_id: str, chunk: EventChunk):
        self.last_used = self.app_ctx.timestamp_generator.current_time()
        self.local_junctions[stream_id].send(chunk)

    def shutdown(self):
        for j in self.local_junctions.values():
            j.stop()


class _PartitionExecutor:
    """Per-event key evaluation (ValuePartitionExecutor /
    RangePartitionExecutor in the reference)."""

    def __init__(self, pt, definition, factory):
        scope = Scope()
        scope.add_primary(pt.stream_id, None, definition)
        compiler = factory(scope)
        self.pt = pt
        self.ranges: Optional[List] = None
        if isinstance(pt, ValuePartitionType):
            self.value_expr: Optional[CompiledExpr] = \
                compiler.compile(pt.expression)
        elif isinstance(pt, RangePartitionType):
            self.value_expr = None
            self.ranges = [(r.partition_key, compiler.compile(r.condition))
                           for r in pt.ranges]
        else:
            raise SiddhiAppCreationError(f"Unknown partition type {pt!r}")

    def keys(self, chunk: EventChunk) -> List[Optional[str]]:
        n = len(chunk)
        ctx = EvalCtx(chunk.columns, chunk.timestamps, n)
        if self.value_expr is not None:
            v = self.value_expr.fn(ctx)
            arr = np.asarray(v)
            if arr.ndim == 0:
                arr = np.broadcast_to(arr, (n,))
            return [None if x is None else str(x) for x in
                    (x.item() if isinstance(x, np.generic) else x
                     for x in arr)]
        out: List[Optional[str]] = [None] * n
        for key, cond in self.ranges:
            m = np.asarray(cond.fn(ctx), bool)
            if m.ndim == 0:
                m = np.broadcast_to(m, (n,))
            for i in range(n):
                if out[i] is None and m[i]:
                    out[i] = key
        return out


class _PartitionStreamReceiver:
    def __init__(self, pr: "PartitionRuntime", stream_id: str,
                 executor: Optional[_PartitionExecutor]):
        self.pr = pr
        self.stream_id = stream_id
        self.executor = executor

    def receive_chunk(self, chunk: EventChunk):
        pr = self.pr
        with pr.lock:
            if self.executor is None:
                # non-partitioned stream used inside the partition:
                # broadcast to every live key instance (reference
                # PartitionStreamReceiver with no executors)
                for inst in list(pr.instances.values()):
                    inst.send(self.stream_id, chunk)
                return
            keys = self.executor.keys(chunk)
            # group contiguous same-key runs to keep event order per key
            order: List[str] = []
            groups: Dict[str, List[int]] = {}
            for i, k in enumerate(keys):
                if k is None:
                    continue  # no matching range → dropped
                if k not in groups:
                    groups[k] = []
                    order.append(k)
                groups[k].append(i)
            for k in order:
                inst = pr.instance_of(k)
                inst.send(self.stream_id, chunk.take(np.asarray(groups[k])))


class _CallbackProxy:
    def __init__(self, pr: "PartitionRuntime", query_name: str):
        self.pr = pr
        self.query_name = query_name

    def add_callback(self, cb):
        self.pr.pending_callbacks.setdefault(self.query_name, []).append(cb)
        for inst in self.pr.instances.values():
            qr = inst.query_runtimes.get(self.query_name)
            if qr is not None:
                qr.add_callback(cb)


@persistent_schema("partition", schema=PartitionState())
class PartitionRuntime:
    def __init__(self, partition: Partition, app_runtime, name: str):
        self.partition = partition
        self.app_runtime = app_runtime
        self.name = name
        self.lock = threading.RLock()
        self.instances: Dict[str, _PartitionInstance] = {}
        self.pending_callbacks: Dict[str, List] = {}

        from ..plan.expr_compiler import ExprCompiler

        def factory(scope):
            return ExprCompiler(scope, np,
                                app_runtime.app_ctx.script_functions,
                                app_runtime.extension_registry)

        self.executors: Dict[str, _PartitionExecutor] = {}
        for pt in partition.partition_types:
            d = app_runtime.definition_of(pt.stream_id)
            self.executors[pt.stream_id] = _PartitionExecutor(pt, d, factory)

        # streams consumed by partition queries
        self.partitioned_streams: List[str] = []
        used: List[str] = []
        for q in partition.queries:
            used.extend(self._input_stream_ids(q))
        for sid in dict.fromkeys(used):
            if sid.startswith("#"):
                continue
            self.partitioned_streams.append(sid)

        # device mode: partition keys become lanes of one NFA state slab
        # instead of per-key runtime clones (the TPU replacement for
        # PartitionRuntime.java:255-308's cloneIfNotExist)
        self.device_mode = False
        self.device_query_runtimes: Dict[str, QueryRuntime] = {}
        self.fallback_reason: Optional[str] = None
        if self._try_device_mode():
            return
        # parse queries once so global output streams exist before any key
        # arrives (reference: QueryParser runs per partition query at build
        # time, creating inferred output definitions)
        _PartitionInstance(self, "__template__", template=True)
        # subscribe receivers on the global junctions
        for sid in self.partitioned_streams:
            recv = _PartitionStreamReceiver(self, sid,
                                            self.executors.get(sid))
            app_runtime.junction_of(sid).subscribe(recv)
        # @purge(enable='true', interval='..', idle.period='..')
        purge = find_annotation(partition.annotations, "purge")
        if purge is not None and \
                str(purge.get("enable", "true")).lower() == "true":
            from .runtime import _parse_time_str
            self.purge_idle_ms = _parse_time_str(
                purge.get("idle.period", "5 min"))
            self.purge_interval_ms = _parse_time_str(
                purge.get("interval", "1 min"))
            self._schedule_purge()

    def shard_report(self) -> Dict[str, dict]:
        """Per-query partition shard-out status (round 15,
        parallel/shards.py): shard count when the keyed device runtime
        split out, else the recorded monolithic-fallback reason."""
        out: Dict[str, dict] = {}
        for name, qr in self.device_query_runtimes.items():
            dev = getattr(qr, "device_runtime", None)
            shards = getattr(dev, "shards", None)
            out[name] = {"shards": len(shards) if shards else 0,
                         "reason": getattr(dev, "shard_reason", None)}
        return out

    def _try_device_mode(self) -> bool:
        """Compile every partition query onto keyed device lanes; any
        incompatibility rolls back cleanly to the host clone machinery."""
        from ..plan.planner import engine_mode
        from ..query_api import StateInputStream

        app = self.app_runtime
        mode = engine_mode(app.app)
        reject = None
        if mode == "host":
            reject = "engine mode 'host'"
        elif find_annotation(self.partition.annotations, "purge") is not None:
            reject = "@purge needs host per-key instances"
        else:
            from ..query_api import SingleInputStream
            for q in self.partition.queries:
                if not isinstance(q.input_stream,
                                  (StateInputStream, SingleInputStream)):
                    reject = "join partition query needs host instances"
                    break
                # _input_stream_ids keeps the '#' prefix, so inner-stream
                # consumers fail the subset check → host per-key isolation
                ids = set(self._input_stream_ids(q))
                if not ids <= set(self.executors):
                    reject = "partition query reads a non-partitioned stream"
                    break
                out = q.output_stream
                if getattr(out, "is_inner", False):
                    reject = "inner-stream output needs host per-key " \
                        "instances"
                    break
        if reject is not None:
            if mode == "device":
                raise SiddhiAppCreationError(
                    f"engine mode 'device': partition not compilable "
                    f"({reject})")
            self.fallback_reason = reject
            return False
        try:
            for i, q in enumerate(self.partition.queries):
                name = q.name or f"{self.name}_query_{i}"
                qr = QueryRuntime(q, app, name,
                                  device_key_executors=self.executors)
                self.device_query_runtimes[name] = qr
                for cb in self.pending_callbacks.get(name, []):
                    qr.add_callback(cb)
            self.device_mode = True
            return True
        except SiddhiAppCreationError as e:
            if mode == "device":
                raise
            # roll back partial junction subscriptions before host fallback
            for qr in self.device_query_runtimes.values():
                for sid, recv in qr.receivers.items():
                    app.junction_of(sid).unsubscribe(recv)
            self.device_query_runtimes = {}
            self.fallback_reason = str(e)
            return False

    @staticmethod
    def _input_stream_ids(q: Query) -> List[str]:
        from ..query_api import (JoinInputStream, SingleInputStream,
                                 StateInputStream)
        s = q.input_stream
        if isinstance(s, SingleInputStream):
            return [("#" + s.stream_id) if s.is_inner else s.stream_id]
        if isinstance(s, JoinInputStream):
            return [x.stream_id for x in (s.left, s.right)]
        if isinstance(s, StateInputStream):
            return s.all_stream_ids()
        return []

    def instance_of(self, key: str) -> _PartitionInstance:
        inst = self.instances.get(key)
        if inst is None:
            inst = _PartitionInstance(self, key)
            self.instances[key] = inst
        return inst

    def query_runtime_by_name(self, target: str):
        if self.device_mode:
            return self.device_query_runtimes.get(target)
        for q in self.partition.queries:
            if q.name == target:
                return _CallbackProxy(self, target)
        return None

    # ------------------------------------------------------------ purge

    def _schedule_purge(self):
        ctx = self.app_runtime.app_ctx

        def fire(now):
            with self.lock:
                dead = [k for k, inst in self.instances.items()
                        if now - inst.last_used > self.purge_idle_ms]
                for k in dead:
                    self.instances.pop(k).shutdown()
            ctx.scheduler.notify_at(now + self.purge_interval_ms, fire)
        ctx.scheduler.notify_at(
            ctx.timestamp_generator.current_time() + self.purge_interval_ms,
            fire)

    # ------------------------------------------------------------ snapshot

    def current_state(self):
        if self.device_mode:
            out = {}
            for qname, qr in self.device_query_runtimes.items():
                with qr.lock:      # ingest holds qr.lock, not pr.lock
                    out[qname] = {eid: obj.current_state()
                                  for eid, obj in qr.stateful_elements()}
            return {"device": out}
        out = {}
        with self.lock:
            for key, inst in self.instances.items():
                qstates = {}
                for qname, qr in inst.query_runtimes.items():
                    qstates[qname] = {eid: obj.current_state()
                                      for eid, obj in qr.stateful_elements()}
                out[key] = qstates
        return {"keys": out}

    def restore_state(self, state):
        if self.device_mode:
            for qname, elems in state.get("device", {}).items():
                qr = self.device_query_runtimes.get(qname)
                if qr is None:
                    continue
                with qr.lock:
                    live = dict(qr.stateful_elements())
                    for eid, s in elems.items():
                        if eid in live and s is not None:
                            live[eid].restore_state(s)
            return
        with self.lock:
            for key, qstates in state["keys"].items():
                inst = self.instance_of(key)
                for qname, elems in qstates.items():
                    qr = inst.query_runtimes.get(qname)
                    if qr is None:
                        continue
                    live = dict(qr.stateful_elements())
                    for eid, s in elems.items():
                        if eid in live and s is not None:
                            live[eid].restore_state(s)
