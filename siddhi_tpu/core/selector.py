"""QuerySelector: select / group by / having / order by / limit / offset.

(reference: query/selector/QuerySelector.java + GroupByKeyGenerator.java +
attribute/OutputAttributeProcessor — per-event group-key lookup and aggregator
object maps.)

Batched design: the chunk is partitioned by group key once, each aggregator
consumes its group's rows as columns (vectorised running outputs), and the
remaining select expressions run as one fused column program over the whole
batch.  Aggregator calls inside select expressions are intercepted at compile
time via the Scope.function_resolver hook and replaced by reads of synthetic
aggregate-output columns.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..plan.expr_compiler import (CompiledExpr, EvalCtx, ExprCompiler, Scope)
from ..query_api.definition import (AbstractDefinition, Attribute, AttrType,
                                    StreamDefinition)
from ..query_api.expression import AttributeFunction, Variable
from ..query_api.query import Selector
from .aggregator import AGGREGATORS, is_aggregator
from .event import CURRENT, EXPIRED, RESET, TIMER, EventChunk
from .processor import Processor
from .stateschema import MapOf, Struct, persistent_schema


class _AggSpec:
    __slots__ = ("name", "arg", "arg_type", "col_name", "output_type",
                 "cls")

    def __init__(self, name: str, arg: Optional[CompiledExpr],
                 col_name: str, cls=None):
        self.name = name
        self.arg = arg
        self.arg_type = arg.type if arg is not None else None
        self.col_name = col_name
        # cls: an extension AttributeAggregator subclass registered via
        # SiddhiManager.set_extension (≙ the reference's custom
        # StringConcatAggregator-style test extensions,
        # query/selector/attribute/aggregator SPI)
        self.cls = cls or AGGREGATORS[name]
        self.output_type = self.cls(self.arg_type).output_type

    def new_instance(self):
        return self.cls(self.arg_type)


@persistent_schema("selector",
                   schema=Struct(aggs=MapOf("agg-slots")))
class QuerySelector(Processor):
    def __init__(self, selector: Selector, input_scope: Scope,
                 input_definition: Optional[AbstractDefinition],
                 compiler_factory, output_id: str = "out"):
        super().__init__()
        self.selector = selector
        self.agg_specs: List[_AggSpec] = []
        self._agg_states: Dict[Tuple, List] = {}
        self._compile(selector, input_scope, input_definition, compiler_factory,
                      output_id)

    # ------------------------------------------------------------ compile

    def _compile(self, selector, input_scope: Scope, input_definition,
                 compiler_factory, output_id):
        # hook aggregator interception into the scope
        prev_resolver = input_scope.function_resolver

        def resolver(f: AttributeFunction):
            if is_aggregator(f.namespace, f.name, len(f.args)):
                return self._register_agg(f, compiler)
            ext = self._find_extension_aggregator(f, compiler)
            if ext is not None:
                return ext
            return prev_resolver(f) if prev_resolver else None

        input_scope.function_resolver = resolver
        compiler: ExprCompiler = compiler_factory(input_scope)

        self.group_by: List[CompiledExpr] = [
            compiler.compile(v) for v in selector.group_by]

        out_attrs: List[Attribute] = []
        self.out_exprs: List[CompiledExpr] = []
        self.out_names: List[str] = []
        if selector.select_all:
            assert input_definition is not None, "select * needs a definition"
            for a in input_definition.attributes:
                ce = compiler.compile(Variable(a.name))
                self.out_exprs.append(ce)
                self.out_names.append(a.name)
                out_attrs.append(Attribute(a.name, ce.type))
        else:
            for oa in selector.attributes:
                ce = compiler.compile(oa.expr)
                if oa.rename in self.out_names:
                    # reference DuplicateAttributeException
                    # (SelectorParser): columnar output would silently
                    # overwrite the earlier column
                    from ..utils.errors import SiddhiAppCreationError
                    raise SiddhiAppCreationError(
                        f"Duplicate output attribute '{oa.rename}' in "
                        "select (use 'as' to alias)")
                self.out_exprs.append(ce)
                self.out_names.append(oa.rename)
                out_attrs.append(Attribute(oa.rename, ce.type))
        self.output_definition = StreamDefinition(output_id, out_attrs)

        # having: output attributes shadow input attributes
        self.having: Optional[CompiledExpr] = None
        if selector.having is not None:
            hs = Scope()
            for a in out_attrs:
                def g(ctx, name=a.name):
                    return ctx.columns[name]
                hs.add(None, a.name, a.type, g)
            # fall back to input scope entries for unshadowed names
            hs._entries = {**input_scope._entries, **hs._entries}
            hs.function_resolver = resolver
            self.having = compiler_factory(hs).compile(selector.having)

        self.order_by = []
        for ob in selector.order_by:
            if ob.variable.attribute in self.out_names:
                self.order_by.append((ob.variable.attribute, ob.ascending))
        self.limit = selector.limit
        self.offset = selector.offset
        input_scope.function_resolver = prev_resolver

    def _register_agg(self, f: AttributeFunction, compiler,
                      cls=None) -> CompiledExpr:
        col = f"__agg_{len(self.agg_specs)}"
        arg = compiler.compile(f.args[0]) if f.args else None
        spec = _AggSpec(f.name.lower(), arg, col, cls=cls)
        self.agg_specs.append(spec)

        def getter(ctx, name=col):
            return ctx.columns[name]
        return CompiledExpr(getter, spec.output_type)

    def _find_extension_aggregator(self, f: AttributeFunction, compiler):
        """Custom attribute aggregators from the extension registry
        (reference: siddhiManager.setExtension + AttributeAggregator SPI,
        query/extension test corpus)."""
        from .aggregator import AttributeAggregator
        reg = getattr(compiler, "extension_registry", None)
        if reg is None:
            return None
        impl = reg.find_function(f.namespace or "", f.name)
        if not (isinstance(impl, type) and
                issubclass(impl, AttributeAggregator)):
            return None
        if len(f.args) != 1:
            from ..utils.errors import SiddhiAppCreationError
            raise SiddhiAppCreationError(
                f"aggregator extension '{f.namespace}:{f.name}' takes "
                f"exactly one argument, got {len(f.args)}")
        return self._register_agg(f, compiler, cls=impl)

    # ------------------------------------------------------------ runtime

    def process(self, chunk: EventChunk):
        n = len(chunk)
        if n == 0:
            return
        data_mask = (chunk.types == CURRENT) | (chunk.types == EXPIRED)
        reset_mask = chunk.types == RESET
        if not data_mask.any() and not reset_mask.any():
            return  # pure TIMER chunk

        ctx = EvalCtx(dict(chunk.columns), chunk.timestamps, n,
                      qualified=chunk.qualified)

        key_cols: Optional[List[np.ndarray]] = None
        if self.agg_specs:
            key_cols = [np.asarray(g.fn(ctx)) for g in self.group_by]
            self._run_aggregators(chunk, ctx, data_mask, reset_mask, key_cols)

        out_cols: Dict[str, np.ndarray] = {}
        for name, ce in zip(self.out_names, self.out_exprs):
            v = ce.fn(ctx)
            if v is None:
                v = np.full(n, None, object)
            if not isinstance(v, np.ndarray) or v.ndim == 0:
                from .event import dtype_for
                arr = np.empty(n, dtype_for(ce.type))
                arr[:] = v
                v = arr
            out_cols[name] = v

        out = EventChunk(self.out_names, chunk.timestamps, chunk.types,
                         out_cols)
        out = out.mask(data_mask)
        keep_idx = np.flatnonzero(data_mask)
        if out.is_empty:
            return

        if self.having is not None:
            hctx = EvalCtx(dict(out.columns), out.timestamps, len(out))
            hm = np.asarray(self.having.fn(hctx), bool)
            if hm.ndim == 0:
                hm = np.full(len(out), bool(hm))
            out = out.mask(hm)
            keep_idx = keep_idx[hm]
            if out.is_empty:
                return

        if self.agg_specs and getattr(chunk, "is_batch", False):
            # batch-marked chunks (lengthBatch/timeBatch/externalTimeBatch/
            # batch windows) summarize: one aggregated row per batch — the
            # last event, or the last per group key in first-seen key order
            # (reference QuerySelector.processInBatchNoGroupBy /
            # processInBatchGroupBy)
            if self.group_by:
                picks: Dict[Tuple, int] = {}
                for pos, oi in enumerate(keep_idx):
                    key = tuple(kc[oi].item() if hasattr(kc[oi], "item")
                                else kc[oi] for kc in key_cols)
                    picks[key] = pos        # dict keeps first-seen key order
                out = out.take(np.asarray(list(picks.values()), np.int64))
            else:
                out = out.take(np.asarray([len(out) - 1], np.int64))

        if self.order_by:
            keys = []
            for name, _asc in reversed(self.order_by):
                col = out.columns[name]
                keys.append(col)
            idx = np.arange(len(out))
            for name, asc in reversed(self.order_by):
                col = out.columns[name]
                order = np.argsort(col[idx], kind="stable")
                if not asc:
                    order = order[::-1]
                idx = idx[order]
            out = out.take(idx)
        if self.offset:
            out = out.slice(self.offset, len(out))
        if self.limit is not None:
            out = out.slice(0, self.limit)
        self.send_next(out)

    def _run_aggregators(self, chunk, ctx, data_mask, reset_mask, key_cols):
        n = len(chunk)
        # group keys (key_cols) were evaluated once in process(); agg args
        # evaluated over the whole batch once here
        arg_vals = [spec.arg.fn(ctx) if spec.arg is not None else None
                    for spec in self.agg_specs]
        from .event import dtype_for
        out_cols = [np.zeros(n, dtype_for(spec.output_type)
                             if spec.output_type not in
                             (AttrType.OBJECT, AttrType.STRING) else object)
                    for spec in self.agg_specs]

        active = data_mask | reset_mask
        idx_active = np.flatnonzero(active)
        if len(idx_active) == 0:
            return
        if self.group_by:
            keys = [tuple(kc[i].item() if hasattr(kc[i], "item") else kc[i]
                          for kc in key_cols) for i in idx_active]
        else:
            keys = [() for _ in idx_active]

        # RESET rows reset every group's state
        if reset_mask.any():
            # process per-row in order, handling resets globally
            for i in idx_active:
                if reset_mask[i]:
                    self._agg_states.clear()
            # fall through to grouped processing (resets already applied
            # before grouped pass only if reset precedes; to keep exact
            # ordering, do a simple ordered pass when resets are present)
            self._ordered_pass(idx_active, keys, arg_vals, chunk.types,
                               out_cols)
        else:
            # group rows by key, vectorised per group
            groups: Dict[Tuple, List[int]] = {}
            for pos, i in enumerate(idx_active):
                groups.setdefault(keys[pos], []).append(i)
            for key, rows in groups.items():
                rows_arr = np.asarray(rows)
                states = self._agg_states.get(key)
                if states is None:
                    states = [spec.new_instance() for spec in self.agg_specs]
                    self._agg_states[key] = states
                tps = chunk.types[rows_arr]
                for si, _spec in enumerate(self.agg_specs):
                    vals = None
                    if arg_vals[si] is not None:
                        v = arg_vals[si]
                        vals = (v[rows_arr] if isinstance(v, np.ndarray)
                                and v.ndim > 0 else
                                np.full(len(rows_arr), v))
                    out_cols[si][rows_arr] = states[si].process(vals, tps)
        for spec, col in zip(self.agg_specs, out_cols):
            ctx.columns[spec.col_name] = col

    def _ordered_pass(self, idx_active, keys, arg_vals, types, out_cols):
        for pos, i in enumerate(idx_active):
            key = keys[pos]
            if types[i] == RESET:
                for states in self._agg_states.values():
                    for si, _spec in enumerate(self.agg_specs):
                        v = arg_vals[si]
                        vals = None if v is None else np.asarray(
                            [v[i] if isinstance(v, np.ndarray) and v.ndim > 0
                             else v])
                        states[si].process(vals, np.asarray([RESET], np.int8))
                continue
            states = self._agg_states.get(key)
            if states is None:
                states = [spec.new_instance() for spec in self.agg_specs]
                self._agg_states[key] = states
            for si, _spec in enumerate(self.agg_specs):
                v = arg_vals[si]
                vals = None if v is None else np.asarray(
                    [v[i] if isinstance(v, np.ndarray) and v.ndim > 0 else v])
                out_cols[si][i] = states[si].process(
                    vals, np.asarray([types[i]], np.int8))[0]

    # ------------------------------------------------------------ state

    def current_state(self):
        return {"aggs": {repr(k): [a.state() for a in v]
                         for k, v in self._agg_states.items()}}

    def restore_state(self, state):
        import ast
        self._agg_states.clear()
        for k, states in state["aggs"].items():
            try:
                key = ast.literal_eval(k)
            except (ValueError, SyntaxError):
                key = k
            insts = [spec.new_instance() for spec in self.agg_specs]
            for inst, s in zip(insts, states):
                inst.restore(s)
            self._agg_states[key] = insts
