"""Ingest protection: overload admission control, poison-event
quarantine, and the dispatch-storm watchdog.

The egress side got production armor in the resilience PR (sink retry
queues, circuit breakers, error stores); this module is the matching
ingest armor:

  * ``OverloadConfig`` — per-stream @Async admission policy
    (``@Async(overload='BLOCK'|'SHED_OLDEST'|'SHED_NEW'|'STORE')``) with
    high/low watermarks on queue depth.  BLOCK bounds the formerly
    infinite ``Queue.put()`` with a timeout + typed
    ``BufferOverflowError``; the shedding policies keep the engine alive
    at 10x offered load by dropping (and exactly counting) events
    instead of wedging.
  * ``QuarantineConfig`` / ``IngestValidator`` — opt-in per-stream
    (``@quarantine(...)``) vectorized validation of ingested events:
    NaN/Inf numerics, non-coercible payload types, timestamps that
    regress beyond a configurable slack or sit so far from the
    high-water mark that they would overflow the ts32 window math.
    Rejects are routed to the error store with a typed reason (origin
    ``'ingest'``) and are replayable through the normal
    ``/errors/replay`` path — a replay re-validates.
  * ``DispatchWatchdog`` — an always-on tripwire for runaway
    timer/dispatch loops (the session-timer incident class: a 1 ms
    re-arm crawl dispatching 50k+ times on a 60-event stream with zero
    ingest progress).  When one timer target re-fires past a threshold
    with no ingest progress, the watchdog trips, force-disarms that
    target, records a ``WD0xx`` incident (surfaced on ``GET /health``
    and the error store), and lets the app keep running degraded
    instead of spinning.
  * ``IngestMetrics`` — always-on admit/shed/overflow/quarantine
    counters and a saturation gauge, rendered on ``GET /metrics``
    (deliberately independent of ``@app:statistics``, like
    ResilienceMetrics).

Kill switch: ``SIDDHI_TPU_INGEST_GUARD=0`` disables the whole subsystem
(admission falls back to the legacy unbounded blocking put, no
validator, no watchdog).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.errors import DispatchStormError, PoisonEventError
from .statistics import Counter, Gauge

log = logging.getLogger(__name__)

#: Kill switch for the whole ingest-protection subsystem.
GUARD_ENV = "SIDDHI_TPU_INGEST_GUARD"

OVERLOAD_POLICIES = ("BLOCK", "SHED_OLDEST", "SHED_NEW", "STORE")


def guard_enabled() -> bool:
    raw = os.environ.get(GUARD_ENV, "").strip().lower()
    return raw not in ("0", "false", "off", "no")


# ------------------------------------------------------------------ admission


class OverloadConfig:
    """Admission policy for one @Async junction.

    Watermarks are fractions of ``buffer.size`` (chunks).  Shedding
    policies engage at the high watermark and (for SHED_OLDEST) evict
    down to the low watermark, giving hysteresis; BLOCK ignores the
    watermarks except for /health saturation reporting.
    """

    __slots__ = ("policy", "high", "low", "high_chunks", "low_chunks",
                 "block_timeout_s", "drain_timeout_s")

    def __init__(self, policy: str = "BLOCK", high: float = 0.8,
                 low: float = 0.5, buffer_size: int = 1024,
                 block_timeout_ms: float = 60_000.0,
                 drain_timeout_ms: float = 600_000.0):
        policy = (policy or "BLOCK").upper()
        if policy not in OVERLOAD_POLICIES:
            log.warning("unknown overload policy %r: falling back to BLOCK "
                        "(see analyzer diagnostic SA060)", policy)
            policy = "BLOCK"
        if not (0.0 < high <= 1.0) or not (0.0 <= low <= 1.0) or low >= high:
            log.warning("invalid overload watermarks high=%s low=%s: using "
                        "0.8/0.5 (see analyzer diagnostic SA061)", high, low)
            high, low = 0.8, 0.5
        if block_timeout_ms <= 0:
            block_timeout_ms = 60_000.0
        if drain_timeout_ms <= 0:
            drain_timeout_ms = 600_000.0
        self.policy = policy
        self.high = high
        self.low = low
        self.high_chunks = max(1, int(high * buffer_size))
        self.low_chunks = min(max(0, int(low * buffer_size)),
                              self.high_chunks - 1)
        self.block_timeout_s = block_timeout_ms / 1000.0
        self.drain_timeout_s = drain_timeout_ms / 1000.0

    @staticmethod
    def from_annotation(ann, buffer_size: int) -> "OverloadConfig":
        def num(key, default):
            raw = ann.get(key, None)
            if raw is None:
                return default
            try:
                return float(raw)
            except (TypeError, ValueError):
                log.warning("@Async(%s=%r) on stream is not numeric: using "
                            "%s (see analyzer diagnostic SA061)",
                            key, raw, default)
                return default
        return OverloadConfig(
            policy=ann.get("overload", "BLOCK"),
            high=num("overload.high", 0.8),
            low=num("overload.low", 0.5),
            buffer_size=buffer_size,
            block_timeout_ms=num("block.timeout.ms", 60_000.0),
            drain_timeout_ms=num("drain.timeout.ms", 600_000.0))


# ------------------------------------------------------------------ quarantine


def _parse_bool(raw, default: bool) -> bool:
    if raw is None:
        return default
    v = str(raw).strip().lower()
    if v in ("0", "false", "off", "no"):
        return False
    if v in ("1", "true", "on", "yes"):
        return True
    return default      # malformed: analyzer diagnostic SA063


class QuarantineConfig:
    """Validation policy for one stream's ingest, from ``@quarantine(...)``.

    Opt-in by design: apps that deliberately feed NaN/Inf through the
    engine (outer-join null lanes, sentinel payloads) keep today's
    bit-identical behavior unless the annotation is present.
    """

    __slots__ = ("ts_slack_ms", "check_nan", "check_wrap")

    def __init__(self, ts_slack_ms: Optional[int] = None,
                 check_nan: bool = True, check_wrap: bool = True):
        self.ts_slack_ms = ts_slack_ms
        self.check_nan = check_nan
        self.check_wrap = check_wrap

    @staticmethod
    def from_annotation(ann) -> "QuarantineConfig":
        slack = None
        raw = ann.get("ts.slack.ms", None)
        if raw is not None:
            try:
                slack = int(raw)
                if slack < 0:
                    raise ValueError
            except (TypeError, ValueError):
                log.warning("@quarantine(ts.slack.ms=%r) is not a "
                            "non-negative integer: timestamp-regression "
                            "check disabled (see analyzer diagnostic "
                            "SA063)", raw)
                slack = None
        return QuarantineConfig(
            ts_slack_ms=slack,
            check_nan=_parse_bool(ann.get("nan", None), True),
            check_wrap=_parse_bool(ann.get("wrap", None), True))


class IngestValidator:
    """Vectorized poison-event filter for one stream.

    ``filter_chunk`` splits an ingest chunk into (admitted, rejects) by
    reason; ``salvage_rows`` isolates non-coercible rows when the bulk
    ``EventChunk.from_rows`` coercion fails.  The timestamp high-water
    mark advances only on admitted events, so a single wrap-poison
    timestamp cannot drag the admissible window with it.
    """

    REASON_NAN = "nan"
    REASON_TYPE = "type"
    REASON_TS_REGRESS = "ts_regress"
    REASON_TS_WRAP = "ts_wrap"

    def __init__(self, definition, config: QuarantineConfig):
        self.definition = definition
        self.config = config
        self._hwm: Optional[int] = None
        self._lock = threading.Lock()

    def salvage_rows(self, rows, stamps) -> Tuple[list, list, list]:
        """Per-row fallback when the whole-chunk dtype coercion raised:
        returns (good_rows, good_stamps, bad_events)."""
        from .event import Event, EventChunk
        good_rows: list = []
        good_stamps: list = []
        bad: list = []
        for r, ts in zip(rows, stamps):
            try:
                EventChunk.from_rows(self.definition, [r], [ts])
            except (TypeError, ValueError):
                bad.append(Event(ts, list(r)))
            else:
                good_rows.append(r)
                good_stamps.append(ts)
        return good_rows, good_stamps, bad

    def filter_chunk(self, chunk) -> Tuple[Any, List[Tuple[str, Any]]]:
        """Split `chunk` into (admitted_chunk, [(reason, reject_chunk)]).
        Vectorized: one boolean mask pass per enabled check."""
        cfg = self.config
        n = len(chunk)
        if n == 0:
            return chunk, []
        bad = np.zeros(n, bool)
        reasons = np.empty(n, object)
        if cfg.check_nan:
            for name in chunk.names:
                col = chunk.columns[name]
                if np.issubdtype(col.dtype, np.floating):
                    m = ~np.isfinite(col) & ~bad
                    reasons[m] = self.REASON_NAN
                    bad |= m
        ts = chunk.timestamps
        with self._lock:
            hwm = self._hwm
            if hwm is not None:
                if cfg.ts_slack_ms is not None:
                    m = (ts < hwm - cfg.ts_slack_ms) & ~bad
                    reasons[m] = self.REASON_TS_REGRESS
                    bad |= m
                if cfg.check_wrap:
                    from ..ops.ts32 import safe_max
                    lim = safe_max(cfg.ts_slack_ms or 0)
                    m = (np.abs(ts - hwm) > lim) & ~bad
                    reasons[m] = self.REASON_TS_WRAP
                    bad |= m
            good = chunk.mask(~bad)
            if len(good) > 0:
                mx = int(good.timestamps.max())
                if hwm is None or mx > hwm:
                    self._hwm = mx
        rejects: List[Tuple[str, Any]] = []
        if bad.any():
            for reason in (self.REASON_NAN, self.REASON_TS_REGRESS,
                           self.REASON_TS_WRAP):
                m = bad & (reasons == reason)
                if m.any():
                    rejects.append((reason, chunk.mask(m)))
        return good, rejects


def route_rejects(junction, events_by_reason: List[Tuple[str, list]]):
    """Deliver quarantined events to their destination: honor @OnError
    STREAM routing; otherwise the error store (origin='ingest'); last
    resort a log line.  Always counts ingest_quarantined_total."""
    from .resilience import make_entry
    rt = getattr(junction.app_ctx, "runtime", None)
    app_name = rt.name if rt is not None else ""
    im = getattr(rt, "ingest_metrics", None)
    store = getattr(rt, "error_store", None)
    sid = junction.definition.id
    total = sum(len(events) for _r, events in events_by_reason)
    from .flight import flight, quarantine_burst_threshold
    if total >= quarantine_burst_threshold():
        flight().emit(
            "quarantine_burst", app=app_name,
            detail={"stream": sid, "rejected": total,
                    "reasons": {r: len(e) for r, e in events_by_reason
                                if e}},
            runtime=rt)
    for reason, events in events_by_reason:
        if not events:
            continue
        if im is not None:
            im.ingest_quarantined_total.inc(len(events), stream=sid,
                                            reason=reason)
        err = PoisonEventError(
            f"quarantined {len(events)} event(s) on '{sid}': {reason}")
        if junction.on_error_action == "STREAM" \
                and junction.fault_junction is not None:
            from .event import EventChunk
            fd = junction.fault_junction.definition
            rows = [list(e.data) + [repr(err)] for e in events]
            stamps = [e.timestamp for e in events]
            junction.fault_junction.send(
                EventChunk.from_rows(fd, rows, stamps))
        elif store is not None:
            store.store(make_entry(app_name, sid, "ingest", err, events))
            rm = getattr(rt, "resilience_metrics", None)
            if rm is not None:
                rm.errors_stored_total.inc(len(events), stream=sid,
                                           origin="ingest")
        else:
            log.error("dropping %d quarantined event(s) on '%s' (%s): no "
                      "error store configured", len(events), sid, reason)


# ------------------------------------------------------------------ fair share


class TenantQuota:
    """Token-bucket ingest quota for one tenant app (``@app:quota``).

    ``rate`` is the sustained external-ingest budget in events/second;
    ``burst`` is the bucket capacity (default ``2*rate``, floor 1).
    ``admit(n)`` returns how many of the next ``n`` events may pass —
    the ingest boundary sheds the rest (reason ``'quota'``), so one
    greedy tenant saturating its own budget can never starve the shared
    device of co-tenants' dispatch slots.

    ``now`` is injectable for deterministic tests; production callers
    use the monotonic clock.  ``breach`` latches per episode so the
    flight recorder emits ONE quota_breach bundle per excursion instead
    of one per shed chunk.
    """

    __slots__ = ("app_name", "rate", "burst", "tokens", "_last",
                 "_lock", "breach")

    def __init__(self, app_name: str, rate: float,
                 burst: Optional[float] = None):
        self.app_name = app_name
        self.rate = max(float(rate), 0.0)
        b = float(burst) if burst is not None else self.rate * 2.0
        self.burst = max(b, 1.0)
        self.tokens = self.burst
        self._last: Optional[float] = None
        self._lock = threading.Lock()
        self.breach = False

    @staticmethod
    def from_annotation(app_name: str, ann) -> Optional["TenantQuota"]:
        def num(key):
            raw = ann.get(key, None)
            if raw is None:
                return None
            try:
                return float(raw)
            except (TypeError, ValueError):
                log.warning("@app:quota(%s=%r) is not numeric: ignored "
                            "(see analyzer diagnostic SA064)", key, raw)
                return None
        pos = ann.positional()
        rate = num("rate")
        if rate is None and pos:
            try:
                rate = float(pos[0])
            except (TypeError, ValueError):
                rate = None
        if rate is None or rate <= 0:
            log.warning("@app:quota on '%s' has no positive rate: quota "
                        "disabled (see analyzer diagnostic SA064)", app_name)
            return None
        return TenantQuota(app_name, rate, num("burst"))

    def admit(self, n: int, now: Optional[float] = None) -> int:
        """How many of ``n`` offered events fit the budget right now."""
        if n <= 0:
            return 0
        with self._lock:
            t = time.monotonic() if now is None else now
            if self._last is None:
                self._last = t
            dt = t - self._last
            if dt > 0:
                self.tokens = min(self.burst, self.tokens + dt * self.rate)
                self._last = t
            take = int(min(n, self.tokens))
            self.tokens -= take
            return take

    def level(self) -> float:
        """Remaining token fraction (1.0 = idle budget, 0.0 = exhausted)
        — the per-tenant saturation gauge on /metrics."""
        with self._lock:
            return self.tokens / self.burst if self.burst > 0 else 0.0


class FairShare:
    """Process-global fair-share registry: one ``TenantQuota`` per app
    plus the per-tenant admitted/shed counters rendered on /metrics.

    Registration rides ``@app:quota`` parsing (before junctions exist),
    eviction rides app shutdown; the ingest boundary caches the quota
    object at InputHandler construction, so the hot path never touches
    this registry's lock.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._quotas: Dict[str, TenantQuota] = {}
        self.tenant_admitted_total = Counter("tenant_admitted_total")
        self.tenant_shed_total = Counter("tenant_shed_total")

    def register(self, quota: TenantQuota) -> None:
        with self._lock:
            self._quotas[quota.app_name] = quota

    def unregister(self, app_name: str) -> None:
        with self._lock:
            self._quotas.pop(app_name, None)

    def quota_for(self, app_name: str) -> Optional[TenantQuota]:
        with self._lock:
            return self._quotas.get(app_name)

    def note(self, app_name: str, admitted: int, shed: int) -> None:
        if admitted:
            self.tenant_admitted_total.inc(admitted, app=app_name)
        if shed:
            self.tenant_shed_total.inc(shed, app=app_name)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            quotas = list(self._quotas.values())
        return {q.app_name: {"rate": q.rate, "burst": q.burst,
                             "level": q.level(),
                             "admitted": self.tenant_admitted_total.value(
                                 app=q.app_name),
                             "shed": self.tenant_shed_total.value(
                                 app=q.app_name)}
                for q in quotas}

    def prometheus_lines(self) -> List[str]:
        from .statistics import _fmt_labels
        out: List[str] = []
        with self._lock:
            quotas = list(self._quotas.values())
        for q in quotas:
            lb = _fmt_labels({"app": q.app_name})
            out.append(f"siddhi_tenant_quota_rate{lb} {q.rate:.9g}")
            out.append(f"siddhi_tenant_quota_burst{lb} {q.burst:.9g}")
            out.append(f"siddhi_tenant_quota_level{lb} {q.level():.9g}")
        for lkey, v in self.tenant_admitted_total.series().items():
            out.append(
                f"siddhi_tenant_admitted_total{_fmt_labels(dict(lkey))} {v}")
        for lkey, v in self.tenant_shed_total.series().items():
            out.append(
                f"siddhi_tenant_shed_total{_fmt_labels(dict(lkey))} {v}")
        return out


_FAIR_SHARE = FairShare()


def fair_share() -> FairShare:
    return _FAIR_SHARE


#: HELP/TYPE headers for the fair-share series (statistics.prometheus_text)
TENANT_TYPES = [
    ("siddhi_tenant_quota_rate", "gauge",
     "Configured @app:quota sustained ingest rate (events/second)"),
    ("siddhi_tenant_quota_burst", "gauge",
     "Configured @app:quota burst capacity (events)"),
    ("siddhi_tenant_quota_level", "gauge",
     "Remaining quota-bucket fraction per tenant (1 = idle budget)"),
    ("siddhi_tenant_admitted_total", "counter",
     "Events admitted under a tenant's fair-share quota"),
    ("siddhi_tenant_shed_total", "counter",
     "Events shed at the ingest boundary by fair-share quota "
     "enforcement"),
]


# ------------------------------------------------------------------ watchdog

#: Incident catalog (mirrors the SAxxx diagnostic catalog shape).
WD_CATALOG = {
    "WD001": "dispatch storm: a timer target re-fired repeatedly with "
             "zero ingest progress; the target was force-disarmed and "
             "the app continues degraded",
}


class DispatchWatchdog:
    """Tripwire for runaway timer/dispatch loops.

    Rides the scheduler fire path (always-on — the kernel profiler's
    dispatch counters only count when profiling is enabled): every timer
    fire is checked against a per-target streak of fires with an
    unchanged ingest-progress counter.  The streak deliberately ignores
    the fire instant: the round-5 session re-arm pathology was a 1 ms
    timer *crawl* (the re-arm instant advanced by one guard-bumped
    millisecond per fire, 50k+ dispatches on a 60-event stream), so a
    same-instant key would never see it.  A streak reaching
    ``threshold`` trips the watchdog: the target is disarmed (its
    pending and future ``notify_at`` registrations are dropped), a
    WD001 incident is recorded for ``GET /health``, and an error-store
    entry (origin='watchdog') is written when a store is configured.

    ``note_progress`` is called by every junction send and device
    pipeline submission; any event movement resets the streak, so only
    a genuinely stuck loop can trip it.  Legitimate fire bursts are
    bounded by the number of distinctly armed instants per chunk (a few
    per event), far below the 256-fire threshold, and emitting fires
    feed a junction — which itself notes progress.
    """

    def __init__(self, app_name: str, metrics: Optional["IngestMetrics"]
                 = None, threshold: int = 256):
        self.app_name = app_name
        self.metrics = metrics
        self.threshold = threshold
        self.incidents: List[Dict[str, Any]] = []
        self._disarmed: set = set()
        self._streaks: Dict[Any, list] = {}   # target -> [fires, first_ts, progress]
        self._progress = 0
        self._lock = threading.Lock()

    # hot path: junction.send / pipeline submit.  A lost increment under
    # a race only delays one streak reset; equality (not magnitude) is
    # what the streak check consumes.
    def note_progress(self, n: int = 1):
        self._progress += n

    def is_disarmed(self, target) -> bool:
        return target in self._disarmed

    def allow(self, target, now: int) -> bool:
        """Scheduler consult before invoking `target(now)`.  Returns
        False when the target is (or just became) disarmed."""
        with self._lock:
            if target in self._disarmed:
                return False
            p = self._progress
            st = self._streaks.get(target)
            if st is None or st[2] != p:
                self._streaks[target] = [1, now, p]
                return True
            st[0] += 1
            if st[0] < self.threshold:
                return True
            self._disarmed.add(target)
            fires, since = st[0], st[1]
        self._trip(target, now, fires, since)
        return False

    def _describe(self, target) -> str:
        owner = getattr(target, "__self__", None)
        fn = getattr(target, "__func__", target)
        name = getattr(fn, "__name__", repr(fn))
        if owner is not None:
            return f"{type(owner).__name__}.{name}"
        return name

    def _trip(self, target, now: int, fires: int, since: int):
        desc = self._describe(target)
        incident: Dict[str, Any] = {
            "code": "WD001", "app": self.app_name, "target": desc,
            "at": int(now), "since": int(since), "fires": fires,
            "detail": WD_CATALOG["WD001"],
        }
        from .profiling import profiler, storm_snapshot
        if profiler().enabled:
            incident["kernel_dispatches"] = storm_snapshot()
        self.incidents.append(incident)
        if self.metrics is not None:
            self.metrics.watchdog_trips_total.inc(target=desc)
        log.error("WD001 dispatch-storm watchdog tripped on app '%s': "
                  "target %s fired %d times over t=[%d..%d] with zero "
                  "ingest progress; timer disarmed", self.app_name, desc,
                  fires, since, now)
        try:
            from .resilience import make_entry
            # the owning runtime attaches itself as self.runtime
            rt = getattr(self, "runtime", None)
            rt_store = getattr(rt, "error_store", None)
            if rt_store is not None:
                rt_store.store(make_entry(
                    self.app_name, desc, "watchdog",
                    DispatchStormError(
                        f"WD001: {desc} fired {fires}x at t={now}"),
                    []))
        except Exception:   # noqa: BLE001 — tripping must never raise
            log.exception("watchdog error-store write failed")
        try:
            from .flight import flight
            flight().emit("watchdog_trip", app=self.app_name,
                          detail=incident,
                          runtime=getattr(self, "runtime", None))
        except Exception:   # noqa: BLE001
            log.exception("watchdog flight-bundle emit failed")


# ------------------------------------------------------------------ metrics


class IngestMetrics:
    """Always-on ingest-protection counters (ResilienceMetrics pattern:
    independent of @app:statistics, rendered on GET /metrics)."""

    def __init__(self, app_name: str):
        self.app_name = app_name
        self.ingest_admitted_total = Counter("ingest_admitted_total")
        self.ingest_shed_total = Counter("ingest_shed_total")
        self.ingest_overflow_total = Counter("ingest_overflow_total")
        self.ingest_quarantined_total = Counter("ingest_quarantined_total")
        self.ingest_saturation = Gauge("ingest_saturation")
        self.watchdog_trips_total = Counter("watchdog_trips_total")

    def prometheus_lines(self) -> List[str]:
        from .statistics import _fmt_labels
        out: List[str] = []

        def emit(metric: str, series, fmt=str):
            for lkey, v in series.items():
                lb = _fmt_labels({"app": self.app_name, **dict(lkey)})
                out.append(f"siddhi_{metric}{lb} {fmt(v)}")

        emit("ingest_admitted_total", self.ingest_admitted_total.series())
        emit("ingest_shed_total", self.ingest_shed_total.series())
        emit("ingest_overflow_total", self.ingest_overflow_total.series())
        emit("ingest_quarantined_total",
             self.ingest_quarantined_total.series())
        emit("ingest_saturation", self.ingest_saturation.series(),
             lambda v: f"{v:.9g}")
        emit("watchdog_trips_total", self.watchdog_trips_total.series())
        return out


#: HELP/TYPE headers merged into statistics._TYPES-driven exposition
INGEST_TYPES = [
    ("siddhi_ingest_admitted_total", "counter",
     "Events admitted into an @Async junction buffer"),
    ("siddhi_ingest_shed_total", "counter",
     "Events shed by overload policy (reason: shed_oldest | shed_new | "
     "stored | drain_timeout)"),
    ("siddhi_ingest_overflow_total", "counter",
     "Events rejected after the bounded BLOCK admission timeout"),
    ("siddhi_ingest_quarantined_total", "counter",
     "Events rejected by the @quarantine ingest validator (reason: nan | "
     "type | ts_regress | ts_wrap)"),
    ("siddhi_ingest_saturation", "gauge",
     "@Async buffer depth as a fraction of buffer.size"),
    ("siddhi_watchdog_trips_total", "counter",
     "Dispatch-storm watchdog trips (WD0xx incidents)"),
]
