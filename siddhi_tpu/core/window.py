"""Window processors.

(reference: query/processor/stream/window/*.java — 24 files: length,
lengthBatch, time, timeBatch, timeLength, externalTime, externalTimeBatch,
batch, session, sort, frequent, lossyFrequent, cron, delay ... each keeping a
SnapshotableStreamEventQueue buffer and emitting CURRENT on arrival plus
EXPIRED/RESET on eviction, per the temporal event algebra of
docs/siddhi-architecture.md:243-268.)

TPU-native design: window contents are columnar EventChunks (struct-of-arrays)
rather than linked lists of pooled objects; evictions are computed as array
slices per *batch* rather than per event, and the CURRENT/EXPIRED interleaving
the reference produces event-by-event is reconstructed with one permutation
(`_interleave`) so downstream batched aggregators observe the identical order.
Windows are FindableProcessors: joins probe their buffer columns directly.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx
from ..utils.errors import SiddhiAppCreationError
from .event import (CURRENT, EXPIRED, RESET, TIMER, EventChunk)
from .processor import Processor
from .stateschema import (Chunk, ListOf, MapOf, Opt, Scalar, Struct,
                          persistent_schema)


@persistent_schema("window-buffer",
                   schema=Struct(buffer=Opt(Chunk())))
class WindowProcessor(Processor):
    """Base: keeps a columnar buffer; subclasses implement `on_data`."""

    requires_scheduler = False

    def __init__(self, app_ctx, names: List[str]):
        super().__init__()
        self.app_ctx = app_ctx
        self.names = names
        self.buffer: Optional[EventChunk] = None
        self.lock: Optional[threading.RLock] = None  # set by query wiring

    # -------------------------------------------------------------- helpers

    def _buf_len(self) -> int:
        return 0 if self.buffer is None else len(self.buffer)

    def _buf_append(self, chunk: EventChunk):
        chunk = chunk.with_types(CURRENT)
        self.buffer = chunk if self.buffer is None \
            else EventChunk.concat([self.buffer, chunk])

    def _buf_take_front(self, k: int) -> EventChunk:
        assert self.buffer is not None
        front = self.buffer.slice(0, k)
        self.buffer = self.buffer.slice(k, len(self.buffer))
        return front

    def process(self, chunk: EventChunk):
        if chunk.is_empty:
            return
        timer_mask = chunk.types == TIMER
        if timer_mask.any():
            self.on_timer_event(int(chunk.timestamps[timer_mask][-1]))
        data = chunk.mask(~timer_mask)
        if not data.is_empty:
            self.on_data(data)

    def on_data(self, chunk: EventChunk):
        raise NotImplementedError

    def on_timer_event(self, ts: int):
        pass

    def _locked(self, fn, *args):
        if self.lock is not None:
            with self.lock:
                fn(*args)
        else:
            fn(*args)

    # -------------------------------------------------------------- find (joins)

    def find_chunk(self) -> Optional[EventChunk]:
        """Current window contents for join probing / store queries."""
        return self.buffer

    # -------------------------------------------------------------- state

    def current_state(self):
        if self.buffer is None:
            return {"buffer": None}
        return {"buffer": _chunk_state(self.buffer)}

    def restore_state(self, state):
        self.buffer = _chunk_restore(state["buffer"], self.names)


@persistent_schema("window-grouped",
                   schema=Struct(keys=ListOf("key"),
                                 inners=ListOf("window-state")))
class GroupingWindowProcessor(WindowProcessor):
    """Extension base: window state partitioned per group key (reference
    query/processor/stream/window/GroupingWindowProcessor.java — the
    `_groupingKey` SPI base its grouping window extensions subclass).

    Subclasses declare `make_inner() -> WindowProcessor` (a fresh inner
    window per key) and get one isolated inner instance per group-key
    value; emissions from every inner flow to this processor's `next`."""

    def __init__(self, app_ctx, names, key_expr: CompiledExpr):
        super().__init__(app_ctx, names)
        self.key_expr = key_expr
        self._inners: Dict = {}

    def make_inner(self) -> "WindowProcessor":
        raise NotImplementedError

    def _inner_for(self, key) -> "WindowProcessor":
        w = self._inners.get(key)
        if w is None:
            w = self.make_inner()
            w.lock = self.lock
            w.next = _GroupForward(self)
            self._inners[key] = w
        return w

    _NAN_KEY = "__nan__"

    def on_data(self, chunk: EventChunk):
        n = len(chunk)
        ctx = EvalCtx(dict(chunk.columns), chunk.timestamps, n)
        keys = np.asarray(self.key_expr.fn(ctx))
        if keys.ndim == 0:
            keys = np.full(n, keys)
        # NaN != NaN would both defeat the dedup (a leaked inner per
        # occurrence) and zero the mask (events silently dropped) — fold
        # every NaN into one sentinel bucket
        key_list = [self._NAN_KEY if k != k else k for k in keys.tolist()]
        for key in dict.fromkeys(key_list):          # first-seen order
            m = np.asarray([k == key for k in key_list])
            self._inner_for(key).process(chunk.mask(m))

    def on_timer_event(self, ts: int):
        for w in self._inners.values():
            w.on_timer_event(ts)

    def find_chunk(self) -> Optional[EventChunk]:
        parts = [w.find_chunk() for w in self._inners.values()]
        parts = [p for p in parts if p is not None and not p.is_empty]
        return EventChunk.concat(parts) if parts else None

    def current_state(self):
        return {"keys": list(self._inners),
                "inners": [w.current_state()
                           for w in self._inners.values()]}

    def restore_state(self, state):
        self._inners = {}
        for key, s in zip(state["keys"], state["inners"]):
            self._inner_for(key).restore_state(s)


class _GroupForward(Processor):
    """Routes a per-key inner window's emissions to the group processor's
    downstream."""

    def __init__(self, owner: GroupingWindowProcessor):
        super().__init__()
        self.owner = owner

    def process(self, chunk: EventChunk):
        if self.owner.next is not None:
            self.owner.next.process(chunk)


def _chunk_state(c: EventChunk) -> dict:
    return {"names": c.names,
            "timestamps": c.timestamps.tolist(),
            "types": c.types.tolist(),
            "columns": {k: v.tolist() for k, v in c.columns.items()},
            "dtypes": {k: str(v.dtype) for k, v in c.columns.items()}}


def _chunk_restore(s: Optional[dict], names) -> Optional[EventChunk]:
    if s is None:
        return None
    cols = {}
    for k, vals in s["columns"].items():
        dt = s["dtypes"][k]
        cols[k] = np.asarray(vals, object) if dt == "object" \
            else np.asarray(vals, np.dtype(dt))
    return EventChunk(s["names"], np.asarray(s["timestamps"], np.int64),
                      np.asarray(s["types"], np.int8), cols)


def _interleave(expired: EventChunk, current: EventChunk,
                pair_from: int) -> EventChunk:
    """Reconstruct the reference's per-event emission order: current events
    [0..pair_from) emit alone; current event pair_from+j is preceded by
    expired[j].  Result: [c_0..c_{pf-1}, e_0, c_pf, e_1, c_{pf+1}, ...]."""
    if expired.is_empty:
        return current
    m, k = len(current), len(expired)
    total = m + k
    # build gather order over concat([expired, current])
    order = np.empty(total, np.int64)
    pos = 0
    ci, ei = 0, 0
    # vectorised construction
    head = pair_from
    order[:head] = k + np.arange(head)                       # leading currents
    body = np.empty((m - head) * 2, np.int64)
    body[0::2] = np.arange(k)                                # expired j
    body[1::2] = k + head + np.arange(m - head)              # current pf+j
    order[head:] = body[:total - head]
    both = EventChunk.concat([expired, current])
    return both.take(order)


# ===================================================================== length

class LengthWindowProcessor(WindowProcessor):
    """Sliding length(n) (reference LengthWindowProcessor.java)."""

    def __init__(self, app_ctx, names, length: int):
        super().__init__(app_ctx, names)
        self.length = length

    def on_data(self, chunk: EventChunk):
        m = len(chunk)
        b = self._buf_len()
        combined = EventChunk.concat([self.buffer, chunk.with_types(CURRENT)]) \
            if self.buffer is not None else chunk.with_types(CURRENT)
        overflow = max(0, b + m - self.length)
        expired = combined.slice(0, overflow).with_types(EXPIRED)
        self.buffer = combined.slice(overflow, b + m)
        # expired event timestamps = displacing event's timestamp
        if overflow:
            c0 = max(0, self.length - b)   # currents that displace nothing
            disp_ts = chunk.timestamps[c0:c0 + overflow]
            expired = expired.with_timestamps(disp_ts)
            out = _interleave(expired, chunk, c0)
        else:
            out = chunk
        self.send_next(out)


@persistent_schema("window-length-batch",
                   schema=Struct(buffer=Opt(Chunk()),
                                 expired_batch=Opt(Chunk())))
class LengthBatchWindowProcessor(WindowProcessor):
    """Tumbling lengthBatch(n): emits [prev batch EXPIRED, RESET, new batch
    CURRENT] when n events collect (reference LengthBatchWindowProcessor)."""

    def __init__(self, app_ctx, names, length: int):
        super().__init__(app_ctx, names)
        self.length = length
        self.expired_batch: Optional[EventChunk] = None

    def on_data(self, chunk: EventChunk):
        pending = EventChunk.concat([self.buffer, chunk]) \
            if self.buffer is not None else chunk
        flushes = []
        while len(pending) >= self.length:
            batch = pending.slice(0, self.length)
            pending = pending.slice(self.length, len(pending))
            ts = int(batch.timestamps[-1])
            outs = []
            if self.expired_batch is not None:
                outs.append(self.expired_batch.with_types(EXPIRED)
                            .with_timestamps(np.full(len(self.expired_batch),
                                                     ts, np.int64)))
            outs.append(_reset_row(batch, ts))
            outs.append(batch.with_types(CURRENT))
            self.expired_batch = batch
            flushes.append(outs)
        self.buffer = pending if len(pending) else None
        # one chunk PER batch flush — aggregated selects summarize each
        # batch-marked chunk to a single row (reference setBatch(true)), so
        # merging flushes would drop all but the last batch's aggregate
        for outs in flushes:
            out = EventChunk.concat(outs)
            out.is_batch = True
            self.send_next(out)

    def current_state(self):
        s = super().current_state()
        s["expired_batch"] = None if self.expired_batch is None \
            else _chunk_state(self.expired_batch)
        return s

    def restore_state(self, state):
        super().restore_state(state)
        self.expired_batch = _chunk_restore(state.get("expired_batch"),
                                            self.names)


def _reset_row(proto: EventChunk, ts: int) -> EventChunk:
    cols = {n: np.asarray([None], object) if proto.columns[n].dtype == object
            else np.zeros(1, proto.columns[n].dtype) for n in proto.names}
    return EventChunk(proto.names, np.asarray([ts], np.int64),
                      np.asarray([RESET], np.int8), cols)


# ===================================================================== time

class TimeWindowProcessor(WindowProcessor):
    """Sliding time(t): events expire t ms after arrival, driven by the
    scheduler (reference TimeWindowProcessor.java)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, window_ms: int):
        super().__init__(app_ctx, names)
        self.window_ms = window_ms

    def on_data(self, chunk: EventChunk):
        now = int(chunk.timestamps[-1])
        expired = self._collect_expired(now)
        self._buf_append(chunk)
        self.app_ctx.scheduler.notify_at(now + self.window_ms, self._on_timer)
        # all expired here predate the whole batch → emit before currents
        if expired is not None and not expired.is_empty:
            self.send_next(EventChunk.concat([expired, chunk]))
        else:
            self.send_next(chunk)

    def _collect_expired(self, now: int) -> Optional[EventChunk]:
        if self.buffer is None or self.buffer.is_empty:
            return None
        cutoff = now - self.window_ms
        k = int(np.searchsorted(self.buffer.timestamps, cutoff, side="right"))
        if k <= 0:
            return None
        ex = self._buf_take_front(k)
        return ex.with_types(EXPIRED).with_timestamps(
            ex.timestamps + self.window_ms)

    def _on_timer(self, now: int):
        def run():
            expired = self._collect_expired(now)
            if expired is not None and not expired.is_empty:
                self.send_next(expired)
            if self._buf_len():
                nxt = int(self.buffer.timestamps[0]) + self.window_ms
                self.app_ctx.scheduler.notify_at(nxt, self._on_timer)
        self._locked(run)

    def on_timer_event(self, ts: int):
        expired = self._collect_expired(ts)
        if expired is not None and not expired.is_empty:
            self.send_next(expired)


class ExternalTimeWindowProcessor(TimeWindowProcessor):
    """Sliding externalTime(ts_attr, t): driven purely by event timestamps
    (reference ExternalTimeWindowProcessor.java)."""

    requires_scheduler = False

    def __init__(self, app_ctx, names, ts_expr: CompiledExpr, window_ms: int):
        WindowProcessor.__init__(self, app_ctx, names)
        self.window_ms = window_ms
        self.ts_expr = ts_expr

    def on_data(self, chunk: EventChunk):
        ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
        etimes = np.asarray(self.ts_expr.fn(ctx), np.int64)
        chunk = chunk.with_timestamps(etimes)
        outs = []
        # per-event: expire then current (event time strictly ordered)
        for i in range(len(chunk)):
            now = int(etimes[i])
            expired = self._collect_expired_lte(now)
            if expired is not None:
                outs.append(expired)
            row = chunk.slice(i, i + 1)
            self._buf_append(row)
            outs.append(row)
        self.send_next(EventChunk.concat(outs))

    def _collect_expired_lte(self, now: int) -> Optional[EventChunk]:
        if self.buffer is None or self.buffer.is_empty:
            return None
        cutoff = now - self.window_ms
        k = int(np.searchsorted(self.buffer.timestamps, cutoff, side="right"))
        if k <= 0:
            return None
        ex = self._buf_take_front(k)
        return ex.with_types(EXPIRED).with_timestamps(
            np.full(len(ex), now, np.int64))


class TimeBatchWindowProcessor(WindowProcessor):
    """Tumbling timeBatch(t) (reference TimeBatchWindowProcessor.java)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, window_ms: int,
                 start_time: Optional[int] = None):
        super().__init__(app_ctx, names)
        self.window_ms = window_ms
        self.next_emit: Optional[int] = None
        self.start_time = start_time
        self.expired_batch: Optional[EventChunk] = None

    def on_data(self, chunk: EventChunk):
        now = int(chunk.timestamps[-1])
        if self.next_emit is None:
            base = self.start_time if self.start_time is not None else \
                int(chunk.timestamps[0])
            self.next_emit = base + self.window_ms
            self.app_ctx.scheduler.notify_at(self.next_emit, self._on_timer)
        self._emit_due(now)
        self._buf_append(chunk)

    def _emit_due(self, now: int):
        while self.next_emit is not None and now >= self.next_emit:
            self._flush(self.next_emit)
            self.next_emit += self.window_ms

    def _flush(self, ts: int):
        outs = []
        batch = self.buffer
        self.buffer = None
        if self.expired_batch is not None:
            outs.append(self.expired_batch.with_types(EXPIRED)
                        .with_timestamps(np.full(len(self.expired_batch), ts,
                                                 np.int64)))
        if batch is not None and not batch.is_empty:
            outs.append(_reset_row(batch, ts))
            outs.append(batch.with_types(CURRENT))
        self.expired_batch = batch
        if outs:
            out = EventChunk.concat(outs)
            out.is_batch = True
            self.send_next(out)

    def _on_timer(self, now: int):
        def run():
            self._emit_due(now)
            if self.next_emit is not None:
                self.app_ctx.scheduler.notify_at(self.next_emit, self._on_timer)
        self._locked(run)

    def on_timer_event(self, ts: int):
        self._emit_due(ts)


@persistent_schema("window-hopping",
                   schema=Struct(buffer=Opt(Chunk()),
                                 next_emit=Scalar("opt_int"),
                                 last_emitted=Opt(Chunk())))
class HopingWindowProcessor(WindowProcessor):
    """Hopping time window: every hop(t2) emit the events of the last
    window(t1) as CURRENT and those that slid out as EXPIRED (reference
    HopingWindowProcessor.java — 'hoping' spelling kept for SiddhiQL
    compatibility; `hopping` is accepted too)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, window_ms: int, hop_ms: int):
        super().__init__(app_ctx, names)
        self.window_ms = window_ms
        self.hop_ms = hop_ms
        self.next_emit: Optional[int] = None
        self.last_emitted: Optional[EventChunk] = None

    def on_data(self, chunk: EventChunk):
        if self.next_emit is None:
            self.next_emit = int(chunk.timestamps[0]) + self.hop_ms
            self.app_ctx.scheduler.notify_at(self.next_emit, self._on_timer)
        # a batch may span hop boundaries: events at or before a due hop
        # belong to that hop's window, so split-append before each emission
        while not chunk.is_empty and \
                int(chunk.timestamps[-1]) >= self.next_emit:
            pre = chunk.timestamps <= self.next_emit
            if pre.any():
                self._buf_append(chunk.mask(pre))
                chunk = chunk.mask(~pre)
            self._hop(self.next_emit)
            self.next_emit += self.hop_ms
        if not chunk.is_empty:
            self._buf_append(chunk)

    def _emit_due(self, now: int):
        while self.next_emit is not None and now >= self.next_emit:
            self._hop(self.next_emit)
            self.next_emit += self.hop_ms

    def _hop(self, ts: int):
        # window contents at this hop = events with ts in (ts - window, ts]
        outs = []
        if self.buffer is not None and not self.buffer.is_empty:
            keep = self.buffer.timestamps > ts - self.window_ms
            self.buffer = self.buffer.mask(keep)
        current = self.buffer
        if self.last_emitted is not None and not self.last_emitted.is_empty:
            gone = self.last_emitted.timestamps <= ts - self.window_ms
            expired = self.last_emitted.mask(gone)
            if not expired.is_empty:
                outs.append(expired.with_types(EXPIRED).with_timestamps(
                    np.full(len(expired), ts, np.int64)))
        if current is not None and not current.is_empty:
            outs.append(_reset_row(current, ts))
            outs.append(current.with_types(CURRENT))
        self.last_emitted = current.copy() if current is not None else None
        if outs:
            self.send_next(EventChunk.concat(outs))

    def _on_timer(self, now: int):
        def run():
            self._emit_due(now)
            if self.next_emit is not None:
                self.app_ctx.scheduler.notify_at(self.next_emit,
                                                 self._on_timer)
        self._locked(run)

    def on_timer_event(self, ts: int):
        self._emit_due(ts)

    def current_state(self):
        s = super().current_state()
        s["next_emit"] = self.next_emit
        s["last_emitted"] = (_chunk_state(self.last_emitted)
                             if self.last_emitted is not None else None)
        return s

    def restore_state(self, state):
        super().restore_state(state)
        self.next_emit = state.get("next_emit")
        le = state.get("last_emitted")
        self.last_emitted = _chunk_restore(le, self.names) if le else None


class ExternalTimeBatchWindowProcessor(WindowProcessor):
    """Tumbling externalTimeBatch(ts_attr, t [, start])
    (reference ExternalTimeBatchWindowProcessor.java)."""

    def __init__(self, app_ctx, names, ts_expr: CompiledExpr, window_ms: int,
                 start_time: Optional[int] = None):
        super().__init__(app_ctx, names)
        self.ts_expr = ts_expr
        self.window_ms = window_ms
        self.start_time = start_time
        self.window_end: Optional[int] = None
        self.expired_batch: Optional[EventChunk] = None

    def on_data(self, chunk: EventChunk):
        ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
        etimes = np.asarray(self.ts_expr.fn(ctx), np.int64)
        outs = []
        for i in range(len(chunk)):
            t = int(etimes[i])
            if self.window_end is None:
                base = self.start_time if self.start_time is not None else t
                self.window_end = base + self.window_ms
            while t >= self.window_end:
                flushed = self._flush(self.window_end)
                if flushed is not None:
                    outs.append(flushed)
                self.window_end += self.window_ms
            row = chunk.slice(i, i + 1)
            self._buf_append(row)
        # one chunk per window flush (see LengthBatchWindowProcessor.on_data)
        for out in outs:
            out.is_batch = True
            self.send_next(out)

    def _flush(self, ts: int) -> Optional[EventChunk]:
        outs = []
        batch = self.buffer
        self.buffer = None
        if self.expired_batch is not None:
            outs.append(self.expired_batch.with_types(EXPIRED)
                        .with_timestamps(np.full(len(self.expired_batch), ts,
                                                 np.int64)))
        if batch is not None and not batch.is_empty:
            outs.append(_reset_row(batch, ts))
            outs.append(batch.with_types(CURRENT))
            self.expired_batch = batch
        if not outs:
            return None
        return EventChunk.concat(outs)


class TimeLengthWindowProcessor(WindowProcessor):
    """timeLength(t, n): sliding, bounded by both time and count
    (reference TimeLengthWindowProcessor.java)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, window_ms: int, length: int):
        super().__init__(app_ctx, names)
        self.window_ms = window_ms
        self.length = length

    def on_data(self, chunk: EventChunk):
        outs = []
        for i in range(len(chunk)):
            row = chunk.slice(i, i + 1)
            now = int(row.timestamps[0])
            ex_t = self._expire_time(now)
            if ex_t is not None:
                outs.append(ex_t)
            if self._buf_len() >= self.length:
                ex = self._buf_take_front(1)
                outs.append(ex.with_types(EXPIRED).with_timestamps(
                    np.asarray([now], np.int64)))
            self._buf_append(row)
            outs.append(row)
            self.app_ctx.scheduler.notify_at(now + self.window_ms,
                                             self._on_timer)
        self.send_next(EventChunk.concat(outs))

    def _expire_time(self, now: int) -> Optional[EventChunk]:
        if self.buffer is None or self.buffer.is_empty:
            return None
        cutoff = now - self.window_ms
        k = int(np.searchsorted(self.buffer.timestamps, cutoff, side="right"))
        if k <= 0:
            return None
        ex = self._buf_take_front(k)
        return ex.with_types(EXPIRED).with_timestamps(
            ex.timestamps + self.window_ms)

    def _on_timer(self, now: int):
        def run():
            ex = self._expire_time(now)
            if ex is not None and not ex.is_empty:
                self.send_next(ex)
        self._locked(run)

    def on_timer_event(self, ts: int):
        ex = self._expire_time(ts)
        if ex is not None and not ex.is_empty:
            self.send_next(ex)


# ===================================================================== batch

class BatchWindowProcessor(WindowProcessor):
    """batch(): each arriving chunk replaces the window; previous chunk expires
    (reference WindowBatchWindowProcessor / batch window)."""

    def on_data(self, chunk: EventChunk):
        outs = []
        ts = int(chunk.timestamps[-1])
        if self.buffer is not None and not self.buffer.is_empty:
            outs.append(self.buffer.with_types(EXPIRED)
                        .with_timestamps(np.full(self._buf_len(), ts,
                                                 np.int64)))
        outs.append(_reset_row(chunk, ts))
        outs.append(chunk.with_types(CURRENT))
        self.buffer = chunk.with_types(CURRENT)
        out = EventChunk.concat(outs)
        out.is_batch = True
        self.send_next(out)


# ===================================================================== session

@persistent_schema("window-session",
                   schema=Struct(sessions=MapOf("session")))
class SessionWindowProcessor(WindowProcessor):
    """session(gap [, key_attr [, allowedLatency]]): per-key session batches
    emitted as EXPIRED on gap timeout (reference SessionWindowProcessor)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, gap_ms: int,
                 key_expr: Optional[CompiledExpr] = None):
        super().__init__(app_ctx, names)
        self.gap_ms = gap_ms
        self.key_expr = key_expr
        self.sessions: Dict[object, List] = {}   # key -> [chunks, last_ts]

    def on_data(self, chunk: EventChunk):
        now = int(chunk.timestamps[-1])
        self._expire_sessions(now, emit=True)
        if self.key_expr is not None:
            ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
            keys = np.asarray(self.key_expr.fn(ctx))
        else:
            keys = np.full(len(chunk), "", object)
        for i in range(len(chunk)):
            k = keys[i].item() if hasattr(keys[i], "item") else keys[i]
            row = chunk.slice(i, i + 1)
            sess = self.sessions.setdefault(k, [[], 0])
            sess[0].append(row)
            sess[1] = int(row.timestamps[0])
        self.app_ctx.scheduler.notify_at(now + self.gap_ms, self._on_timer)
        self.send_next(chunk)

    def _expire_sessions(self, now: int, emit: bool):
        done = [k for k, (chunks, last) in self.sessions.items()
                if now - last >= self.gap_ms]
        outs = []
        for k in done:
            chunks, last = self.sessions.pop(k)
            ex = EventChunk.concat(chunks).with_types(EXPIRED)
            outs.append(ex.with_timestamps(
                np.full(len(ex), last + self.gap_ms, np.int64)))
        if outs and emit:
            self.send_next(EventChunk.concat(outs))
        elif outs:
            self.send_next(EventChunk.concat(outs))

    def _on_timer(self, now: int):
        self._locked(self._expire_sessions, now, True)

    def on_timer_event(self, ts: int):
        self._expire_sessions(ts, True)

    def current_state(self):
        return {"sessions": {repr(k): ([_chunk_state(c) for c in chunks], last)
                             for k, (chunks, last) in self.sessions.items()}}

    def restore_state(self, state):
        import ast
        self.sessions.clear()
        for k, (chunks, last) in state["sessions"].items():
            try:
                key = ast.literal_eval(k)
            except (ValueError, SyntaxError):
                key = k
            self.sessions[key] = [[_chunk_restore(c, self.names)
                                   for c in chunks], last]


# ===================================================================== sort

class SortWindowProcessor(WindowProcessor):
    """sort(n, attr [, 'asc'|'desc', attr2, ...]): keeps the top-n events by
    sort order; evicted extremum emitted EXPIRED (reference
    SortWindowProcessor.java)."""

    def __init__(self, app_ctx, names, length: int,
                 sort_keys: List[Tuple[CompiledExpr, bool]]):
        super().__init__(app_ctx, names)
        self.length = length
        self.sort_keys = sort_keys

    def on_data(self, chunk: EventChunk):
        outs = []
        for i in range(len(chunk)):
            row = chunk.slice(i, i + 1)
            self._buf_append(row)
            outs.append(row)
            if self._buf_len() > self.length:
                idx = self._sorted_indices()
                # evict the LAST element in sort order
                evict = int(idx[-1])
                ex = self.buffer.slice(evict, evict + 1)
                keep = np.concatenate([np.arange(evict),
                                       np.arange(evict + 1, self._buf_len())])
                self.buffer = self.buffer.take(keep)
                outs.append(ex.with_types(EXPIRED).with_timestamps(
                    row.timestamps))
        self.send_next(EventChunk.concat(outs))

    def _sorted_indices(self) -> np.ndarray:
        b = self.buffer
        ctx = EvalCtx(b.columns, b.timestamps, len(b))
        idx = np.arange(len(b))
        for ce, asc in reversed(self.sort_keys):
            col = np.asarray(ce.fn(ctx))
            order = np.argsort(col[idx], kind="stable")
            if not asc:
                order = order[::-1]
            idx = idx[order]
        return idx


# ===================================================================== frequent

@persistent_schema("window-frequent",
                   schema=Struct(counts=MapOf("int"),
                                 latest=MapOf("chunk")))
class FrequentWindowProcessor(WindowProcessor):
    """frequent(n [, attrs...]): Misra-Gries heavy hitters; evicted events
    emitted EXPIRED (reference FrequentWindowProcessor.java)."""

    def __init__(self, app_ctx, names, count: int,
                 key_exprs: List[CompiledExpr]):
        super().__init__(app_ctx, names)
        self.count = count
        self.key_exprs = key_exprs
        self.counts: Dict[object, int] = {}
        self.latest: Dict[object, EventChunk] = {}

    def _keys(self, chunk: EventChunk) -> List:
        if not self.key_exprs:
            return [tuple(chunk.row(i)[1]) for i in range(len(chunk))]
        ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
        cols = [np.asarray(ce.fn(ctx)) for ce in self.key_exprs]
        return [tuple(c[i].item() if hasattr(c[i], "item") else c[i]
                      for c in cols) for i in range(len(chunk))]

    def on_data(self, chunk: EventChunk):
        outs = []
        keys = self._keys(chunk)
        for i, k in enumerate(keys):
            row = chunk.slice(i, i + 1)
            if k in self.counts:
                self.counts[k] += 1
                self.latest[k] = row
                outs.append(row)
            elif len(self.counts) < self.count:
                self.counts[k] = 1
                self.latest[k] = row
                outs.append(row)
            else:
                # new key at capacity: decrement the resident keys, evict
                # zeros (EXPIRED); admit the new key only if space opened,
                # else drop the arriving event unemitted (reference
                # FrequentWindowProcessor.process)
                evicted = []
                for kk in list(self.counts):
                    self.counts[kk] -= 1
                    if self.counts[kk] <= 0:
                        del self.counts[kk]
                        ev = self.latest.pop(kk)
                        evicted.append(ev.with_types(EXPIRED)
                                       .with_timestamps(row.timestamps))
                outs.extend(evicted)
                if len(self.counts) < self.count:
                    self.counts[k] = 1
                    self.latest[k] = row
                    outs.append(row)
        self.send_next(EventChunk.concat(outs))

    def current_state(self):
        return {"counts": {repr(k): v for k, v in self.counts.items()},
                "latest": {repr(k): _chunk_state(v)
                           for k, v in self.latest.items()}}

    def restore_state(self, state):
        import ast
        self.counts = {}
        self.latest = {}
        for k, v in state["counts"].items():
            self.counts[ast.literal_eval(k)] = v
        for k, v in state["latest"].items():
            self.latest[ast.literal_eval(k)] = _chunk_restore(v, self.names)


class LossyFrequentWindowProcessor(FrequentWindowProcessor):
    """lossyFrequent(support [, error, attrs...]) — lossy counting
    (reference LossyFrequentWindowProcessor.java)."""

    def __init__(self, app_ctx, names, support: float, error: float,
                 key_exprs: List[CompiledExpr]):
        WindowProcessor.__init__(self, app_ctx, names)
        self.support = support
        self.error = error
        self.key_exprs = key_exprs
        self.counts: Dict[object, int] = {}
        self.deltas: Dict[object, int] = {}
        self.latest: Dict[object, EventChunk] = {}
        self.total = 0

    def on_data(self, chunk: EventChunk):
        outs = []
        keys = self._keys(chunk)
        width = int(np.ceil(1.0 / self.error)) if self.error > 0 else 1000
        for i, k in enumerate(keys):
            row = chunk.slice(i, i + 1)
            self.total += 1
            bucket = int(np.ceil(self.total / width))
            if k in self.counts:
                self.counts[k] += 1
            else:
                self.counts[k] = 1
                self.deltas[k] = bucket - 1
            self.latest[k] = row
            outs.append(row)
            if self.total % width == 0:
                for kk in list(self.counts):
                    if self.counts[kk] + self.deltas.get(kk, 0) <= bucket:
                        del self.counts[kk]
                        self.deltas.pop(kk, None)
                        ev = self.latest.pop(kk, None)
                        if ev is not None:
                            outs.append(ev.with_types(EXPIRED)
                                        .with_timestamps(row.timestamps))
        self.send_next(EventChunk.concat(outs))


# ===================================================================== delay

class DelayWindowProcessor(WindowProcessor):
    """delay(t): events re-emitted as CURRENT after t ms
    (reference DelayWindowProcessor.java)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, delay_ms: int):
        super().__init__(app_ctx, names)
        self.delay_ms = delay_ms

    def on_data(self, chunk: EventChunk):
        now = int(chunk.timestamps[-1])
        due = self._due(now)
        self._buf_append(chunk)
        self.app_ctx.scheduler.notify_at(now + self.delay_ms, self._on_timer)
        if due is not None and not due.is_empty:
            self.send_next(due)

    def _due(self, now: int) -> Optional[EventChunk]:
        if self.buffer is None or self.buffer.is_empty:
            return None
        cutoff = now - self.delay_ms
        k = int(np.searchsorted(self.buffer.timestamps, cutoff, side="right"))
        if k <= 0:
            return None
        out = self._buf_take_front(k)
        return out.with_types(CURRENT)

    def _on_timer(self, now: int):
        def run():
            due = self._due(now)
            if due is not None and not due.is_empty:
                self.send_next(due)
            if self._buf_len():
                self.app_ctx.scheduler.notify_at(
                    int(self.buffer.timestamps[0]) + self.delay_ms,
                    self._on_timer)
        self._locked(run)

    def on_timer_event(self, ts: int):
        due = self._due(ts)
        if due is not None and not due.is_empty:
            self.send_next(due)


# ===================================================================== cron

class CronWindowProcessor(WindowProcessor):
    """cron('expr'): emits the collected batch on each cron fire
    (reference CronWindowProcessor.java, Quartz-driven)."""

    requires_scheduler = True

    def __init__(self, app_ctx, names, cron_expr: str):
        super().__init__(app_ctx, names)
        from ..utils.cron import CronSchedule
        self.cron = CronSchedule(cron_expr)
        self.expired_batch: Optional[EventChunk] = None
        self._armed = False

    def on_data(self, chunk: EventChunk):
        self._buf_append(chunk)
        if not self._armed:
            self._armed = True
            nxt = self.cron.next_after(self.app_ctx.current_time())
            self.app_ctx.scheduler.notify_at(nxt, self._on_timer)

    def _on_timer(self, now: int):
        def run():
            outs = []
            batch = self.buffer
            self.buffer = None
            if self.expired_batch is not None:
                outs.append(self.expired_batch.with_types(EXPIRED)
                            .with_timestamps(np.full(len(self.expired_batch),
                                                     now, np.int64)))
            if batch is not None and not batch.is_empty:
                outs.append(batch.with_types(CURRENT))
                self.expired_batch = batch
            if outs:
                self.send_next(EventChunk.concat(outs))
            nxt = self.cron.next_after(now)
            self.app_ctx.scheduler.notify_at(nxt, self._on_timer)
        self._locked(run)


# ===================================================================== factory

def create_window_processor(name: str, params: List, app_ctx, names,
                            compile_expr, namespace: str = "",
                            extension_registry=None) -> WindowProcessor:
    """Factory mapping window names to processors.  `params` are query-api
    Expressions; `compile_expr` compiles one against the input scope.
    Namespaced (or unknown) names resolve through the extension registry
    (reference: SiddhiExtensionLoader window holders) — the registered
    class either subclasses WindowProcessor (instantiated as
    cls(app_ctx, names, params, compile_expr)) or provides a
    create(app_ctx, names, params, compile_expr) factory."""
    from ..query_api.expression import Constant, TimeConstant

    def _extension():
        if extension_registry is None:
            return None
        ext = extension_registry.find_window(namespace or "", name)
        if ext is None:
            return None
        # the registry is kind-unsegregated: only window-shaped classes
        # qualify, so a colliding function/source name falls through to
        # the proper "Unknown window type" error
        if hasattr(ext, "create"):
            return ext.create(app_ctx, names, params, compile_expr)
        if isinstance(ext, type) and issubclass(ext, WindowProcessor):
            return ext(app_ctx, names, params, compile_expr)
        return None

    if namespace:
        wp = _extension()
        if wp is None:
            raise SiddhiAppCreationError(
                f"Unknown window type '{namespace}:{name}'")
        return wp

    def const(i, default=None):
        if i >= len(params):
            return default
        p = params[i]
        if isinstance(p, Constant):
            return p.value
        raise SiddhiAppCreationError(
            f"window {name}: parameter {i} must be a constant")

    def time_ms(i, default=None):
        if i >= len(params):
            return default
        p = params[i]
        if isinstance(p, TimeConstant):
            return p.value
        if isinstance(p, Constant):
            return int(p.value)
        raise SiddhiAppCreationError(
            f"window {name}: parameter {i} must be a time constant")

    low = name.lower()
    if low == "length":
        return LengthWindowProcessor(app_ctx, names, int(const(0)))
    if low == "lengthbatch":
        return LengthBatchWindowProcessor(app_ctx, names, int(const(0)))
    if low == "time":
        return TimeWindowProcessor(app_ctx, names, time_ms(0))
    if low == "timebatch":
        return TimeBatchWindowProcessor(app_ctx, names, time_ms(0),
                                        const(1, None))
    if low == "timelength":
        return TimeLengthWindowProcessor(app_ctx, names, time_ms(0),
                                         int(const(1)))
    if low == "externaltime":
        return ExternalTimeWindowProcessor(app_ctx, names,
                                           compile_expr(params[0]),
                                           time_ms(1))
    if low == "externaltimebatch":
        return ExternalTimeBatchWindowProcessor(app_ctx, names,
                                                compile_expr(params[0]),
                                                time_ms(1), const(2, None))
    if low == "batch":
        return BatchWindowProcessor(app_ctx, names)
    if low == "session":
        key = compile_expr(params[1]) if len(params) > 1 else None
        return SessionWindowProcessor(app_ctx, names, time_ms(0), key)
    if low == "sort":
        n = int(const(0))
        keys: List[Tuple[CompiledExpr, bool]] = []
        i = 1
        while i < len(params):
            p = params[i]
            if isinstance(p, Constant) and isinstance(p.value, str) and \
                    p.value.lower() in ("asc", "desc"):
                if keys:
                    keys[-1] = (keys[-1][0], p.value.lower() == "asc")
            else:
                keys.append((compile_expr(p), True))
            i += 1
        return SortWindowProcessor(app_ctx, names, n, keys)
    if low == "frequent":
        key_exprs = [compile_expr(p) for p in params[1:]]
        return FrequentWindowProcessor(app_ctx, names, int(const(0)), key_exprs)
    if low == "lossyfrequent":
        support = float(const(0))
        error = float(const(1, support / 10.0))
        key_exprs = [compile_expr(p) for p in params[2:]]
        return LossyFrequentWindowProcessor(app_ctx, names, support, error,
                                            key_exprs)
    if low in ("hoping", "hopping"):
        return HopingWindowProcessor(app_ctx, names, time_ms(0), time_ms(1))
    if low == "delay":
        return DelayWindowProcessor(app_ctx, names, time_ms(0))
    if low == "cron":
        return CronWindowProcessor(app_ctx, names, str(const(0)))
    wp = _extension()
    if wp is not None:
        return wp
    raise SiddhiAppCreationError(f"Unknown window type '{name}'")
