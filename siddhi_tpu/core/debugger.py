"""Step-debugger over live streams.

(reference: core/debugger/SiddhiDebugger.java:37-213 — acquireBreakPoint on a
query's IN/OUT terminal blocks event threads there; next() steps to the next
terminal, play() runs to the next acquired breakpoint; getQueryState exposes
the query's live state — wired through ProcessStreamReceiver.receive
checks :103-106.)

Columnar twist: a breakpoint fires once per event *chunk* arriving at the
terminal; the callback receives the chunk's events.  `next()`/`play()` may be
called from the callback (synchronous stepping) or from another thread (the
blocked event thread resumes).
"""
from __future__ import annotations

import threading
from typing import Callable, Optional, Set, Tuple

from .event import EventChunk


class SiddhiDebugger:
    IN = "IN"
    OUT = "OUT"

    def __init__(self, app_runtime):
        self.app_runtime = app_runtime
        self._break_points: Set[Tuple[str, str]] = set()
        self._step_mode = False
        self._resume = threading.Event()
        self._resume.set()
        self._callback: Optional[Callable] = None
        self._enabled = True

    # ------------------------------------------------------------ control

    def acquire_break_point(self, query_name: str, terminal: str):
        self._break_points.add((query_name, terminal))

    def release_break_point(self, query_name: str, terminal: str):
        self._break_points.discard((query_name, terminal))

    def release_all_break_points(self):
        self._break_points.clear()

    def next(self):
        """Step: resume and break again at the very next terminal."""
        self._step_mode = True
        self._resume.set()

    def play(self):
        """Resume until the next acquired breakpoint."""
        self._step_mode = False
        self._resume.set()

    def set_debugger_callback(self, cb: Callable):
        """cb(events, query_name, terminal, debugger)"""
        self._callback = cb

    def get_query_state(self, query_name: str) -> dict:
        qr = self.app_runtime.query_runtimes.get(query_name)
        if qr is None:
            return {}
        return {eid: obj.current_state()
                for eid, obj in qr.stateful_elements()}

    def detach(self):
        self._enabled = False
        self._resume.set()

    # ------------------------------------------------------------ hook

    def check(self, query_name: str, terminal: str, chunk: EventChunk):
        """Called from query terminals on the event thread."""
        if not self._enabled:
            return
        if not (self._step_mode or
                (query_name, terminal) in self._break_points):
            return
        self._step_mode = False
        self._resume.clear()
        if self._callback is not None:
            self._callback(chunk.to_events(), query_name, terminal, self)
        self._resume.wait(timeout=60.0)
