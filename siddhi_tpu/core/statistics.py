"""Statistics / metrics subsystem.

(reference: util/statistics/** — Codahale metrics-core trackers behind
StatisticsManager / StatisticsTrackerFactory SPIs; throughput per junction,
latency per query, memory gauges; console/JMX reporters configured by
`@app:statistics(reporter='console', interval='5')`.)

Grown into a full metrics core (observability PR):

  * ``Histogram`` — log-bucketed HDR-style value recorder (32 sub-buckets
    per octave → ≤ ~6% relative error) with p50/p95/p99/max, the shape a
    p99-latency headline metric needs (BASELINE.json).
  * ``LatencyTracker`` — histogram-backed, safe under nesting and
    concurrent queries (per-thread mark stacks; the old single `_mark`
    field dropped legitimate 0-ns marks and let interleaved queries
    corrupt each other).
  * ``ThroughputTracker`` — lifetime AND windowed (since-last-snapshot)
    rates, so a reporter interval sees current load, not the lifetime
    average.
  * ``Counter`` / ``Gauge`` — label-carrying primitives for everything
    that isn't one of the four classic tracker kinds.
  * Prometheus/OpenMetrics text rendering (``prometheus_text``) consumed
    by the service's ``GET /metrics`` endpoint (service/rest.py).

Metric naming keeps the reference's
``io.siddhi.SiddhiApps.<app>.Siddhi.<kind>.<name>`` scheme internally;
the Prometheus renderer maps it onto ``siddhi_*{app=,kind=,name=}``
series.  Everything stays off the hot path when ``@app:statistics`` is
disabled: no trackers are registered at all (core/runtime.py wires them
only when enabled).
"""
from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .threads import engine_thread_name

# ------------------------------------------------------------------ histogram

_SUB_BITS = 5                    # 2^5 sub-buckets per octave
_SUB = 1 << _SUB_BITS            # values < 32 are exact
_HALF = _SUB >> 1


def _bucket_index(v: int) -> int:
    """Value → log-bucket index.  Exact below _SUB; above, one bucket per
    (octave, sub-bucket) pair — HDR-histogram math with 2^(1-_SUB_BITS)
    (~6%) worst-case relative error."""
    if v < _SUB:
        return v if v >= 0 else 0
    s = v.bit_length() - _SUB_BITS
    return _SUB + ((s - 1) << (_SUB_BITS - 1)) + ((v >> s) - _HALF)


def _bucket_bounds(idx: int) -> Tuple[int, int]:
    """Bucket index → half-open value range [lo, hi)."""
    if idx < _SUB:
        return idx, idx + 1
    s = ((idx - _SUB) >> (_SUB_BITS - 1)) + 1
    sub = (idx - _SUB) & (_HALF - 1)
    lo = (_HALF + sub) << s
    return lo, lo + (1 << s)


class Histogram:
    """Log-bucketed value recorder with percentile estimation.

    ``record`` is O(1) (a bit_length + one list increment); percentile
    reads walk the bucket array.  Thread-safe: records take a lock —
    callers record per *chunk*, not per event, so contention is nil.
    """

    __slots__ = ("counts", "count", "total", "min", "max", "_lock")

    def __init__(self):
        self.counts: List[int] = []
        self.count = 0
        self.total = 0
        self.min: Optional[int] = None
        self.max = 0
        self._lock = threading.Lock()

    def record(self, v: int) -> None:
        v = int(v)
        if v < 0:
            v = 0
        idx = _bucket_index(v)
        with self._lock:
            if idx >= len(self.counts):
                self.counts.extend([0] * (idx + 1 - len(self.counts)))
            self.counts[idx] += 1
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def percentile(self, q: float) -> float:
        """q in [0, 100] → bucket-midpoint estimate (≤ ~6% rel error)."""
        with self._lock:
            n = self.count
            if n == 0:
                return 0.0
            target = max(1, int(round(q / 100.0 * n)))
            cum = 0
            for idx, c in enumerate(self.counts):
                if not c:
                    continue
                cum += c
                if cum >= target:
                    lo, hi = _bucket_bounds(idx)
                    return (lo + hi - 1) / 2.0
            return float(self.max)

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def buckets(self) -> List[Tuple[int, int]]:
        """Non-empty (upper_bound, count) pairs in increasing order —
        feed for cumulative Prometheus ``_bucket`` series."""
        with self._lock:
            return [(_bucket_bounds(i)[1], c)
                    for i, c in enumerate(self.counts) if c]

    def summary(self, scale: float = 1.0) -> Dict[str, float]:
        return {"count": self.count,
                "mean": self.mean() * scale,
                "p50": self.percentile(50) * scale,
                "p95": self.percentile(95) * scale,
                "p99": self.percentile(99) * scale,
                "min": (self.min or 0) * scale,
                "max": self.max * scale}


# ------------------------------------------------------------------ trackers

class ThroughputTracker:
    __slots__ = ("name", "count", "_t0", "_win_count", "_win_t0")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self._t0 = time.time()
        self._win_count = 0
        self._win_t0 = self._t0

    def event_in(self, n: int = 1):
        self.count += n

    def rate(self) -> float:
        dt = time.time() - self._t0
        return self.count / dt if dt > 0 else 0.0

    def windowed_rate(self) -> float:
        """Rate since the previous ``windowed_rate`` call (the reporter
        interval), falling back to the lifetime rate on the first read."""
        now = time.time()
        dt = now - self._win_t0
        dn = self.count - self._win_count
        self._win_t0, self._win_count = now, self.count
        if dt <= 0:
            return 0.0
        return dn / dt


class LatencyTracker:
    """Histogram-backed latency tracker.

    Marks nest via a per-thread stack (``mark_in``/``mark_out`` pairs can
    recurse — e.g. a query feeding another query on the same thread — and
    concurrent queries on different threads never see each other's
    marks).  A 0-ns duration is recorded, not dropped."""

    __slots__ = ("name", "total_ns", "count", "hist", "_tls")

    def __init__(self, name: str):
        self.name = name
        self.total_ns = 0
        self.count = 0
        self.hist = Histogram()
        self._tls = threading.local()

    def mark_in(self):
        stack = getattr(self._tls, "marks", None)
        if stack is None:
            stack = self._tls.marks = []
        stack.append(time.perf_counter_ns())

    def mark_out(self):
        stack = getattr(self._tls, "marks", None)
        if not stack:
            return              # unmatched mark_out: ignore
        dt = time.perf_counter_ns() - stack.pop()
        self.total_ns += dt
        self.count += 1
        self.hist.record(dt)

    def avg_ms(self) -> float:
        return (self.total_ns / self.count) / 1e6 if self.count else 0.0

    def percentiles_ms(self) -> Dict[str, float]:
        return {"p50_ms": self.hist.percentile(50) / 1e6,
                "p95_ms": self.hist.percentile(95) / 1e6,
                "p99_ms": self.hist.percentile(99) / 1e6,
                "max_ms": self.hist.max / 1e6}


class MemoryTracker:
    """Gauge over registered state holders exposing `memory_bytes()`."""

    def __init__(self, name: str):
        self.name = name
        self._holders: List[Callable[[], int]] = []

    def register(self, fn: Callable[[], int]):
        self._holders.append(fn)

    def bytes(self) -> int:
        return sum(f() for f in self._holders)


class BufferedEventsTracker:
    """Queue-depth gauge over registered suppliers — wired to @Async
    junction queues (core/stream.py) so backpressure is visible before it
    becomes an @OnError drop."""

    def __init__(self, name: str):
        self.name = name
        self._suppliers: List[Callable[[], int]] = []

    def register(self, fn: Callable[[], int]):
        self._suppliers.append(fn)

    @property
    def buffered(self) -> int:
        total = 0
        for f in self._suppliers:
            try:
                total += int(f())
            except Exception:   # noqa: BLE001 — a dying junction reads as 0
                pass
        return total


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter with label support: ``c.inc(3, stream='S')``."""

    __slots__ = ("name", "_series", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._series: Dict[Tuple, int] = {}
        self._lock = threading.Lock()

    def inc(self, n: int = 1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> int:
        return self._series.get(_label_key(labels), 0)

    def series(self) -> Dict[Tuple, int]:
        return dict(self._series)


class Gauge:
    """Point-in-time value with label support; a labelset can also be
    bound to a supplier callable (read at snapshot time)."""

    __slots__ = ("name", "_series", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._series: Dict[Tuple, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels):
        with self._lock:
            self._series[_label_key(labels)] = lambda v=value: v

    def set_fn(self, fn: Callable[[], float], **labels):
        with self._lock:
            self._series[_label_key(labels)] = fn

    def value(self, **labels) -> float:
        fn = self._series.get(_label_key(labels))
        return float(fn()) if fn is not None else 0.0

    def series(self) -> Dict[Tuple, float]:
        out = {}
        for key, fn in list(self._series.items()):
            try:
                out[key] = float(fn())
            except Exception:   # noqa: BLE001 — supplier died with its owner
                out[key] = 0.0
        return out


# ------------------------------------------------------------------ manager

class StatisticsManager:
    """Registry + reporter.  Metric naming mirrors the reference:
    io.siddhi.SiddhiApps.<app>.Siddhi.<kind>.<name>
    (reference SiddhiAppRuntime.java:720-727)."""

    def __init__(self, app_name: str, reporter: str = "console",
                 interval_s: int = 60):
        self.app_name = app_name
        self.reporter = reporter
        self.interval_s = interval_s
        self.throughput: Dict[str, ThroughputTracker] = {}
        self.latency: Dict[str, LatencyTracker] = {}
        self.memory: Dict[str, MemoryTracker] = {}
        self.buffered: Dict[str, BufferedEventsTracker] = {}
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.enabled = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lifecycle_lock = threading.Lock()

    def _metric(self, kind: str, name: str) -> str:
        return f"io.siddhi.SiddhiApps.{self.app_name}.Siddhi.{kind}.{name}"

    def throughput_tracker(self, kind: str, name: str) -> ThroughputTracker:
        key = self._metric(kind, name)
        return self.throughput.setdefault(key, ThroughputTracker(key))

    def latency_tracker(self, kind: str, name: str) -> LatencyTracker:
        key = self._metric(kind, name)
        return self.latency.setdefault(key, LatencyTracker(key))

    def memory_tracker(self, kind: str, name: str) -> MemoryTracker:
        key = self._metric(kind, name)
        return self.memory.setdefault(key, MemoryTracker(key))

    def buffered_tracker(self, kind: str, name: str) -> BufferedEventsTracker:
        key = self._metric(kind, name)
        return self.buffered.setdefault(key, BufferedEventsTracker(key))

    def counter(self, kind: str, name: str) -> Counter:
        key = self._metric(kind, name)
        return self.counters.setdefault(key, Counter(key))

    def gauge(self, kind: str, name: str) -> Gauge:
        key = self._metric(kind, name)
        return self.gauges.setdefault(key, Gauge(key))

    def snapshot(self) -> dict:
        return {
            "throughput": {k: {"count": t.count, "rate_eps": t.rate(),
                               "rate_windowed_eps": t.windowed_rate()}
                           for k, t in self.throughput.items()},
            "latency_ms": {k: {"avg_ms": t.avg_ms(), "count": t.count,
                               **t.percentiles_ms()}
                           for k, t in self.latency.items()},
            "memory_bytes": {k: m.bytes() for k, m in self.memory.items()},
            "buffered": {k: b.buffered for k, b in self.buffered.items()},
            "counters": {k: {"|".join("=".join(p) for p in key) or "_": v
                             for key, v in c.series().items()}
                         for k, c in self.counters.items()},
            "gauges": {k: {"|".join("=".join(p) for p in key) or "_": v
                           for key, v in g.series().items()}
                       for k, g in self.gauges.items()},
        }

    # -------------------------------------------------------- prometheus

    def _parse_key(self, key: str) -> Dict[str, str]:
        """io.siddhi.SiddhiApps.<app>.Siddhi.<kind>.<name> → labels."""
        prefix = "io.siddhi.SiddhiApps."
        rest = key[len(prefix):] if key.startswith(prefix) else key
        app, sep, tail = rest.partition(".Siddhi.")
        if not sep:
            return {"app": self.app_name, "kind": "", "name": rest}
        kind, _, name = tail.partition(".")
        return {"app": app, "kind": kind, "name": name}

    def prometheus_lines(self) -> List[str]:
        lines: List[str] = []
        for key, t in self.throughput.items():
            lb = _fmt_labels(self._parse_key(key))
            lines.append(f"siddhi_throughput_events_total{lb} {t.count}")
            lines.append(
                f"siddhi_throughput_events_per_second{lb} {t.rate():.6g}")
        for key, t in self.latency.items():
            lb_map = self._parse_key(key)
            lb = _fmt_labels(lb_map)
            cum = 0
            for hi_ns, c in t.hist.buckets():
                cum += c
                le = hi_ns / 1e9
                lines.append("siddhi_latency_seconds_bucket"
                             f"{_fmt_labels(lb_map, le=f'{le:.9g}')} {cum}")
            lines.append("siddhi_latency_seconds_bucket"
                         f"{_fmt_labels(lb_map, le='+Inf')} {t.hist.count}")
            lines.append(
                f"siddhi_latency_seconds_sum{lb} {t.total_ns / 1e9:.9g}")
            lines.append(f"siddhi_latency_seconds_count{lb} {t.hist.count}")
        for key, m in self.memory.items():
            lb = _fmt_labels(self._parse_key(key))
            lines.append(f"siddhi_memory_bytes{lb} {m.bytes()}")
        for key, b in self.buffered.items():
            lb = _fmt_labels(self._parse_key(key))
            lines.append(f"siddhi_buffered_events{lb} {b.buffered}")
        for key, c in self.counters.items():
            base = self._parse_key(key)
            for lkey, v in c.series().items():
                lb = _fmt_labels({**base, **dict(lkey)})
                lines.append(f"siddhi_counter_total{lb} {v}")
        for key, g in self.gauges.items():
            base = self._parse_key(key)
            for lkey, v in g.series().items():
                lb = _fmt_labels({**base, **dict(lkey)})
                lines.append(f"siddhi_gauge{lb} {v:.9g}")
        return lines

    # ------------------------------------------------------------ lifecycle

    def start_reporting(self):
        self.enabled = True
        if self.reporter not in ("console", "json") or self.interval_s <= 0:
            return
        with self._lifecycle_lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()

            def loop():
                while not self._stop.wait(self.interval_s):
                    if self.enabled:
                        print(json.dumps({"siddhi_stats": self.snapshot()}),
                              file=sys.stderr)
            self._thread = threading.Thread(
                target=loop, daemon=True,
                name=engine_thread_name("siddhi-stats-reporter"))
            self._thread.start()

    def stop_reporting(self):
        self.enabled = False
        with self._lifecycle_lock:
            self._stop.set()
            t = self._thread
            if t is not None:
                # join, don't abandon: the old `_thread = None` without a
                # join let a racing start_reporting spawn a second
                # reporter while the first still printed
                t.join(timeout=5.0)
                self._thread = None


# ------------------------------------------------------------------ exposition

def _fmt_labels(labels: Dict[str, str], **extra) -> str:
    merged = {**labels, **extra}
    merged = {k: v for k, v in merged.items() if v != ""}
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(merged.items()))
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


_TYPES = [
    ("siddhi_throughput_events_total",
     "counter", "Events entering a stream junction"),
    ("siddhi_throughput_events_per_second",
     "gauge", "Lifetime event rate of a stream junction"),
    ("siddhi_latency_seconds",
     "histogram", "Per-query processing latency"),
    ("siddhi_memory_bytes", "gauge", "State-holder buffer footprint"),
    ("siddhi_buffered_events",
     "gauge", "Queued events in @Async junction buffers"),
    ("siddhi_counter_total", "counter", "App-defined counters"),
    ("siddhi_gauge", "gauge", "App-defined gauges"),
    ("siddhi_kernel_calls_total",
     "counter", "Device kernel invocations"),
    ("siddhi_kernel_compile_count",
     "gauge", "XLA compiles (incl. retraces) of a kernel"),
    ("siddhi_kernel_device_time_seconds_total",
     "gauge", "Blocked device time per kernel (profiling mode)"),
    ("siddhi_kernel_dispatch_time_seconds_total",
     "gauge", "Host-side dispatch time per kernel"),
    ("siddhi_kernel_h2d_bytes_total",
     "counter", "Host->device bytes fed to a kernel"),
    ("siddhi_kernel_d2h_bytes_total",
     "counter", "Device->host bytes retired from a kernel"),
    ("siddhi_kernel_batch_events_total",
     "counter", "Events carried through a kernel"),
    ("siddhi_kernel_dispatches_total",
     "counter", "Device executions launched by a kernel"),
    ("siddhi_kernel_scan_ticks_total",
     "counter", "lax.scan ticks executed inside a kernel"),
    ("siddhi_kernel_live_bytes",
     "gauge", "Live device-buffer bytes owned by a kernel"),
    ("siddhi_kernel_batch_b", "gauge", "Events folded per scan tick (B)"),
    ("siddhi_app_dispatches_per_block",
     "gauge", "Device dispatches per ingest block (running average)"),
]

#: Always-on host-rim accounting (core/profiling.RimStats): rendered on
#: every /metrics scrape regardless of @app:statistics — the zero-copy
#: columnar path is asserted against these counters.
RIM_TYPES = [
    ("siddhi_events_materialized_total",
     "counter", "Per-event Event objects built from columnar chunks"),
    ("siddhi_host_rim_seconds_total",
     "counter", "Host-rim wall time (ingress conversion + egress "
     "delivery)"),
]

#: Always-on per-stage latency ledger + lag watermarks + SLO engine
#: (core/ledger.py): rendered on every /metrics scrape regardless of
#: @app:statistics; SIDDHI_TPU_LEDGER=0 freezes the counters.
LEDGER_TYPES = [
    ("siddhi_ledger_stage_seconds_total",
     "counter", "Exclusive wall time attributed to a pipeline stage"),
    ("siddhi_ledger_stage_spans_total",
     "counter", "Ledger span exits per pipeline stage"),
    ("siddhi_ledger_stage_latency_ms",
     "gauge", "Per-app per-block stage latency quantiles (ms)"),
    ("siddhi_event_time_lag_ms",
     "gauge", "Max admitted event timestamp vs wall/playback clock"),
    ("siddhi_processing_lag_ms",
     "gauge", "Wall time since a stream last admitted a chunk"),
    ("siddhi_slo_burn_rate",
     "gauge", "Observed / target ratio per @app:slo objective"),
    ("siddhi_slo_breach_active",
     "gauge", "1 while an app's SLO breach is active"),
    ("siddhi_slo_breach_total",
     "counter", "SLO breach transitions (SLO001 incidents)"),
]

#: Opt-in on-device state telemetry (@app:statistics(telemetry='true')).
#: Accumulated in-kernel (ops/nfa.py, ops/dwin.py) and read out through
#: the fused-egress slab — see DeviceTelemetry.
TELEMETRY_TYPES = [
    ("siddhi_nfa_state_occupancy",
     "gauge", "Live NFA slot occupancy per automaton state"),
    ("siddhi_nfa_gate_pass_total",
     "counter", "Condition-gate passes per automaton state"),
    ("siddhi_nfa_gate_fail_total",
     "counter", "Condition-gate failures per automaton state"),
    ("siddhi_nfa_within_drops_total",
     "counter", "Partial matches expired by the within clause"),
    ("siddhi_dwin_ring_fill", "gauge", "Device window ring occupancy"),
    ("siddhi_dwin_evictions_total",
     "counter", "Events evicted/expired from a device window"),
    ("siddhi_dwin_overflow_total",
     "counter", "Device window ring overflow trips"),
]


#: Always-on process-level series: resident set, uptime, and Python GC
#: tallies.  The GC-amplification finding (egress allocation storms
#: triggering gen-2 collections) previously had no resident gauge to
#: correlate against — these render on every scrape, app stats or not.
PROCESS_TYPES = [
    ("siddhi_process_rss_bytes", "gauge",
     "Resident set size of the engine process"),
    ("siddhi_process_uptime_seconds", "gauge",
     "Seconds since this process imported the engine"),
    ("siddhi_gc_collections_total", "counter",
     "Python GC collections per generation"),
    ("siddhi_gc_collected_total", "counter",
     "Objects collected by the Python GC per generation"),
    ("siddhi_gc_uncollectable_total", "counter",
     "Uncollectable objects found by the Python GC per generation"),
]

_PROCESS_START = time.time()


def _rss_bytes() -> int:
    """Resident set in bytes: /proc/self/status VmRSS (kB) where it
    exists, else getrusage (Linux reports KiB there too)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:   # noqa: BLE001 — exotic platform: report zero
        return 0


def process_lines() -> List[str]:
    import gc
    lines = [f"siddhi_process_rss_bytes {_rss_bytes()}",
             "siddhi_process_uptime_seconds "
             f"{time.time() - _PROCESS_START:.3f}"]
    for gen, st in enumerate(gc.get_stats()):
        lb = f'{{generation="{gen}"}}'
        lines.append(f"siddhi_gc_collections_total{lb} "
                     f"{st.get('collections', 0)}")
        lines.append(f"siddhi_gc_collected_total{lb} "
                     f"{st.get('collected', 0)}")
        lines.append(f"siddhi_gc_uncollectable_total{lb} "
                     f"{st.get('uncollectable', 0)}")
    return lines


class DeviceTelemetry:
    """Host-side holder for the opt-in on-device telemetry blocks.

    NFA carries contribute a ``[P, 3S+1]`` int32 leaf per query
    (per-state occupancy gauge, cumulative gate pass/fail counts, within
    drops); device windows contribute ``[fill, evictions, overflow]``.
    The device runtimes push the latest host copy here on retire; REST
    ``/metrics``, ``rt.statistics`` and the flight ring read it out."""

    def __init__(self, app_name: str):
        self.app_name = app_name
        self._lock = threading.Lock()
        self._nfa: Dict[str, Dict[str, Any]] = {}
        self._windows: Dict[str, Dict[str, int]] = {}

    def update_nfa(self, query: str, telem, n_states: int,
                   unit_kinds=None) -> None:
        import numpy as np
        t = np.asarray(telem)
        if t.ndim == 2:             # [P, 3S+1] → totals across partitions
            t = t.sum(axis=0)
        S = int(n_states)
        with self._lock:
            self._nfa[query] = {
                "occupancy": [int(v) for v in t[:S]],
                "gate_pass": [int(v) for v in t[S:2 * S]],
                "gate_fail": [int(v) for v in t[2 * S:3 * S]],
                "within_drops": int(t[3 * S]),
                "state_kinds": list(unit_kinds or []),
            }

    def update_window(self, name: str, telem3) -> None:
        import numpy as np
        t = np.asarray(telem3).reshape(-1)
        with self._lock:
            self._windows[name] = {"fill": int(t[0]),
                                   "evictions": int(t[1]),
                                   "overflow": int(t[2])}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"nfa": {q: dict(v) for q, v in self._nfa.items()},
                    "windows": {w: dict(v)
                                for w, v in self._windows.items()}}

    def prometheus_lines(self) -> List[str]:
        lines: List[str] = []
        with self._lock:
            for query, rec in self._nfa.items():
                for i, occ in enumerate(rec["occupancy"]):
                    lb = _fmt_labels({"app": self.app_name, "query": query,
                                      "state": str(i)})
                    lines.append(f"siddhi_nfa_state_occupancy{lb} {occ}")
                for i, v in enumerate(rec["gate_pass"]):
                    lb = _fmt_labels({"app": self.app_name, "query": query,
                                      "state": str(i)})
                    lines.append(f"siddhi_nfa_gate_pass_total{lb} {v}")
                for i, v in enumerate(rec["gate_fail"]):
                    lb = _fmt_labels({"app": self.app_name, "query": query,
                                      "state": str(i)})
                    lines.append(f"siddhi_nfa_gate_fail_total{lb} {v}")
                lb = _fmt_labels({"app": self.app_name, "query": query})
                lines.append("siddhi_nfa_within_drops_total"
                             f"{lb} {rec['within_drops']}")
            for name, rec in self._windows.items():
                lb = _fmt_labels({"app": self.app_name, "window": name})
                lines.append(f"siddhi_dwin_ring_fill{lb} {rec['fill']}")
                lines.append("siddhi_dwin_evictions_total"
                             f"{lb} {rec['evictions']}")
                lines.append("siddhi_dwin_overflow_total"
                             f"{lb} {rec['overflow']}")
        return lines


def prometheus_text(managers: List[StatisticsManager],
                    kernel_profiler=None, resilience=None,
                    ingest=None, telemetry=None, tenants=None) -> str:
    """Full Prometheus/OpenMetrics text exposition over any number of app
    StatisticsManagers plus the (process-global) kernel profiler, the
    per-runtime ResilienceMetrics (core/resilience.py), the per-runtime
    IngestMetrics (core/overload.py) and the per-runtime DeviceTelemetry
    holders.  Every series family gets its # HELP/# TYPE header exactly
    once, before any samples."""
    from .ledger import ledger
    from .numguard import NUMERIC_TYPES, all_numeric_sentinels
    from .overload import INGEST_TYPES, TENANT_TYPES
    from .profiling import rim_stats
    from .resilience import RESILIENCE_TYPES
    from ..plan.xtenant import XTENANT_TYPES
    from ..plan.shapes import SHAPES_TYPES, shape_registry
    lines: List[str] = []
    for name, typ, help_ in (_TYPES + RIM_TYPES + LEDGER_TYPES +
                             TELEMETRY_TYPES + RESILIENCE_TYPES +
                             INGEST_TYPES + TENANT_TYPES + XTENANT_TYPES +
                             SHAPES_TYPES + NUMERIC_TYPES + PROCESS_TYPES):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {typ}")
    lines.extend(rim_stats().prometheus_lines())
    lines.extend(ledger().prometheus_lines())
    lines.extend(shape_registry().prometheus_lines())
    for ns in all_numeric_sentinels():
        # numeric sentinels (core/numguard.py, SIDDHI_TPU_NUMGUARD):
        # process-global registry like the flight recorder
        lines.extend(ns.prometheus_lines())
    lines.extend(process_lines())
    for sm in managers:
        lines.extend(sm.prometheus_lines())
    if kernel_profiler is not None:
        lines.extend(kernel_profiler.prometheus_lines())
    for rm in (resilience or []):
        lines.extend(rm.prometheus_lines())
    for im in (ingest or []):
        lines.extend(im.prometheus_lines())
    for dt in (telemetry or []):
        lines.extend(dt.prometheus_lines())
    for tn in (tenants or []):
        # fair-share quotas (overload.FairShare) and the cross-tenant
        # packer (plan/xtenant.TenantPacker): per-tenant / per-bucket
        lines.extend(tn.prometheus_lines())
    return "\n".join(lines) + "\n"
