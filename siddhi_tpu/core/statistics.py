"""Statistics / metrics subsystem.

(reference: util/statistics/** — Codahale metrics-core trackers behind
StatisticsManager / StatisticsTrackerFactory SPIs; throughput per junction,
latency per query, memory gauges; console/JMX reporters configured by
`@app:statistics(reporter='console', interval='5')`.)

Here: lightweight in-process counters with an optional periodic console/JSON
reporter thread.  The memory gauge reports numpy buffer footprints of
registered state holders instead of walking a Java object graph.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from typing import Callable, Dict, List, Optional


class ThroughputTracker:
    __slots__ = ("name", "count", "_t0")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self._t0 = time.time()

    def event_in(self, n: int = 1):
        self.count += n

    def rate(self) -> float:
        dt = time.time() - self._t0
        return self.count / dt if dt > 0 else 0.0


class LatencyTracker:
    __slots__ = ("name", "total_ns", "count", "_mark")

    def __init__(self, name: str):
        self.name = name
        self.total_ns = 0
        self.count = 0
        self._mark = 0

    def mark_in(self):
        self._mark = time.perf_counter_ns()

    def mark_out(self):
        if self._mark:
            self.total_ns += time.perf_counter_ns() - self._mark
            self.count += 1
            self._mark = 0

    def avg_ms(self) -> float:
        return (self.total_ns / self.count) / 1e6 if self.count else 0.0


class MemoryTracker:
    """Gauge over registered state holders exposing `memory_bytes()`."""

    def __init__(self, name: str):
        self.name = name
        self._holders: List[Callable[[], int]] = []

    def register(self, fn: Callable[[], int]):
        self._holders.append(fn)

    def bytes(self) -> int:
        return sum(f() for f in self._holders)


class BufferedEventsTracker:
    def __init__(self, name: str):
        self.name = name
        self.buffered = 0


class StatisticsManager:
    """Registry + reporter.  Metric naming mirrors the reference:
    io.siddhi.SiddhiApps.<app>.Siddhi.<kind>.<name>
    (reference SiddhiAppRuntime.java:720-727)."""

    def __init__(self, app_name: str, reporter: str = "console",
                 interval_s: int = 60):
        self.app_name = app_name
        self.reporter = reporter
        self.interval_s = interval_s
        self.throughput: Dict[str, ThroughputTracker] = {}
        self.latency: Dict[str, LatencyTracker] = {}
        self.memory: Dict[str, MemoryTracker] = {}
        self.buffered: Dict[str, BufferedEventsTracker] = {}
        self.enabled = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _metric(self, kind: str, name: str) -> str:
        return f"io.siddhi.SiddhiApps.{self.app_name}.Siddhi.{kind}.{name}"

    def throughput_tracker(self, kind: str, name: str) -> ThroughputTracker:
        key = self._metric(kind, name)
        return self.throughput.setdefault(key, ThroughputTracker(key))

    def latency_tracker(self, kind: str, name: str) -> LatencyTracker:
        key = self._metric(kind, name)
        return self.latency.setdefault(key, LatencyTracker(key))

    def memory_tracker(self, kind: str, name: str) -> MemoryTracker:
        key = self._metric(kind, name)
        return self.memory.setdefault(key, MemoryTracker(key))

    def buffered_tracker(self, kind: str, name: str) -> BufferedEventsTracker:
        key = self._metric(kind, name)
        return self.buffered.setdefault(key, BufferedEventsTracker(key))

    def snapshot(self) -> dict:
        return {
            "throughput": {k: {"count": t.count, "rate_eps": t.rate()}
                           for k, t in self.throughput.items()},
            "latency_ms": {k: t.avg_ms() for k, t in self.latency.items()},
            "memory_bytes": {k: m.bytes() for k, m in self.memory.items()},
            "buffered": {k: b.buffered for k, b in self.buffered.items()},
        }

    # ------------------------------------------------------------ lifecycle

    def start_reporting(self):
        self.enabled = True
        if self.reporter not in ("console", "json") or self.interval_s <= 0:
            return
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                if self.enabled:
                    print(json.dumps({"siddhi_stats": self.snapshot()}),
                          file=sys.stderr)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop_reporting(self):
        self.enabled = False
        self._stop.set()
        self._thread = None
