"""Per-chunk latency ledger, event-time lag watermarks and the SLO engine.

BENCH rounds report one end-to-end number (188 ms p99 match latency as of
round 11) with zero stage attribution.  This module generalizes the
round-11 ``rim_ns`` discipline — one always-on counter, kill-switchable,
overhead-bounded in ``bench --smoke`` — into a stage-bucketed wall-clock
ledger over the whole ingest→publish path:

  ingress     input-handler admit (validate/encode, before junction.send)
  queue       @Async buffer wait (enqueue → worker dequeue; 0 when sync)
  dispatch    junction fan-out + host-side query processing not otherwise
              attributed (exclusive of the nested stages below)
  device      device step issue + blocking retire waits (NFA dispatch,
              retire_events, window/group process_block, filter program)
  egress_d2h  the fused egress slab's single device→host read
  decode      columnar slab decode back into EventChunks
  publish     terminal callback / sink delivery

Stages are recorded through nest-aware spans: a span's *exclusive* time
(elapsed minus enclosed child spans) goes to its stage, so the per-stage
sums reconcile against an independently measured end-to-end wall clock
without double counting (``bench --phase waterfall`` asserts >= 95%
coverage).  Per-block deltas are folded into per-app/per-stage HDR
histograms (PR 1 machinery) and a ``ledger`` waterfall row on each flight
ring record — same global-accumulator-delta convention as the ring's
existing rim/kernel ms split.

On top of the ledger:

  * event-time lag watermarks: per-(app, stream) gauges of admitted-event
    timestamps vs the wall/playback clock
    (``siddhi_event_time_lag_ms`` / ``siddhi_processing_lag_ms``);
  * an SLO engine: ``@app:slo(latency.p99.ms=..., lag.ms=...)`` targets,
    per-app burn-rate gauges, ``/health`` degradation on sustained breach
    and an ``SLO001`` incident bundle through the flight bus carrying the
    breaching window's waterfall.

Always-on with a ``SIDDHI_TPU_LEDGER=0`` kill switch; the env is re-read
per call so the bench overhead phase can toggle it per block.  Like
``RimStats`` this is NOT gated on the profiler's ``enabled``.
"""
from __future__ import annotations

import os
import threading
import time

from collections import deque
from typing import Any, Dict, List, Optional

from .hotpath import hot_path
from .statistics import Histogram

LEDGER_ENV = "SIDDHI_TPU_LEDGER"

#: stage keys in pipeline order (waterfall rows and /stats render in this
#: order; see module docstring for the boundary definitions)
STAGES = ("ingress", "queue", "dispatch", "device", "egress_d2h",
          "decode", "publish")

_STAGE_SET = frozenset(STAGES)


# os.environ.get pays ~0.9 us per call (key encode + value decode);
# the ledger asks "am I on?" ~10x per ingest block, so that alone would
# eat a fifth of the < 5% overhead budget.  os._Environ keeps the live
# mapping in ``_data`` (mutated in place by os.environ[...] = ..., so
# per-block toggling still works); reading it directly is a plain dict
# get.  Fall back to the public API if the internals ever move.
_ENV_DATA = getattr(os.environ, "_data", None)
_LEDGER_KEY = (os.environ.encodekey(LEDGER_ENV)
               if _ENV_DATA is not None and hasattr(os.environ, "encodekey")
               else LEDGER_ENV)
if _ENV_DATA is not None and _LEDGER_KEY not in _ENV_DATA and \
        LEDGER_ENV in os.environ:
    _ENV_DATA = None        # key codec mismatch: use the public API

_PARSED: Dict[Any, bool] = {}       # raw env value -> parsed verdict


def ledger_enabled() -> bool:
    """Kill switch, re-read per call (same contract as flight_enabled):
    ``SIDDHI_TPU_LEDGER=0`` disables every stamp mid-process."""
    if _ENV_DATA is not None:
        raw = _ENV_DATA.get(_LEDGER_KEY)
    else:
        raw = os.environ.get(LEDGER_ENV)
    if raw is None:
        return True
    v = _PARSED.get(raw)
    if v is None:
        s = os.fsdecode(raw) if isinstance(raw, bytes) else raw
        v = s.strip().lower() not in ("0", "false", "off", "no")
        _PARSED[raw] = v
    return v


# --------------------------------------------------------------- SLO config


class SloConfig:
    """Targets from ``@app:slo(...)``, parsed tolerantly like the @Async
    overload options (bad values clamp to defaults with a log warning;
    the analyzer's SA07x diagnostics are where the author learns why)."""

    __slots__ = ("latency_p99_ms", "lag_ms", "window_blocks",
                 "breach_blocks")

    def __init__(self, latency_p99_ms: Optional[float] = None,
                 lag_ms: Optional[float] = None,
                 window_blocks: int = 128, breach_blocks: int = 3):
        if latency_p99_ms is not None and latency_p99_ms <= 0:
            latency_p99_ms = None
        if lag_ms is not None and lag_ms <= 0:
            lag_ms = None
        self.latency_p99_ms = latency_p99_ms
        self.lag_ms = lag_ms
        self.window_blocks = max(4, int(window_blocks))
        self.breach_blocks = max(1, int(breach_blocks))

    @staticmethod
    def from_annotation(ann) -> "SloConfig":
        def num(key, default):
            raw = ann.get(key, None)
            if raw is None:
                return default
            try:
                return float(raw)
            except (TypeError, ValueError):
                return default      # malformed: analyzer diagnostic SA070
        wb = num("window.blocks", 128.0)
        bb = num("breach.blocks", 3.0)
        return SloConfig(
            latency_p99_ms=num("latency.p99.ms", None),
            lag_ms=num("lag.ms", None),
            window_blocks=int(wb) if wb and wb > 0 else 128,
            breach_blocks=int(bb) if bb and bb > 0 else 3)

    def as_dict(self) -> Dict[str, Any]:
        return {"latency.p99.ms": self.latency_p99_ms,
                "lag.ms": self.lag_ms,
                "window.blocks": self.window_blocks,
                "breach.blocks": self.breach_blocks}


class _SloState:
    """Rolling evaluation state for one app's SLO.  A breach needs
    ``breach_blocks`` CONSECUTIVE over-target evaluations — one slow
    block is tail, a run of them is an incident (same philosophy as the
    dispatch-storm watchdog's sustained-window trip)."""

    __slots__ = ("config", "window", "consecutive", "breached",
                 "breach_total", "burn_latency", "burn_lag",
                 "observed_p99_ms", "blocks")

    def __init__(self, config: SloConfig):
        self.config = config
        self.window: "deque" = deque(maxlen=config.window_blocks)
        self.consecutive = 0
        self.breached = False
        self.breach_total = 0
        self.burn_latency = 0.0
        self.burn_lag = 0.0
        self.observed_p99_ms = 0.0
        self.blocks = 0

    def observe(self, total_ms: Optional[float],
                lag_ms: Optional[float]) -> bool:
        """One evaluation; returns True exactly on the transition into
        breach (the caller emits the SLO001 bundle then, once)."""
        cfg = self.config
        if total_ms is not None:
            self.window.append(total_ms)
            self.blocks += 1
        if cfg.latency_p99_ms and len(self.window) >= 4:
            ordered = sorted(self.window)
            self.observed_p99_ms = ordered[
                min(len(ordered) - 1, int(0.99 * len(ordered)))]
            self.burn_latency = self.observed_p99_ms / cfg.latency_p99_ms
        if cfg.lag_ms and lag_ms is not None:
            self.burn_lag = max(0.0, lag_ms) / cfg.lag_ms
        burn = max(self.burn_latency, self.burn_lag)
        if burn > 1.0:
            self.consecutive += 1
        else:
            self.consecutive = 0
            self.breached = False       # sustained recovery clears it
        if self.consecutive >= cfg.breach_blocks and not self.breached:
            self.breached = True
            self.breach_total += 1
            return True
        return False

    def as_dict(self) -> Dict[str, Any]:
        return {"config": self.config.as_dict(),
                "burn_rate": {"latency_p99": round(self.burn_latency, 4),
                              "lag": round(self.burn_lag, 4)},
                "observed_p99_ms": round(self.observed_p99_ms, 3),
                "window_blocks_observed": len(self.window),
                "consecutive_over_target": self.consecutive,
                "breached": self.breached,
                "breach_total": self.breach_total}


# ------------------------------------------------------------------ spans


_pcns = time.perf_counter_ns


class _Span:
    """Nest-aware stage span.  On exit the span's EXCLUSIVE time
    (elapsed minus enclosed child spans on this thread) is credited to
    its stage and its full elapsed time is charged to the parent's
    child accumulator — so ``sum(stage_ns)`` over a fully-spanned path
    equals the wall clock once, not once per nesting level.

    The hot path runs cold-cache right next to device dispatches, where
    every attribute chase costs real time — frames are plain two-int
    lists ``[t0, child_ns]`` on a thread-local stack, no per-frame
    object."""

    __slots__ = ("ledger", "stage", "frame", "stack")

    def __init__(self, ledger: "LatencyLedger", stage: str):
        self.ledger = ledger
        self.stage = stage

    def __enter__(self):
        if ledger_enabled():
            tls = self.ledger._tls
            st = getattr(tls, "stack", None)
            if st is None:
                st = tls.stack = []
            frame = [_pcns(), 0]
            st.append(frame)
            self.frame = frame
            self.stack = st
        else:
            self.frame = None
        return self

    def __exit__(self, *exc):
        frame = self.frame
        if frame is None:
            return False
        elapsed = _pcns() - frame[0]
        st = self.stack
        st.pop()
        if st:
            st[-1][1] += elapsed
        ns = elapsed - frame[1]
        led = self.ledger
        if ns > 0:
            led._ns[self.stage] += ns
        led._spans[self.stage] += 1
        return False


# ------------------------------------------------------------------ ledger


class LatencyLedger:
    """Process-global stage accumulators + per-app histograms + lag
    watermarks + SLO state.

    Hot-path writes are plain int adds under the GIL (the RimStats
    contract: exact single-threaded, monotone everywhere); dict creation
    for new (app, stage) keys is the only locked path."""

    #: per-app block deltas buffered before the histogram fold — the
    #: fold (6-8 locked Histogram.records) costs ~10x its isolated time
    #: right after a device block (cold caches), so the hot path only
    #: appends the integer deltas and the fold runs once per
    #: _FOLD_EVERY blocks / lazily on any read surface
    _FOLD_EVERY = 64

    def __init__(self):
        self._ns: Dict[str, int] = {s: 0 for s in STAGES}
        self._spans: Dict[str, int] = {s: 0 for s in STAGES}
        self._lock = threading.Lock()
        self._tls = threading.local()
        # (app, stage) -> Histogram of per-block stage ns; stage "total"
        # is the per-block all-stage sum (the e2e estimator SLOs burn on)
        self._hist: Dict[tuple, Histogram] = {}
        # app -> buffered per-block delta lists awaiting the fold
        self._pending: Dict[str, list] = {}
        # app -> the most recent block's stage deltas (waterfall row)
        self._last_deltas: Dict[str, list] = {}
        # (app, stream) -> lag watermark state
        self._lag: Dict[tuple, Dict[str, float]] = {}
        self._slo: Dict[str, _SloState] = {}

    # -------------------------------------------------------- hot path

    @property
    def enabled(self) -> bool:
        return ledger_enabled()

    def _tls_stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, stage: str) -> _Span:
        return _Span(self, stage)

    def record(self, stage: str, ns: int) -> None:
        """Credit ``ns`` of exclusive wall time to ``stage``."""
        if ns < 0:
            ns = 0
        self._ns[stage] += ns
        self._spans[stage] += 1

    def note_ingress(self, app: str, stream: str, event_ts_ms: int,
                     now_ms: float, dur_ns: int) -> None:
        """Per-chunk admit stamp: ingress stage time + the event-time lag
        watermark (max admitted event timestamp vs the wall clock — or
        the playback clock when the app replays history)."""
        self.record("ingress", dur_ns)
        ent = self._lag.get((app, stream))
        if ent is None:
            ent = self._lag[(app, stream)] = {}
        ent["event_ts_ms"] = float(event_ts_ms)
        ent["admit_wall_ms"] = time.time() * 1000.0
        ent["lag_ms"] = float(now_ms) - float(event_ts_ms)

    # ------------------------------------------------------ block fold

    def stage_ns(self) -> Dict[str, int]:
        return dict(self._ns)

    def _hist_for(self, app: str, stage: str) -> Histogram:
        h = self._hist.get((app, stage))
        if h is None:
            with self._lock:
                h = self._hist.setdefault((app, stage), Histogram())
        return h

    @hot_path("per-block stage-delta banking + SLO evaluation")
    def note_block(self, app: str, owner, runtime=None,
                   want_row: bool = True) -> Optional[Dict[str, float]]:
        """Bank one ingest block's stage deltas (global accumulators vs
        ``owner``'s last snapshot — the flight ring's rim/kernel-split
        convention), evaluate the app's SLO, and return the waterfall
        row for the flight record (only built when ``want_row``; the
        histogram fold is deferred — see ``_FOLD_EVERY``)."""
        if not ledger_enabled():
            return None
        ns = self._ns
        cur = [ns[s] for s in STAGES]
        prev = getattr(owner, "_ledger_ns0", None)
        owner._ledger_ns0 = cur
        if prev is None:
            return None
        deltas = [c - p if c > p else 0 for c, p in zip(cur, prev)]
        total_ns = sum(deltas)
        self._last_deltas[app] = deltas
        pend = self._pending.get(app)
        if pend is None:
            with self._lock:
                pend = self._pending.setdefault(app, [])
        pend.append(deltas)
        if len(pend) >= self._FOLD_EVERY:
            self._fold_pending(app)
        st = self._slo.get(app)
        if st is not None and st.observe(
                total_ns / 1e6 if total_ns > 0 else None,
                self._app_lag_ms(app)):
            self._emit_breach(app, st, runtime)
        if not want_row or total_ns <= 0:
            return None
        return self._row_ms(deltas)

    @staticmethod
    def _row_ms(deltas) -> Dict[str, float]:
        return {s: round(d / 1e6, 4)
                for s, d in zip(STAGES, deltas) if d > 0}

    def _fold_pending(self, app: Optional[str] = None) -> None:
        """Drain buffered block deltas into the per-app histograms
        (cold path: every read surface calls this first)."""
        apps = [app] if app is not None else list(self._pending)
        for a in apps:
            pend = self._pending.get(a)
            if not pend:
                continue
            drained = pend[:]
            del pend[:len(drained)]     # GIL-safe vs concurrent appends
            for deltas in drained:
                tot = 0
                for s, d in zip(STAGES, deltas):
                    if d > 0:
                        tot += d
                        self._hist_for(a, s).record(d)
                if tot > 0:
                    self._hist_for(a, "total").record(tot)

    def _app_lag_ms(self, app: str) -> Optional[float]:
        lags = [v["lag_ms"] for (a, _s), v in list(self._lag.items())
                if a == app]
        return max(lags) if lags else None

    def _emit_breach(self, app: str, st: _SloState, runtime) -> None:
        """SLO001 through the flight bus: the breach ships its own
        waterfall evidence (last block row + the per-stage histogram
        summaries of the breaching window)."""
        from .flight import flight
        try:
            flight().emit("slo_breach", app=app, detail={
                "code": "SLO001",
                "slo": st.config.as_dict(),
                "observed": st.as_dict(),
                "waterfall": self._row_ms(
                    self._last_deltas.get(app, [])),
                "stage_summary_ms": self._stage_summary(app),
            }, runtime=runtime)
        except Exception:   # noqa: BLE001 — SLO accounting must not raise
            pass

    # ----------------------------------------------------- SLO registry

    def register_slo(self, app: str, config: SloConfig) -> None:
        with self._lock:
            self._slo[app] = _SloState(config)

    def drop_app(self, app: str) -> None:
        """Forget one app's SLO + lag + histogram state (runtime
        shutdown; process-global stage counters are left alone)."""
        with self._lock:
            self._slo.pop(app, None)
            self._pending.pop(app, None)
            self._last_deltas.pop(app, None)
            for key in [k for k in self._lag if k[0] == app]:
                self._lag.pop(key, None)
            for key in [k for k in self._hist if k[0] == app]:
                self._hist.pop(key, None)

    def slo_breached(self, app: str) -> bool:
        st = self._slo.get(app)
        return bool(st is not None and st.breached)

    # ------------------------------------------------------- snapshots

    def _stage_summary(self, app: str) -> Dict[str, Dict[str, float]]:
        self._fold_pending(app)
        out: Dict[str, Dict[str, float]] = {}
        for stage in STAGES + ("total",):
            h = self._hist.get((app, stage))
            if h is not None and h.count:
                out[stage] = h.summary(scale=1e-6)      # ns -> ms
        return out

    def snapshot(self, app: Optional[str] = None) -> Dict[str, Any]:
        self._fold_pending()
        doc: Dict[str, Any] = {
            "enabled": ledger_enabled(),
            "stage_seconds": {s: self._ns[s] / 1e9 for s in STAGES},
            "stage_spans": dict(self._spans),
        }
        apps = sorted({a for (a, _s) in self._hist}
                      ) if app is None else [app]
        per_app = {}
        for a in apps:
            entry: Dict[str, Any] = {"stages_ms": self._stage_summary(a)}
            lags = {s: {"lag_ms": round(v["lag_ms"], 3),
                        "processing_lag_ms": round(
                            time.time() * 1000.0 - v["admit_wall_ms"], 3)}
                    for (aa, s), v in list(self._lag.items()) if aa == a}
            if lags:
                entry["lag"] = lags
            st = self._slo.get(a)
            if st is not None:
                entry["slo"] = st.as_dict()
            last = self._last_deltas.get(a)
            if last:
                entry["last_block_ms"] = self._row_ms(last)
            per_app[a] = entry
        doc["apps"] = per_app
        return doc

    def prometheus_lines(self) -> List[str]:
        from .statistics import _fmt_labels
        self._fold_pending()
        lines: List[str] = []
        for stage in STAGES:
            lab = _fmt_labels({"stage": stage})
            lines.append(f"siddhi_ledger_stage_seconds_total{lab} "
                         f"{self._ns[stage] / 1e9:.9g}")
            lines.append(f"siddhi_ledger_stage_spans_total{lab} "
                         f"{self._spans[stage]}")
        for (app, stage), h in sorted(self._hist.items()):
            if not h.count:
                continue
            s = h.summary(scale=1e-6)
            for q in ("p50", "p99"):
                lab = _fmt_labels({"app": app, "stage": stage, "q": q})
                lines.append(
                    f"siddhi_ledger_stage_latency_ms{lab} {s[q]:.6g}")
        now_ms = time.time() * 1000.0
        for (app, stream), v in sorted(self._lag.items()):
            lab = _fmt_labels({"app": app, "stream": stream})
            lines.append(f"siddhi_event_time_lag_ms{lab} "
                         f"{v['lag_ms']:.6g}")
            lines.append(f"siddhi_processing_lag_ms{lab} "
                         f"{now_ms - v['admit_wall_ms']:.6g}")
        for app, st in sorted(self._slo.items()):
            for slo_kind, burn in (("latency_p99", st.burn_latency),
                                   ("lag", st.burn_lag)):
                lab = _fmt_labels({"app": app, "slo": slo_kind})
                lines.append(f"siddhi_slo_burn_rate{lab} {burn:.6g}")
            lab = _fmt_labels({"app": app})
            lines.append(f"siddhi_slo_breach_active{lab} "
                         f"{1 if st.breached else 0}")
            lines.append(f"siddhi_slo_breach_total{lab} {st.breach_total}")
        return lines

    def reset(self) -> None:
        """Test/bench isolation (mirrors flight().reset())."""
        with self._lock:
            for s in STAGES:
                self._ns[s] = 0
                self._spans[s] = 0
            self._hist.clear()
            self._pending.clear()
            self._last_deltas.clear()
            self._lag.clear()
            self._slo.clear()


_GLOBAL = LatencyLedger()


def ledger() -> LatencyLedger:
    return _GLOBAL
