"""Span tracing — Chrome trace-event JSON export (Perfetto-loadable).

Lightweight spans around the engine's pipeline stages:

    parse → plan → jit-compile → ingest chunk → kernel step →
    match scatter → callback

Dapper-style: each span is one complete ("ph": "X") trace event with
microsecond ``ts``/``dur``, the thread id as ``tid`` and the span's
payload (stream id, batch size, …) in ``args``.  Export with
``SiddhiAppRuntime.dump_trace(path)`` and load the file in Perfetto /
chrome://tracing.

Off by default: ``span()`` returns a shared no-op context manager when
disabled (no allocation, no clock read), so the hot path pays a single
attribute check per chunk.  The tracer is process-global for the same
reason the kernel profiler is — compiled plan objects outlive and
predate individual app runtimes.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = time.perf_counter_ns()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        tr = self.tracer
        ev = {"name": self.name, "cat": self.cat, "ph": "X",
              "ts": (self._t0 - tr._epoch) / 1e3,
              "dur": (t1 - self._t0) / 1e3,
              "pid": tr.pid, "tid": threading.get_ident()}
        if self.args:
            ev["args"] = self.args
        with tr._lock:
            tr._events.append(ev)
            if len(tr._events) > tr.max_events:
                # bound memory: drop the oldest half
                del tr._events[:len(tr._events) // 2]
        return False


class Tracer:
    def __init__(self, pid: int = 0, max_events: int = 500_000):
        self.enabled = False
        self.pid = pid
        self.max_events = max_events
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._epoch = time.perf_counter_ns()

    # ------------------------------------------------------------ control

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._events.clear()

    # ------------------------------------------------------------ recording

    def span(self, name: str, cat: str = "engine", **args):
        """``with tracer.span("ingest.chunk", stream="S", n=1024): ...``"""
        if not self.enabled:
            return _NULL
        return _Span(self, name, cat, args or None)

    def complete(self, name: str, t0_ns: int, t1_ns: int,
                 cat: str = "engine", **args):
        """Record an already-measured interval (perf_counter_ns pair) —
        used by the kernel profiler so a profiled call shows up as a
        span without a second clock read."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": (t0_ns - self._epoch) / 1e3,
              "dur": (t1_ns - t0_ns) / 1e3,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, cat: str = "engine", **args):
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": (time.perf_counter_ns() - self._epoch) / 1e3,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def counter(self, name: str, value: float, cat: str = "engine"):
        if not self.enabled:
            return
        with self._lock:
            self._events.append(
                {"name": name, "cat": cat, "ph": "C",
                 "ts": (time.perf_counter_ns() - self._epoch) / 1e3,
                 "pid": self.pid, "tid": 0, "args": {"value": value}})

    # ------------------------------------------------------------ export

    def to_dict(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """Chrome-trace document.  ``limit`` keeps only the newest N
        events — incident bundles embed the trace, and a full buffer
        (up to 500k events) would dwarf everything else in the dump."""
        with self._lock:
            if limit is not None and len(self._events) > limit:
                events = list(self._events)[-limit:]
            else:
                events = list(self._events)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"engine": "siddhi_tpu"}}

    def export(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path


_GLOBAL = Tracer()


def tracer() -> Tracer:
    return _GLOBAL


def trace_span(name: str, cat: str = "engine", **args):
    """Module-level shortcut bound to the process-global tracer."""
    t = _GLOBAL
    if not t.enabled:
        return _NULL
    return _Span(t, name, cat, args or None)


def enable_tracing():
    _GLOBAL.enable()


def disable_tracing():
    _GLOBAL.disable()
