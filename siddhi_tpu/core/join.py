"""Join runtime: windowed stream-stream, stream-table, stream-window and
stream-aggregation joins.

TPU-shaped design: instead of the reference's per-event `find()` probe with a
compiled condition walked over a linked buffer (query/input/stream/join/
JoinProcessor.java:36-122, JoinInputStreamParser.java), an arriving micro-batch
is joined against the opposite buffer as one vectorised cross-product mask —
n×m condition evaluation in a single fused column program.

Semantics mirrored from the reference:
  - arriving CURRENT events probe the opposite window and emit joined CURRENT
    rows; events expiring from a window probe and emit joined EXPIRED rows
    (docs/siddhi-architecture.md:286-289)
  - `unidirectional` restricts which side triggers output (EventTrigger)
  - left/right/full outer joins emit null-padded rows for non-matching
    arrivals (JoinProcessor + OuterJoinMatcher)
  - a side without a #window holds no buffer: its events join only at their
    own arrival instant (reference empty-window behaviour)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx, Scope
from ..query_api import (EventTrigger, Filter, JoinInputStream, JoinType,
                         StreamFunctionHandler, WindowHandler)
from ..query_api.definition import Attribute, StreamDefinition
from ..utils.errors import SiddhiAppCreationError
from ..query_api.expression import expr_children
from .event import CURRENT, EXPIRED, TIMER, EventChunk
from .processor import Processor
from .window import WindowProcessor, create_window_processor


class _Collector(Processor):
    """Captures a window processor's output chunk (current + expired)."""

    def __init__(self):
        super().__init__()
        self.collected: List[EventChunk] = []

    def process(self, chunk: EventChunk):
        self.collected.append(chunk)

    def drain(self) -> List[EventChunk]:
        out, self.collected = self.collected, []
        return out


class JoinSide:
    """One side of the join: its definition, filter, buffer and aliases."""

    def __init__(self, runtime: "JoinRuntime", stream, factory, side: str):
        self.runtime = runtime
        self.side = side
        self.stream_id = stream.stream_id
        self.ref = stream.stream_ref or stream.stream_id
        app = runtime.qr.app_runtime
        self.is_table = app.has_table(stream.stream_id)
        self.is_named_window = app.has_named_window(stream.stream_id)
        self.is_aggregation = stream.stream_id in app.aggregations
        self.definition = app.definition_of(stream.stream_id)
        if self.is_aggregation:
            self.definition = app.aggregations[
                stream.stream_id].output_definition

        scope = Scope()
        scope.add_primary(self.stream_id, self.ref, self.definition)
        compiler = factory(scope)
        self.filters: List[CompiledExpr] = []
        self.window: Optional[WindowProcessor] = None
        self.collector = _Collector()
        for h in stream.handlers:
            if isinstance(h, Filter):
                self.filters.append(compiler.compile(h.expr))
            elif isinstance(h, WindowHandler):
                if self.is_table or self.is_named_window or \
                        self.is_aggregation:
                    raise SiddhiAppCreationError(
                        f"'{self.stream_id}' is not a stream: windows are "
                        f"not allowed on table/window/aggregation join sides")
                self.window = create_window_processor(
                    h.name, h.params, app.app_ctx,
                    self.definition.attribute_names,
                    lambda e: compiler.compile(e),
                    namespace=h.namespace or "",
                    extension_registry=app.extension_registry)
                self.window.lock = runtime.qr.lock
                self.window.next = self.collector
            elif isinstance(h, StreamFunctionHandler):
                raise SiddhiAppCreationError(
                    "stream functions on join sides are not supported yet")

    def apply_filters(self, chunk: EventChunk) -> EventChunk:
        for f in self.filters:
            n = len(chunk)
            if n == 0:
                break
            ctx = EvalCtx(chunk.columns, chunk.timestamps, n)
            m = np.asarray(f.fn(ctx), bool)
            if m.ndim == 0:
                m = np.full(n, bool(m))
            chunk = chunk.mask(m | (chunk.types == TIMER))
        return chunk

    def buffer_chunk(self) -> Optional[EventChunk]:
        """Opposite-side probe target (reference FindableProcessor.find)."""
        app = self.runtime.qr.app_runtime
        if self.is_table:
            return app.table_of(self.stream_id).all_rows_chunk()
        if self.is_named_window:
            return app.named_window_of(self.stream_id).find_chunk()
        if self.window is not None:
            return self.window.find_chunk()
        return None  # windowless stream side: nothing buffered


class _JoinReceiver:
    def __init__(self, runtime: "JoinRuntime", side: JoinSide):
        self.runtime = runtime
        self.side = side

    def receive_chunk(self, chunk: EventChunk):
        self.runtime.on_arrival(self.side, chunk)



class JoinRuntime:
    def __init__(self, qr, jis: JoinInputStream, factory):
        self.qr = qr
        self.jis = jis
        app = qr.app_runtime
        self.left = JoinSide(self, jis.left, factory, "left")
        self.right = JoinSide(self, jis.right, factory, "right")
        if self.left.is_aggregation or self.right.is_aggregation:
            agg_side = self.left if self.left.is_aggregation else self.right
            self.agg_runtime = app.aggregations[agg_side.stream_id]
        else:
            self.agg_runtime = None
        from ..query_api.expression import Variable
        probes = list(jis.within) if isinstance(jis.within, (tuple, list)) \
            else [jis.within]
        self._agg_per_row = any(isinstance(p, Variable)
                                for p in probes + [jis.per] if p is not None)
        self.join_type = jis.join_type
        self.trigger = jis.trigger

        # joined scope: both sides qualified + unique attrs unqualified
        scope = Scope()
        union_attrs: List[Attribute] = []
        seen: Dict[str, str] = {}
        for side in (self.left, self.right):
            for a in side.definition.attributes:
                def g(ctx, _r=side.ref, _a=a.name):
                    return ctx.qualified[(_r, 0)][_a]
                scope.add(side.ref, a.name, a.type, g)
                if side.stream_id != side.ref:
                    scope.add(side.stream_id, a.name, a.type, g)
                if a.name not in seen:
                    seen[a.name] = side.ref
                    union_attrs.append(a)
                    scope.add(None, a.name, a.type, g)
        self.union_def = StreamDefinition("__join", union_attrs)

        self.on: Optional[CompiledExpr] = None
        if jis.on is not None:
            self.on = factory(scope).compile(jis.on)

        # table sides: precompile the `on` condition as a table probe so
        # PK / @Index hash lookups replace the O(n*m) cross product
        # (reference JoinInputStreamParser compiles the condition against
        # the opposite FindableProcessor for exactly this reason)
        self._table_conds: Dict[str, object] = {}
        for tside, pside in ((self.left, self.right),
                             (self.right, self.left)):
            if not tside.is_table or jis.on is None:
                continue
            if pside.is_table or pside.is_named_window or \
                    pside.is_aggregation:
                continue
            # unqualified attrs present on BOTH sides bind to the left in
            # the joined scope but to the table in probe scope — ambiguous,
            # keep the cross product
            from ..query_api.expression import variables_of
            both = {a.name for a in tside.definition.attributes} & \
                   {a.name for a in pside.definition.attributes}
            if any(v.stream_id is None and v.attribute in both
                   for v in variables_of(jis.on)):
                continue
            try:
                from copy import copy as _copy
                sd = _copy(pside.definition)
                if pside.ref != sd.id:
                    sd.source_alias = pside.ref
                table = app.table_of(tside.stream_id)
                cc = table.compile_condition(jis.on, sd, factory)
                if cc.pk_probe is not None or cc.index_probe is not None:
                    self._table_conds[tside.side] = cc
                elif getattr(cc, "root", None) is not None:
                    # record table (core/record_table.py): the condition
                    # translated to the store-neutral IR — probe natively
                    self._table_conds[tside.side] = cc
            except Exception:  # noqa: BLE001 — any shape issue → cross path
                pass

        # device probe (VERDICT r2 next #7): the `on` condition over the
        # arriving-chunk × buffer cross product — the reference's per-event
        # JoinProcessor.find() hot loop (JoinProcessor.java:36-122) — as
        # one [n, m] broadcast program on the device.  Built when the
        # condition compiles under jnp over numeric attributes; DOUBLE
        # attributes are excluded (f32 lanes would flip borderline
        # compares vs the host's float64) and INT/LONG columns are
        # range-guarded per probe (2^24 f32 exactness).  Falls back to the
        # host numpy mask with self.device_probe_reason recorded.  When a
        # PK/@Index hash probe exists, the host O(1) lookup wins — the
        # device brute-force cross is for non-indexable conditions.
        self.device_probe = None
        self.device_probe_reason: Optional[str] = None
        from ..plan.planner import engine_mode
        app_obj = getattr(app, "app", None)
        mode = engine_mode(app_obj) if app_obj is not None else "host"
        if mode == "host":
            self.device_probe_reason = (
                "device join probe: engine mode 'host'"
                if app_obj is not None
                else "device join probe: inside host partition clone")
        elif jis.on is None:
            self.device_probe_reason = \
                "device join probe: no on-condition (pure cross product)"
        elif self._table_conds:
            self.device_probe_reason = \
                "device join probe: PK/@Index hash probe is faster on host"
        elif self.agg_runtime is not None:
            self.device_probe_reason = \
                "device join probe: aggregation sides are host-only"
        else:
            self._try_build_device_probe(jis, scope)

        qr._finish_chain([], scope, self.union_def, factory)
        self.head = qr._chain_head([])

        # subscribe both sides (self-join: two receivers on one junction);
        # a named-window side subscribes to the shared window itself — its
        # published CURRENT/EXPIRED events trigger the join exactly like
        # the reference's Window.java feeding downstream JoinProcessors
        for side, s in ((self.left, jis.left), (self.right, jis.right)):
            if side.is_table or side.is_aggregation:
                continue
            recv = _JoinReceiver(self, side)
            if side.is_named_window:
                app.named_window_of(s.stream_id).subscribe(recv)
            else:
                junction = app.junction_of(s.stream_id, s.is_inner,
                                           s.is_fault)
                junction.subscribe(recv)
            qr.receivers[f"{side.side}:{s.stream_id}"] = recv

    @property
    def windows(self) -> List[WindowProcessor]:
        return [w for w in (self.left.window, self.right.window)
                if w is not None]

    # ------------------------------------------------------- device probe

    def _try_build_device_probe(self, jis, scope) -> None:
        from ..query_api.definition import AttrType
        from ..query_api.expression import variables_of
        from ..plan.expr_compiler import ExprCompiler as _EC

        from ..query_api.expression import MathExpr

        def _fail(reason):
            self.device_probe_reason = "device join probe: " + reason

        # timestamp functions would read a zeros placeholder in the probe
        # ctx — the sibling device paths reject them the same way
        from ..plan.planner import _is_time_fn, _scan_fns
        if _scan_fns(jis.on, _is_time_fn):
            return _fail("timestamp functions need int64 host evaluation")

        types = {}
        for s in (self.left, self.right):
            for a in s.definition.attributes:
                types.setdefault((s.ref, a.name), a.type)
                types.setdefault((s.stream_id, a.name), a.type)
                types.setdefault((None, a.name), a.type)

        # STRING compares (equality AND order, var-vs-var/var-vs-const)
        # and exact DOUBLE compares rewrite onto per-probe lanes —
        # order-preserving rank codes / monotone 64-bit keys split into
        # i32 pairs (round 5, plan/join_lanes.py)
        from ..plan.join_lanes import JoinLanes, JoinRewriteError
        jl = JoinLanes(types)
        try:
            dev_cond = jl.rewrite(jis.on)
        except JoinRewriteError as ve:
            return _fail(str(ve))
        self._jlanes = jl

        # INT/LONG variables are range-guarded per column (2^24), but
        # arithmetic ON them (L.id * R.id) can leave the exact range even
        # when the columns are inside it — reject at build
        def int_in_math(e, inside=False) -> bool:
            from ..query_api.expression import Variable as _V
            if isinstance(e, _V) and inside and \
                    types.get((e.stream_id, e.attribute)) in \
                    (AttrType.INT, AttrType.LONG):
                return True
            inside = inside or isinstance(e, MathExpr)
            return any(int_in_math(x, inside) for x in expr_children(e))
        if int_in_math(jis.on):
            return _fail("arithmetic on INT/LONG attributes can leave the "
                         "f32 exact-integer range")

        for v in variables_of(jis.on):
            t = types.get((v.stream_id, v.attribute))
            if t is None:
                continue            # resolution errors surface on host
            if t == AttrType.OBJECT:
                return _fail(f"non-numeric attribute '{v.attribute}'")
        try:
            import jax
            import jax.numpy as jnp
            # device scope: numeric attrs mirror the joined scope's
            # wiring; string/double attrs never reach the program raw —
            # the rewritten condition reads their per-probe lanes (exact
            # i32 columns)
            lane_map = jl.lane_map()
            dev_scope = Scope()
            seen_u: set = set()
            for s in (self.left, self.right):
                side_attrs = {a.name for a in s.definition.attributes}
                entries = [(a.name, a.type)
                           for a in s.definition.attributes
                           if a.type not in (AttrType.STRING,
                                             AttrType.DOUBLE,
                                             AttrType.OBJECT)]
                entries += [(lane, AttrType.INT)
                            for (lane, src) in lane_map
                            if src is None or src in side_attrs]
                for name, t in entries:
                    def g(ctx, _r=s.ref, _a=name):
                        return ctx.qualified[(_r, 0)][_a]
                    dev_scope.add(s.ref, name, t, g)
                    if s.stream_id != s.ref:
                        dev_scope.add(s.stream_id, name, t, g)
                    if name not in seen_u:
                        seen_u.add(name)
                        dev_scope.add(None, name, t, g)
            dev_on = _EC(dev_scope, jnp).compile(dev_cond)

            refs = []
            for s in (self.left, self.right):
                side_attrs = {a.name for a in s.definition.attributes}
                names = [a.name for a in s.definition.attributes
                         if a.type not in (AttrType.STRING,
                                           AttrType.DOUBLE,
                                           AttrType.OBJECT)]
                names += [lane for (lane, src) in lane_map
                          if src is None or src in side_attrs]
                keys = [s.ref] + ([s.stream_id]
                                  if s.stream_id != s.ref else [])
                refs.append((keys, names))

            def probe(lcols, rcols, lvalid, rvalid, cap):
                q = {}
                for (keys, names), cols, expand in (
                        (refs[0], lcols, 0), (refs[1], rcols, 1)):
                    cc = {a: (cols[a][:, None] if expand == 0
                              else cols[a][None, :]) for a in names
                          if a in cols}
                    for k in keys:
                        q[(k, 0)] = cc
                n = lvalid.shape[0] * rvalid.shape[0]
                ctx = EvalCtx({}, jnp.zeros((1,), jnp.int32), n,
                              qualified=q)
                m = jnp.asarray(dev_on.fn(ctx), bool)
                m = jnp.broadcast_to(m, (lvalid.shape[0],
                                         rvalid.shape[0]))
                m = m & lvalid[:, None] & rvalid[None, :]
                flat = m.reshape(-1)
                # device-side compaction: shipping the full [n, m] mask
                # through a remote tunnel costs ~n*m bytes; the first-cap
                # matching pair indices (row-major == host emission
                # order) + the true count cost ~cap
                (idx,) = jnp.nonzero(flat, size=cap, fill_value=-1)
                return idx.astype(jnp.int32), \
                    jnp.sum(flat.astype(jnp.int32))

            from ..plan.shapes import shape_registry
            self._probe_jit = shape_registry().jit(
                "join.probe",
                {"lcols": len(refs[0][1]), "rcols": len(refs[1][1])},
                probe, static_argnums=4)
            self._probe_cap = 4096
            # warm trace at [1, 1] so untraceable conditions (functions,
            # scripts, table membership) reject at build time
            warm = {}
            for (_keys, names), s in ((refs[0], self.left),
                                      (refs[1], self.right)):
                warm[s.side] = {
                    nm: jnp.zeros((1,), jnp.int32 if nm.startswith("__")
                                  else jnp.float32)
                    for nm in names}
            self._probe_jit(warm["left"], warm["right"],
                            jnp.zeros((1,), bool), jnp.zeros((1,), bool),
                            4)
            self.device_probe = probe
            # build-time constants of the probe hot path: raw columns the
            # lane encode replaces (strings/doubles) or that never feed
            # the program (objects)
            self._probe_skip = {
                s.side: {a.name for a in s.definition.attributes
                         if a.type in (AttrType.STRING, AttrType.DOUBLE,
                                       AttrType.OBJECT)}
                for s in (self.left, self.right)}
            # condition-referenced attrs per definition: a referenced
            # column that arrives object-typed (outer-join nulls upstream)
            # must force the host mask, not vanish from the feed
            self._cond_attrs = {v.attribute for v in variables_of(jis.on)}
            self._int24 = [
                (s.side, a.name)
                for s in (self.left, self.right)
                for a in s.definition.attributes
                if a.type in (AttrType.INT, AttrType.LONG)]
        except Exception as e:  # noqa: BLE001 — any trace failure → host
            _fail(f"condition not device-traceable ({e})")

    def _device_pairs(self, side: JoinSide, data: EventChunk,
                      buf: EventChunk):
        """(sel_data, sel_buf) matching-pair indices in host emission
        order via the device probe, or None when a runtime guard (int
        2^24 exactness) demands the host path."""
        import jax.numpy as jnp
        left_first = side.side == "left"
        chunks = {"left": data if left_first else buf,
                  "right": buf if left_first else data}
        skip = self._probe_skip
        cols = {}
        for sd, c in chunks.items():
            cc = {}
            for a in c.names:
                if a in skip[sd]:
                    continue           # lanes carry strings/doubles
                col = c.columns[a]
                if col.dtype == object:
                    if a in self._cond_attrs:
                        # a numeric column promoted to object (nulls
                        # from an upstream outer join): host mask owns
                        # null-compare semantics
                        return None
                    continue
                if (sd, a) in getattr(self, "_int24", ()) and len(col) \
                        and np.abs(np.asarray(col, np.int64)).max() >= \
                        (1 << 24):
                    return None     # would round on f32 lanes
                cc[a] = jnp.asarray(np.asarray(col, np.float32))
            cols[sd] = cc
        if self._jlanes.any:
            enc = self._jlanes.encode(
                chunks["left"].columns, len(chunks["left"]),
                chunks["right"].columns, len(chunks["right"]))
            if enc is None:
                return None     # null strings / NaN doubles → host mask
            for sd, lanes in (("left", enc[0]), ("right", enc[1])):
                for name, arr in lanes.items():
                    cols[sd][name] = jnp.asarray(arr)
        nl, nr = len(chunks["left"]), len(chunks["right"])
        # pow2 padding caps retraces at log(max shape) per axis — sliding
        # buffers grow one event at a time, and an XLA compile per
        # distinct (n, m) would dwarf the probe
        nl2 = 1 << max(nl - 1, 0).bit_length()
        nr2 = 1 << max(nr - 1, 0).bit_length()
        if nl2 != nl or nr2 != nr:
            for sd, want in (("left", nl2), ("right", nr2)):
                cols[sd] = {a: jnp.concatenate(
                    [v, jnp.zeros((want - v.shape[0],), v.dtype)])
                    if v.shape[0] != want else v
                    for a, v in cols[sd].items()}
        lv = jnp.asarray(np.arange(nl2) < nl)
        rv = jnp.asarray(np.arange(nr2) < nr)
        while True:
            idx, count = self._probe_jit(cols["left"], cols["right"],
                                         lv, rv, self._probe_cap)
            count = int(count)
            if count <= self._probe_cap:
                break
            # overflow: grow the compaction buffer (new static cap → one
            # retrace) and re-run — results stay exact
            cap = self._probe_cap
            while cap < count:
                cap *= 2
            self._probe_cap = cap
        idx = np.asarray(idx[:count], np.int64)
        li, rj = idx // nr2, idx % nr2
        if not left_first:
            li, rj = rj, li
            order = np.lexsort((rj, li))    # host order: data-major
            li, rj = li[order], rj[order]
        return li, rj

    # ------------------------------------------------------------ event flow

    def on_arrival(self, side: JoinSide, chunk: EventChunk):
        with self.qr.lock:
            opposite = self.right if side.side == "left" else self.left
            chunk = side.apply_filters(chunk)
            if chunk.is_empty:
                return
            data = chunk.only(CURRENT)
            triggers = (self.trigger == EventTrigger.ALL or
                        (self.trigger == EventTrigger.LEFT and
                         side.side == "left") or
                        (self.trigger == EventTrigger.RIGHT and
                         side.side == "right"))
            # 1. arriving CURRENT events probe the opposite buffer
            if triggers and not data.is_empty:
                self._probe_and_emit(side, opposite, data, CURRENT)
            # 1b. a named-window side's publication carries its own
            # EXPIRED rows (shared buffer already applied) — probe them
            # as EXPIRED joins (reference Window.java → JoinProcessor)
            if side.is_named_window and triggers:
                expired = chunk.only(EXPIRED)
                if not expired.is_empty:
                    self._probe_and_emit(side, opposite,
                                         expired.with_types(CURRENT),
                                         EXPIRED)
            # 2. events enter this side's window; expirees probe as EXPIRED
            if side.window is not None:
                side.window.process(chunk)
                for out in side.collector.drain():
                    if not triggers:
                        continue
                    expired = out.only(EXPIRED)
                    if not expired.is_empty:
                        self._probe_and_emit(side, opposite,
                                             expired.with_types(CURRENT),
                                             EXPIRED)

    def _probe_and_emit(self, side: JoinSide, opposite: JoinSide,
                        data: EventChunk, emit_type: int):
        n = len(data)
        cc = self._table_conds.get(opposite.side)
        if self.agg_runtime is not None and opposite.is_aggregation:
            if self._agg_per_row and n > 1:
                # within/per read the probing rows' attributes → each row
                # may target a different range/duration
                for i in range(n):
                    self._probe_and_emit(side, opposite,
                                         data.slice(i, i + 1), emit_type)
                return
            buf = self.agg_runtime.find_chunk(self.jis.within, self.jis.per,
                                              data)
        elif cc is not None:
            from .record_table import AbstractRecordTable
            table = self.qr.app_runtime.table_of(opposite.stream_id)
            if not isinstance(table, AbstractRecordTable):
                # indexed table probe per arriving row (hash lookup +
                # residual); snapshot and probe under ONE lock acquisition
                # so the probed row indices are valid for the snapshot
                with table.lock:
                    buf = table.all_rows_chunk()
                    rows = [table._match_rows(cc, data, i)
                            for i in range(n)] if len(buf) else []
            else:
                # record table: condition pushdown, one native store probe
                # per arriving row (≙ AbstractRecordTable.find with the
                # compiled condition's per-probe parameters).  One lock
                # acquisition for the whole chunk so a concurrent
                # insert/delete cannot yield an inconsistent join view
                # across rows (RLock: find()'s nested acquire is safe)
                with table.lock:
                    chunks = [table.find(cc, data, i) for i in range(n)]
                buf = EventChunk.concat(chunks)
                rows, off = [], 0
                for c in chunks:
                    rows.append(np.arange(off, off + len(c)))
                    off += len(c)
        else:
            buf = opposite.buffer_chunk()
        m = 0 if buf is None or buf.is_empty else len(buf)
        outer_this = (
            self.join_type == JoinType.FULL_OUTER or
            (self.join_type == JoinType.LEFT_OUTER and side.side == "left") or
            (self.join_type == JoinType.RIGHT_OUTER and side.side == "right"))

        if cc is not None and m > 0:
            sel_l = np.concatenate(
                [np.full(len(r), i, np.int64) for i, r in enumerate(rows)]
                or [np.empty(0, np.int64)])
            sel_r = np.concatenate(rows) if rows \
                else np.empty(0, np.int64)
            if outer_this:
                miss = np.asarray([i for i, r in enumerate(rows)
                                   if len(r) == 0], np.int64)
                sel_l = np.concatenate([sel_l, miss])
                sel_r = np.concatenate([sel_r, np.full(len(miss), -1)])
                order = np.argsort(sel_l, kind="stable")
                sel_l, sel_r = sel_l[order], sel_r[order]
            if len(sel_l):
                self._emit(side, data, opposite, buf, sel_l, sel_r,
                           emit_type)
            return

        if m == 0:
            if outer_this:
                self._emit(side, data, opposite, None,
                           np.arange(n), np.full(n, -1), emit_type)
            return

        # cross product: row i of data × row j of buffer
        sel = None
        if self.device_probe is not None:
            sel = self._device_pairs(side, data, buf)
        if sel is not None:
            sel_l, sel_r = sel
        else:
            li = np.repeat(np.arange(n), m)
            rj = np.tile(np.arange(m), n)
            if self.on is not None:
                qualified = {}
                for s, c, idx in ((side, data, li), (opposite, buf, rj)):
                    cols = {a: c.columns[a][idx] for a in c.names}
                    qualified[(s.ref, 0)] = cols
                    if s.stream_id != s.ref:
                        qualified[(s.stream_id, 0)] = cols
                ctx = EvalCtx({}, data.timestamps[li], n * m,
                              qualified=qualified)
                mask = np.asarray(self.on.fn(ctx), bool)
                if mask.ndim == 0:
                    mask = np.full(n * m, bool(mask))
            else:
                mask = np.ones(n * m, bool)
            sel_l, sel_r = li[mask], rj[mask]
        if outer_this:
            matched = np.zeros(n, bool)
            matched[sel_l] = True
            miss = np.flatnonzero(~matched)
            sel_l = np.concatenate([sel_l, miss])
            sel_r = np.concatenate([sel_r, np.full(len(miss), -1)])
            order = np.argsort(sel_l, kind="stable")
            sel_l, sel_r = sel_l[order], sel_r[order]
        if len(sel_l) == 0:
            return
        self._emit(side, data, opposite, buf, sel_l, sel_r, emit_type)

    def _emit(self, side: JoinSide, data: EventChunk, opposite: JoinSide,
              buf: Optional[EventChunk], sel_l: np.ndarray,
              sel_r: np.ndarray, emit_type: int):
        k = len(sel_l)
        qualified = {}
        flat: Dict[str, np.ndarray] = {}

        def null_col(length):
            return np.full(length, None, object)

        for s, c, idx in ((side, data, sel_l), (opposite, buf, sel_r)):
            cols = {}
            for a in s.definition.attribute_names:
                if c is None:
                    cols[a] = null_col(k)
                else:
                    vals = c.columns[a][np.maximum(idx, 0)]
                    if (idx < 0).any():
                        vals = vals.astype(object)
                        vals[idx < 0] = None
                    cols[a] = vals
            qualified[(s.ref, 0)] = cols
            if s.stream_id != s.ref:
                qualified[(s.stream_id, 0)] = cols
        # flattened union columns (left side wins collisions iff it defined
        # the union attr first)
        for a in self.union_def.attribute_names:
            for s in (self.left, self.right):
                if a in s.definition.attribute_names:
                    flat[a] = qualified[(s.ref, 0)][a]
                    break
        ts = data.timestamps[sel_l]
        out = EventChunk(self.union_def.attribute_names, ts,
                         np.full(k, emit_type, np.int8), flat, qualified)
        self.head.process(out)
