"""Snapshot service + persistence stores.

(reference: util/snapshot/SnapshotService.java — full/incremental snapshots of
every registered Snapshotable under the ThreadBarrier; util/persistence/
{InMemory,FileSystem,IncrementalFileSystem}PersistenceStore.java.)

State here is JSON-serialisable dicts of columnar buffers (no Java object
serialisation): each stateful element exposes current_state()/restore_state().
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Dict, Optional


def _loads(snapshot: bytes):
    """Unpickle snapshot bytes; torn/corrupt bytes surface as a typed
    CannotRestoreStateError instead of a raw pickle exception."""
    from ..utils.errors import CannotRestoreStateError
    try:
        return pickle.loads(snapshot)
    except CannotRestoreStateError:
        raise
    except Exception as e:      # noqa: BLE001 — any unpickle failure
        raise CannotRestoreStateError(
            f"snapshot bytes are corrupt or truncated: "
            f"{type(e).__name__}: {e}") from e


def _rev_key(revision: str):
    """Numeric-aware revision sort key: revisions are
    ``{millis}_{app}_{full|inc}`` — order by the leading integer, then
    the string, so ordering survives millis-width changes (lexicographic
    sorting would put 999... after 1000...)."""
    head, _, _ = revision.partition("_")
    try:
        return (0, int(head), revision)
    except ValueError:
        return (1, 0, revision)


class PersistenceStore:
    def save(self, app_name: str, revision: str, snapshot: bytes):
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def revisions(self, app_name: str) -> list:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str):
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    def __init__(self):
        self._data: Dict[str, Dict[str, bytes]] = {}

    def save(self, app_name, revision, snapshot):
        self._data.setdefault(app_name, {})[revision] = snapshot

    def load(self, app_name, revision):
        return self._data.get(app_name, {}).get(revision)

    def last_revision(self, app_name):
        revs = self.revisions(app_name)
        return revs[-1] if revs else None

    def revisions(self, app_name):
        return sorted(self._data.get(app_name, {}).keys(), key=_rev_key)

    def clear_all_revisions(self, app_name):
        self._data.pop(app_name, None)


class FileSystemPersistenceStore(PersistenceStore):
    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def _dir(self, app_name):
        d = os.path.join(self.base_dir, app_name)
        os.makedirs(d, exist_ok=True)
        return d

    def save(self, app_name, revision, snapshot):
        # crash-safe: write to a temp file in the same directory, then
        # os.replace (atomic on POSIX) — a kill mid-write leaves either
        # the old revision set or the new one, never a torn file
        d = self._dir(app_name)
        tmp = os.path.join(d, f".{revision}.tmp")
        final = os.path.join(d, revision)
        with open(tmp, "wb") as f:
            f.write(snapshot)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    def load(self, app_name, revision):
        p = os.path.join(self._dir(app_name), revision)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def last_revision(self, app_name):
        revs = self.revisions(app_name)
        return revs[-1] if revs else None

    def revisions(self, app_name):
        return sorted((f for f in os.listdir(self._dir(app_name))
                       if not f.startswith(".")), key=_rev_key)

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))


class SnapshotService:
    """Registry of stateful elements; produces/consumes revisions."""

    def __init__(self, app_ctx):
        self.app_ctx = app_ctx
        self._elements: Dict[str, object] = {}
        # ONE lock serializes every persist: external persist() callers,
        # worker-callback persists, and the periodic CheckpointScheduler
        # all funnel through it.  Re-entrant so a persist triggered from
        # inside another persist's flush cannot self-deadlock.
        self._lock = threading.RLock()
        self._persist_owner = None   # thread ident of the in-flight persist
        self._active_revision = None
        self._last_rev_ms = 0
        # set by SiddhiAppRuntime: drains async junction queues + retires
        # pipelined device work so a snapshot deterministically includes
        # every event sent before persist() was called
        self.pre_snapshot = None
        # incremental bookkeeping: per-element digest of the last persisted
        # state (reference separates incrementalSnapshotable op-logs from
        # periodic base state, SnapshotService.java:159-205; a content
        # digest over the columnar state plays the role of the op-log)
        self._last_digest: Dict[str, bytes] = {}
        # last revision saved per app: each incremental envelope records
        # the revision it was built on top of, so restore can detect a
        # chain gap (SC006) instead of replaying over it
        self._last_saved: Dict[str, str] = {}

    def register(self, element_id: str, element):
        self._elements[element_id] = element

    def deregister(self, element_id: str):
        self._elements.pop(element_id, None)

    # ------------------------------------------------------------ snapshot

    def _routing(self):
        """The pinned FNV-1a routing digest carried in every envelope —
        per-shard sections only restore under the same key→shard map."""
        try:
            from ..parallel.shards import routing_digest
            return routing_digest()
        except Exception:    # noqa: BLE001 — envelope metadata only
            return None

    def _describe(self, eid: str, state):
        from .stateschema import describe_element
        el = self._elements.get(eid)
        return None if el is None else describe_element(el, state)

    def _verify(self, snap_descs, snap_routing, incremental: bool):
        """Diff the snapshot's embedded schema against the live runtime
        and raise a typed SC0xx error BEFORE any restore_state runs.
        Caller holds the thread barrier."""
        from ..utils.errors import CannotRestoreStateError
        from .stateschema import describe_element, verify_compat
        live = {}
        for eid, el in self._elements.items():
            if incremental and eid not in snap_descs:
                continue       # increments only carry changed elements
            s = el.current_state()
            if s is None:
                continue
            d = describe_element(el, s)
            if d is not None:
                live[eid] = d
        findings = verify_compat(
            snap_descs, live, incremental=incremental,
            snap_routing=snap_routing,
            live_routing=self._routing() if snap_routing else None)
        if findings:
            raise CannotRestoreStateError.from_findings(findings)

    def full_snapshot(self, flush: bool = True) -> bytes:
        """ThreadBarrier-locked capture of every element's state
        (reference SnapshotService.fullSnapshot:97-158), wrapped in the
        v2 envelope: per-element schema descriptions + routing digest
        ride next to the state so restore can verify compatibility
        before touching any carry."""
        from .stateschema import build_envelope
        if flush and self.pre_snapshot is not None:
            self.pre_snapshot()
        barrier = self.app_ctx.thread_barrier
        barrier.lock()
        try:
            state, descs = {}, {}
            for eid, el in self._elements.items():
                s = el.current_state()
                if s is not None:
                    state[eid] = s
                    d = self._describe(eid, s)
                    if d is not None:
                        descs[eid] = d
            env = build_envelope(state, descs, self._routing())
            return pickle.dumps(env, protocol=pickle.HIGHEST_PROTOCOL)
        finally:
            barrier.unlock()

    def restore(self, snapshot: bytes):
        from .stateschema import parse_envelope
        state, descs, routing, incremental, _prev = parse_envelope(
            _loads(snapshot))
        barrier = self.app_ctx.thread_barrier
        barrier.lock()
        try:
            if descs is not None:       # legacy pre-schema snapshots skip
                self._verify(descs, routing, incremental)
            for eid, s in state.items():
                el = self._elements.get(eid)
                if el is not None:
                    el.restore_state(s)
        finally:
            barrier.unlock()

    def incremental_snapshot(self, flush: bool = True,
                             prev: Optional[str] = None) -> bytes:
        """Only elements whose state changed since the last persisted
        snapshot (full or incremental).  ``prev`` records the revision
        this delta was built on top of — the restore chain walker
        verifies the links and fails typed (SC006) on a gap."""
        import hashlib

        from .stateschema import build_envelope
        if flush and self.pre_snapshot is not None:
            self.pre_snapshot()
        barrier = self.app_ctx.thread_barrier
        barrier.lock()
        try:
            changed, descs = {}, {}
            for eid, el in self._elements.items():
                s = el.current_state()
                if s is None:
                    continue
                blob = pickle.dumps(s, protocol=pickle.HIGHEST_PROTOCOL)
                digest = hashlib.sha256(blob).digest()
                if self._last_digest.get(eid) != digest:
                    changed[eid] = s
                    self._last_digest[eid] = digest
                    d = self._describe(eid, s)
                    if d is not None:
                        descs[eid] = d
            env = build_envelope(changed, descs, self._routing(),
                                 incremental=True, prev=prev)
            return pickle.dumps(env, protocol=pickle.HIGHEST_PROTOCOL)
        finally:
            barrier.unlock()

    def _mark_digests(self, snapshot: bytes):
        import hashlib

        from .stateschema import parse_envelope
        state, _descs, _routing, _inc, _prev = parse_envelope(
            pickle.loads(snapshot))
        for eid, s in state.items():
            blob = pickle.dumps(s, protocol=pickle.HIGHEST_PROTOCOL)
            self._last_digest[eid] = hashlib.sha256(blob).digest()

    # ------------------------------------------------------------ revisions

    def persist(self, app_name: str, store: PersistenceStore,
                incremental: bool = False) -> str:
        """Full revisions end `_full`; incremental deltas end `_inc` and are
        replayed on top of the latest full base at restore (reference
        IncrementalFileSystemPersistenceStore revision chains)."""
        # Re-entrant persist: capturing a snapshot can retire pipelined
        # device output, which delivers events synchronously — and a
        # callback on that path may call persist() again on this very
        # thread.  The in-flight snapshot already covers that state;
        # flushing here would deadlock (the junction worker is parked on
        # the thread barrier the outer capture holds, and the nested
        # flush would wait on that worker forever).
        if self._persist_owner == threading.get_ident():
            return self._active_revision
        # Flush BEFORE taking the lock: pre_snapshot waits on junction
        # flush barriers, and a worker-callback persist() blocked on the
        # lock would never consume its barrier copy (deadlock cycle:
        # lock-holder waits on worker, worker waits on lock).
        if self.pre_snapshot is not None:
            self.pre_snapshot()
        with self._lock:      # serialize concurrent persist callers
            # strictly-monotonic revision stamp: two persists inside the
            # same millisecond must not collide on the same revision name
            now = max(int(time.time() * 1000), self._last_rev_ms + 1)
            self._last_rev_ms = now
            self._persist_owner = threading.get_ident()
            try:
                if incremental and self._last_digest:
                    revision = f"{now}_{app_name}_inc"
                    self._active_revision = revision
                    store.save(app_name, revision, self.incremental_snapshot(
                        flush=False, prev=self._last_saved.get(app_name)))
                else:
                    revision = f"{now}_{app_name}_full"
                    self._active_revision = revision
                    snap = self.full_snapshot(flush=False)
                    self._mark_digests(snap)
                    store.save(app_name, revision, snap)
                self._last_saved[app_name] = revision
                return revision
            finally:
                self._persist_owner = None

    def restore_revision(self, app_name: str, store: PersistenceStore,
                         revision: str):
        from ..utils.errors import CannotRestoreStateError
        from .stateschema import parse_envelope
        snap = store.load(app_name, revision)
        if snap is None:
            raise CannotRestoreStateError(f"No revision {revision}")
        _state, _descs, _routing, incremental, _prev = parse_envelope(
            _loads(snap))
        if not incremental:
            self.restore(snap)
            return
        # replay: latest full base before this revision, then every
        # increment up to and including it (numeric-aware ordering)
        rk = _rev_key(revision)
        revisions = sorted((r for r in store.revisions(app_name)
                            if _rev_key(r) <= rk), key=_rev_key)
        base = None
        for r in revisions:
            if r.endswith("_full"):
                base = r
        bk = _rev_key(base) if base is not None else None
        chain = [r for r in revisions
                 if bk is None or _rev_key(r) >= bk]
        # Load and link-check the WHOLE chain before applying anything:
        # each increment records the revision it was built on top of, so
        # a deleted intermediate (which simply vanishes from the
        # revisions() listing) is a typed SC006 gap instead of a silent
        # replay of stale state.
        links, prev_link = [], None
        for r in chain:
            blob = store.load(app_name, r)
            if blob is None:
                raise CannotRestoreStateError(
                    f"incremental restore chain for {revision} is "
                    f"broken: revision {r} vanished from the store "
                    f"between listing and load", code="SC006")
            st, descs_r, routing_r, inc_r, prev_r = parse_envelope(
                _loads(blob))
            if inc_r and prev_r is not None and prev_r != prev_link:
                raise CannotRestoreStateError(
                    f"incremental restore chain for {revision} is "
                    f"broken: {r} was built on top of revision {prev_r} "
                    f"but the previous intact link is "
                    f"{prev_link or '<no full base>'} — an intermediate "
                    f"revision is missing, and replaying over the gap "
                    f"would restore stale state", code="SC006")
            links.append((st, descs_r, routing_r, inc_r))
            prev_link = r
        barrier = self.app_ctx.thread_barrier
        barrier.lock()
        try:
            # every link's schema header verifies against the live
            # runtime before ANY link's state is applied
            for _st, descs_r, routing_r, inc_r in links:
                if descs_r is not None:
                    self._verify(descs_r, routing_r, inc_r)
            for st, _descs_r, _routing_r, _inc_r in links:
                for eid, s in st.items():
                    el = self._elements.get(eid)
                    if el is not None:
                        el.restore_state(s)
        finally:
            barrier.unlock()

    def restore_last_revision(self, app_name: str,
                              store: PersistenceStore) -> Optional[str]:
        rev = store.last_revision(app_name)
        if rev is not None:
            self.restore_revision(app_name, store, rev)
        return rev
