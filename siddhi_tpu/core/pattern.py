"""Pattern & sequence state-machine runtime — host oracle path.

This is the exact-semantics CEP pattern engine the TPU NFA kernel is
conformance-tested against (see plan/nfa_compiler.py + ops/nfa.py for the
batched TPU path).

Reference behavior mirrored from siddhi-core
query/input/stream/state/:
  - StreamPreStateProcessor.java:292-337 (pending-list stepping, within expiry,
    PATTERN vs SEQUENCE no-match handling)
  - StreamPostStateProcessor.java:53-72 (state advance, every re-arm)
  - LogicalPreStateProcessor.java / LogicalPostStateProcessor.java (and/or
    partner-linked pairs sharing partial-match objects)
  - CountPreStateProcessor.java / CountPostStateProcessor.java (kleene
    <m:n> accumulation into one partial, forward-at-min)
  - AbsentStreamPreStateProcessor.java / AbsentLogicalPreStateProcessor.java
    (scheduler-driven `not X for t`)
  - receiver/* + StateStreamRuntime.resetAndUpdate (per-event update/reset
    barriers; SEQUENCE strict contiguity)
and util/parser/StateInputStreamParser.java:76-404 (state graph wiring:
`->` next links, `every` loops, logical partners, within start-state ids).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..plan.expr_compiler import CompiledExpr, EvalCtx, Scope
from ..query_api import (AbsentStreamStateElement, CountStateElement,
                         EveryStateElement, Filter, LogicalOp,
                         LogicalStateElement, NextStateElement,
                         StateInputStream, StateType, StreamStateElement)
from ..query_api.definition import Attribute, StreamDefinition
from ..utils.errors import SiddhiAppCreationError
from .event import CURRENT, EventChunk
from .stateschema import ListOf, Struct, persistent_schema

Row = Tuple[int, Dict[str, Any]]  # (timestamp, {attr: python value})

_UNSET = -0x7FFFFFFF


class StateEvent:
    """A partial match: one slot per state unit (reference
    event/state/StateEvent.java — StreamEvent[] streamEvents).

    Slot contents: None (not matched), a Row, or a list of Rows for count
    states.  Objects are shared between partner/next pending lists exactly
    like the reference shares StateEvent instances."""

    __slots__ = ("events", "timestamp")

    def __init__(self, n_states: int):
        self.events: List[Any] = [None] * n_states
        self.timestamp: int = -1

    def clone(self) -> "StateEvent":
        se = StateEvent(len(self.events))
        se.timestamp = self.timestamp
        se.events = [list(e) if isinstance(e, list) else e
                     for e in self.events]
        return se

    def first_row(self, sid: int) -> Optional[Row]:
        e = self.events[sid]
        if e is None:
            return None
        if isinstance(e, list):
            return e[0] if e else None
        return e

    def last_row(self, sid: int) -> Optional[Row]:
        e = self.events[sid]
        if e is None:
            return None
        if isinstance(e, list):
            return e[-1] if e else None
        return e


class StateUnit:
    """One pattern condition = pre+post state processor pair fused.

    (reference: Stream/Logical/Count/Absent Pre+PostStateProcessor pairs)"""

    def __init__(self, engine: "StateStreamRuntime", state_id: int, ref: str,
                 stream_id: str, definition, state_type: StateType):
        self.engine = engine
        self.state_id = state_id
        self.ref = ref
        self.stream_id = stream_id
        self.definition = definition
        self.state_type = state_type

        self.filter: Optional[CompiledExpr] = None
        self.is_start = False
        self.is_last = False
        self.within_ms: Optional[int] = None
        self.start_state_ids: List[int] = []

        # wiring (reference post-processor links)
        self.next_pre: Optional["StateUnit"] = None
        self.next_every_pre: Optional["StateUnit"] = None
        self.within_every_pre: Optional["StateUnit"] = None

        # count (kleene) configuration
        self.is_count = False
        self.min_count = 1
        self.max_count = 1

        # logical pair configuration
        self.logical_op: Optional[LogicalOp] = None
        self.partner: Optional["StateUnit"] = None

        # absent configuration
        self.is_absent = False
        self.waiting_ms: Optional[int] = None
        self.active = True
        self.last_scheduled = -1
        self.last_arrival = 0

        # runtime state
        self.pending: List[StateEvent] = []
        self.new_list: List[StateEvent] = []
        self.initialized = False
        self.state_changed = False

    # ------------------------------------------------------------ pre side

    def init_start(self):
        """reference StreamPreStateProcessor.init():162-173"""
        if self.is_start and (
                not self.initialized or self.next_every_pre is not None or
                (self.state_type == StateType.SEQUENCE and
                 self.next_pre is not None and self.next_pre.is_absent)):
            self.add_state(StateEvent(self.engine.n_states))
            self.initialized = True

    def add_state(self, se: StateEvent):
        """reference addState :203-216 (+Logical :18-35, +Absent, +Count min0)"""
        if self.is_absent and not self.active:
            return
        if self.logical_op is not None:
            if self.is_start or self.state_type == StateType.SEQUENCE:
                if not self.new_list:
                    self.new_list.append(se)
                if self.partner is not None and not self.partner.new_list:
                    self.partner.new_list.append(se)
            else:
                self.new_list.append(se)
                if self.partner is not None:
                    self.partner.new_list.append(se)
            if self.is_absent and not self.is_start and \
                    self.waiting_ms is not None:
                self._schedule(se.timestamp + self.waiting_ms)
                if self.partner is not None and self.partner.is_absent and \
                        self.partner.waiting_ms is not None:
                    self.partner._schedule(se.timestamp +
                                           self.partner.waiting_ms)
            return
        if self.is_absent and self.state_type == StateType.SEQUENCE:
            self.new_list.clear()
            self.new_list.append(se)
        elif self.state_type == StateType.SEQUENCE:
            if not self.new_list:
                self.new_list.append(se)
        else:
            self.new_list.append(se)
        if self.is_absent and not self.is_start:
            self.last_scheduled = se.timestamp + (self.waiting_ms or 0)
            self._schedule(self.last_scheduled)
        if self.is_count and self.min_count == 0 and \
                se.events[self.state_id] is None:
            # <0:n> — zero occurrences already satisfy (CountPreStateProcessor
            # addState min==0 branch)
            self._min_count_reached(se)

    def add_every_state(self, se: StateEvent):
        """reference addEveryState — clone for the every re-arm."""
        cl = se.clone()
        if self.logical_op is not None:
            if cl.events[self.state_id] is not None:
                row = cl.last_row(self.state_id)
                if row is not None:
                    cl.timestamp = row[0]
            cl.events[self.state_id] = None
            if self.partner is not None:
                cl.events[self.partner.state_id] = None
                self.partner.new_list.append(cl)
            self.new_list.append(cl)
            if self.is_absent and self.waiting_ms is not None:
                self.last_scheduled = (self.engine.now() + self.waiting_ms
                                       if cl.timestamp < 0
                                       else cl.timestamp + self.waiting_ms)
                self._schedule(self.last_scheduled)
            return
        self.new_list.append(cl)
        if self.is_absent:
            self.last_scheduled = se.timestamp + (self.waiting_ms or 0)
            self._schedule(self.last_scheduled)

    def update_state(self):
        self.pending.extend(self.new_list)
        self.new_list.clear()
        if self.logical_op is not None and self.partner is not None:
            self.partner.pending.extend(self.partner.new_list)
            self.partner.new_list.clear()

    def reset_state(self):
        """reference resetState — SEQUENCE per-event strictness barrier."""
        if self.logical_op is not None and self.partner is not None:
            if self.logical_op == LogicalOp.OR or \
                    len(self.pending) == len(self.partner.pending):
                self.pending.clear()
                self.partner.pending.clear()
                if self.is_start and not self.new_list:
                    if self._seq_next_busy():
                        return
                    self.init_start()
            return
        self.pending.clear()
        if self.is_start and not self.new_list:
            if self._seq_next_busy():
                return
            self.init_start()

    def _seq_next_busy(self) -> bool:
        return (self.state_type == StateType.SEQUENCE and
                self.next_every_pre is None and
                self.next_pre is not None and bool(self.next_pre.pending))

    def _expired(self, se: StateEvent, now: int) -> bool:
        """reference isExpired :104-113 — within vs start-state timestamps."""
        if self.is_start or self.within_ms is None:
            return False
        for sid in self.start_state_ids:
            row = se.first_row(sid)
            if row is not None and abs(row[0] - now) > self.within_ms:
                return True
        return False

    # ------------------------------------------------------------ stepping

    def process_and_return(self, row: Row):
        """Step all pending partials over one arriving event
        (reference processAndReturn :292-337)."""
        if self.is_absent and not self.active:
            return
        ts = row[0]
        kept: List[StateEvent] = []
        for se in self.pending:
            if self._expired(se, ts):
                # forward the expired partial to the every-group head
                # EXCEPT when that head is this very unit: the reference
                # would then addEveryState into the LinkedList it is
                # iterating (StreamPreStateProcessor.java:298-306 +
                # updateState :280-288 → ConcurrentModificationException),
                # i.e. the self-forward path is broken/unreachable
                # upstream — here the partial simply dies, matching the
                # device kernel's within-expiry (`A -> every B within t`
                # stops firing t after the chain start).  Forwards to a
                # DIFFERENT head (multi-unit groups, leading groups) keep
                # reference behavior.
                if self.within_every_pre is not None and \
                        self.within_every_pre is not self:
                    self.within_every_pre.add_every_state(se)
                    self.within_every_pre.update_state()
                continue
            if self.logical_op == LogicalOp.OR and self.partner is not None \
                    and se.events[self.partner.state_id] is not None:
                continue  # partner already satisfied this partial
            if self.is_count:
                if self._count_next_processed(se):
                    continue
                lst = se.events[self.state_id]
                if not isinstance(lst, list):
                    lst = []
                    se.events[self.state_id] = lst
                lst.append(row)
                self.state_changed = False
                success = False
                if self._filter_pass(se, row):
                    self._fire_count_post(se, row)
                    success = True
                if not success:
                    lst.pop()
                    if self.state_type == StateType.SEQUENCE:
                        continue  # drop partial
                if not self.state_changed:
                    kept.append(se)
                continue
            # normal / logical / absent unit
            se.events[self.state_id] = row
            self.state_changed = False
            if self._filter_pass(se, row):
                self._fire_post(se, row)
            if self.state_changed:
                continue  # advanced (or consumed) — leaves this pending list
            se.events[self.state_id] = None
            if self.state_type == StateType.SEQUENCE:
                if not (self.is_absent or self.logical_op is not None):
                    continue  # strict sequence: no match → drop partial
                kept.append(se)
            else:
                kept.append(se)
        self.pending = kept

    def _count_next_processed(self, se: StateEvent) -> bool:
        """reference removeIfNextStateProcessed — stop accumulating once a
        later state captured its event."""
        for off in (1, 2):
            pos = self.state_id + off
            if pos < len(se.events) and se.events[pos] is not None:
                return True
        return False

    def _filter_pass(self, se: StateEvent, row: Row) -> bool:
        if self.filter is None:
            return True
        ts, data = row
        cols = {k: v for k, v in data.items()}
        ctx = EvalCtx(cols, np.asarray([ts], np.int64), 1,
                      qualified=self.engine.qualified_of(se),
                      tables=self.engine.tables)
        v = self.filter.fn(ctx)
        arr = np.asarray(v).reshape(-1)
        return bool(arr[0]) if arr.size else bool(v)

    # ------------------------------------------------------------ post side

    def _fire_post(self, se: StateEvent, row: Row):
        """reference StreamPostStateProcessor.process :53-72 and
        Logical/Absent variants."""
        self.state_changed = True
        se.timestamp = row[0]
        if self.is_absent:
            # actual arrival of a `not` stream: kills/poisons the partial,
            # never advances (AbsentStream/AbsentLogical PostStateProcessor)
            self.last_arrival = row[0]
            if self.logical_op is None and self.is_start and \
                    self.next_every_pre is self:
                self.add_every_state(se)
            return
        if self.logical_op == LogicalOp.AND and self.partner is not None:
            can = (se.events[self.partner.state_id] is not None
                   if not self.partner.is_absent
                   else self.partner._partner_can_proceed(se))
            if not can:
                return  # stateChanged only; partner side still pending
        self._forward(se)

    def _partner_can_proceed(self, se: StateEvent) -> bool:
        """reference AbsentLogicalPreStateProcessor.partnerCanProceed."""
        if self.state_type == StateType.SEQUENCE and \
                self.next_every_pre is None and self.last_arrival > 0:
            return False
        if self.waiting_ms is None:
            if self.next_every_pre is None:
                return se.events[self.state_id] is None
            if self.last_arrival > 0:
                self.last_arrival = 0
                self.init_start()
                return False
            return True
        return se.events[self.state_id] is not None

    def _forward(self, se: StateEvent):
        if self.is_last:
            self.engine.collect_match(se)
        if self.next_pre is not None:
            self.next_pre.add_state(se)
        if self.next_every_pre is not None:
            self.next_every_pre.add_every_state(se)

    def _fire_count_post(self, se: StateEvent, row: Row):
        """reference CountPostStateProcessor.process."""
        cnt = len(se.events[self.state_id])
        se.timestamp = row[0]
        if cnt >= self.min_count:
            if self.state_type == StateType.SEQUENCE:
                # reference CountPostStateProcessor.process SEQUENCE branch:
                # forward + self re-add only — no every clone (sequences
                # restart via per-event start re-init)
                if self.is_last:
                    self.engine.collect_match(se)
                if self.next_pre is not None:
                    self.next_pre.add_state(se)
                if cnt != self.max_count:
                    self.add_state(se)
            elif cnt == self.min_count:
                self._min_count_reached(se)
            if cnt == self.max_count:
                self.state_changed = True

    def _min_count_reached(self, se: StateEvent):
        """reference CountPostStateProcessor.processMinCountReached."""
        if self.is_last:
            self.state_changed = True
            self.engine.collect_match(se)
        if self.next_pre is not None:
            self.next_pre.add_state(se)
        if self.next_every_pre is not None:
            self.next_every_pre.add_every_state(se)

    # ------------------------------------------------------------ absent timer

    def _schedule(self, ts: int):
        if ts < 0:
            return
        self.engine.schedule(ts, self)

    def start(self):
        """Arm start-state absent timers (reference
        AbsentStreamPreStateProcessor.start)."""
        if self.is_absent and self.is_start and self.waiting_ms is not None \
                and self.active:
            self.last_scheduled = self.engine.now() + self.waiting_ms
            self._schedule(self.last_scheduled)

    def absent_tick(self, now: int):
        """Timer wakeup (reference AbsentStreamPreStateProcessor.process and
        AbsentLogicalPreStateProcessor.process)."""
        if not self.active or self.waiting_ms is None:
            return
        if self.logical_op is not None:
            self._absent_logical_tick(now)
            return
        initialize = (self.is_start and not self.new_list and not self.pending)
        if initialize and self.state_type == StateType.SEQUENCE and \
                self.next_every_pre is None and self.last_scheduled > 0 and \
                self.initialized:
            initialize = False
        if initialize:
            se = StateEvent(self.engine.n_states)
            self.add_state(se)
            self.initialized = True
        elif self.state_type == StateType.SEQUENCE and self.new_list:
            self.reset_state()
        self.update_state()
        fired: List[StateEvent] = []
        kept: List[StateEvent] = []
        for se in self.pending:
            if self._expired(se, now):
                if self.within_every_pre is not None and \
                        self.next_every_pre is not self:
                    self.next_every_pre_or_within().add_every_state(se)
                    self.next_every_pre_or_within().update_state()
                continue
            if (se.timestamp == -1 and now >= self.last_scheduled) or \
                    (se.timestamp != -1 and
                     now >= se.timestamp + self.waiting_ms):
                se.timestamp = now
                fired.append(se)
                continue
            kept.append(se)
        self.pending = kept
        for se in fired:
            self._forward_absent(se)
        actual_now = self.engine.now()
        if actual_now > self.waiting_ms + now:
            self.last_scheduled = actual_now + self.waiting_ms
        if not fired and self.last_scheduled < now:
            self.last_scheduled = now + self.waiting_ms
            self._schedule(self.last_scheduled)

    def next_every_pre_or_within(self):
        return self.within_every_pre or self.next_every_pre

    def _absent_logical_tick(self, now: int):
        if now < self.last_arrival + self.waiting_ms:
            if self.next_every_pre is not None or self.is_start:
                self._schedule(self.last_arrival + self.waiting_ms)
            return
        if self.is_start and self.state_type == StateType.SEQUENCE and \
                not self.new_list and not self.pending:
            self.add_state(StateEvent(self.engine.n_states))
        elif self.state_type == StateType.SEQUENCE and self.new_list:
            self.reset_state()
        self.update_state()
        fired: List[StateEvent] = []
        kept: List[StateEvent] = []
        partner = self.partner
        for se in self.pending:
            if self._expired(se, now):
                # self-forward would mutate the list under iteration —
                # see process_and_return
                if self.within_every_pre is not None and \
                        self.within_every_pre is not self:
                    self.within_every_pre.add_every_state(se)
                    self.within_every_pre.update_state()
                continue
            passed = (now >= se.timestamp + self.waiting_ms
                      if se.events[self.state_id] is None else
                      now >= se.events[self.state_id][0] + self.waiting_ms) \
                if se.timestamp != -1 else now >= self.last_scheduled
            if passed:
                if self.logical_op == LogicalOp.OR and \
                        se.events[partner.state_id] is None:
                    se.events[self.state_id] = (now, {})
                    fired.append(se)
                    continue
                if self.logical_op == LogicalOp.AND and \
                        se.events[partner.state_id] is not None:
                    fired.append(se)
                    continue
                if self.logical_op == LogicalOp.AND and \
                        se.events[partner.state_id] is None:
                    se.events[self.state_id] = (now, {})
                    kept.append(se)
                    continue
            kept.append(se)
        self.pending = kept
        for se in fired:
            se.timestamp = now
            self._forward_absent(se)
        arrival = self.last_arrival
        self.last_arrival = 0
        if self.next_every_pre is not None or (not fired and self.is_start):
            nxt = (self.engine.now() + self.waiting_ms if arrival == 0
                   else arrival + self.waiting_ms)
            self._schedule(nxt)

    def _forward_absent(self, se: StateEvent):
        """reference sendEvent — absence confirmed, advance."""
        if self.is_last:
            self.engine.collect_match(se)
            self.engine.flush_matches()
        if self.next_pre is not None:
            self.next_pre.add_state(se)
            self.next_pre.update_state()
        if self.next_every_pre is not None:
            self.next_every_pre.add_every_state(se)
            self.next_every_pre.update_state()
        elif self.is_start and self.logical_op is None:
            self.active = False

    # ------------------------------------------------------------ snapshot

    def unit_state(self, enc) -> dict:
        return {"pending": [enc(se) for se in self.pending],
                "new": [enc(se) for se in self.new_list],
                "initialized": self.initialized,
                "active": self.active,
                "last_scheduled": self.last_scheduled,
                "last_arrival": self.last_arrival}

    def restore_unit_state(self, s: dict, dec):
        self.pending = [dec(x) for x in s["pending"]]
        self.new_list = [dec(x) for x in s["new"]]
        self.initialized = s["initialized"]
        self.active = s["active"]
        self.last_scheduled = s["last_scheduled"]
        self.last_arrival = s["last_arrival"]


class PatternReceiver:
    """Junction subscriber feeding one stream's events into the NFA
    (reference receiver/Pattern*|Sequence* ProcessStreamReceiver)."""

    def __init__(self, engine: "StateStreamRuntime", stream_id: str,
                 units: List[StateUnit]):
        self.engine = engine
        self.stream_id = stream_id
        # later states step first (reference reversed eventSequence)
        self.units = list(reversed(units))

    def receive_chunk(self, chunk: EventChunk):
        names = chunk.names
        with self.engine.lock:
            for i in range(len(chunk)):
                if chunk.types[i] != CURRENT:
                    continue
                ts = int(chunk.timestamps[i])
                data = {n: _py(chunk.columns[n][i]) for n in names}
                self.engine.process_event(self, (ts, data))


@persistent_schema("host-pattern",
                   schema=Struct(store=ListOf("state-event"),
                                 units=ListOf("unit-state")))
class StateStreamRuntime:
    """Compiled pattern/sequence input runtime for one query.

    Builds the state-unit graph from the StateElement tree
    (≙ StateInputStreamParser), subscribes per-stream receivers, and emits
    matched partials into the query's selector chain."""

    def __init__(self, query_runtime, sis: StateInputStream, factory):
        self.qr = query_runtime
        self.sis = sis
        self.app = query_runtime.app_runtime
        self.lock = query_runtime.lock
        self.state_type = sis.state_type
        self.units: List[StateUnit] = []
        self.tables = {tid: t for tid, t in self.app.tables.items()}
        self._matches: List[StateEvent] = []
        self._stream_units: Dict[str, List[StateUnit]] = {}
        self._refs_by_unit: Dict[int, str] = {}

        first, last, starts = self._build(sis.state, is_start=True)
        self.first_unit = first
        # mark last pair for emission
        last.is_last = True
        if last.logical_op is not None and last.partner is not None:
            last.partner.is_last = True
        self.n_states = len(self.units)
        for u in self.units:
            u.pending = []
        # top-level within
        if sis.within_ms is not None:
            start_ids = [u.state_id for u in self.units if u.is_start]
            for u in self.units:
                if u.within_ms is None:
                    u.within_ms = sis.within_ms
                if not u.start_state_ids:
                    u.start_state_ids = start_ids
        # compile per-unit filters now that all units exist
        self._compile_filters(factory)
        # selector scope + output definition
        scope, union_def = self._selector_scope()
        query_runtime._finish_chain([], scope, union_def, factory)
        self.selector_head = query_runtime._chain_head([])
        # receivers (one per distinct stream id)
        for stream_id, units in self._stream_units.items():
            recv = PatternReceiver(self, stream_id, units)
            junction = self.app.junction_of(stream_id)
            junction.subscribe(recv)
            query_runtime.receivers[stream_id] = recv
        # arm start states
        for u in self.units:
            u.init_start()

    # ------------------------------------------------------------ build

    def _new_unit(self, el: StreamStateElement) -> StateUnit:
        s = el.stream
        definition = self.app.definition_of(s.stream_id)
        sid = len(self.units)
        ref = s.stream_ref or f"__state_{sid}"
        unit = StateUnit(self, sid, ref, s.stream_id, definition,
                         self.state_type)
        if isinstance(el, AbsentStreamStateElement):
            unit.is_absent = True
            unit.waiting_ms = el.waiting_time_ms
        self.units.append(unit)
        self._stream_units.setdefault(s.stream_id, []).append(unit)
        unit._handlers = s.handlers  # compiled later
        return unit

    def _build(self, el, is_start: bool):
        """Recursive state-graph builder (≙ StateInputStreamParser.parse).
        Returns (first_unit, last_unit, start_units)."""
        if isinstance(el, StreamStateElement):  # includes Absent
            u = self._new_unit(el)
            u.is_start = is_start
            return u, u, [u]
        if isinstance(el, NextStateElement):
            f1, l1, s1 = self._build(el.state, is_start)
            f2, l2, s2 = self._build(el.next, False)
            l1.next_pre = f2
            if l1.logical_op is not None and l1.partner is not None:
                l1.partner.next_pre = f2
            return f1, l2, s1
        if isinstance(el, EveryStateElement):
            f, l, starts = self._build(el.state, is_start)
            l.next_every_pre = f
            if l.logical_op is not None and l.partner is not None:
                l.partner.next_every_pre = f
            group = self._subtree_units(el.state)
            for u in group:
                u.within_every_pre = f
            if el.within_ms is not None:
                self._apply_within(group, el.within_ms, starts)
            return f, l, starts
        if isinstance(el, LogicalStateElement):
            # element2 parsed first in the reference → lower state id
            u2 = self._new_unit(el.state2)
            u1 = self._new_unit(el.state1)
            for u, other in ((u1, u2), (u2, u1)):
                u.logical_op = el.op
                u.partner = other
                u.is_start = is_start
            return u1, u2, [u1, u2]
        if isinstance(el, CountStateElement):
            u = self._new_unit(el.state)
            u.is_count = True
            u.is_start = is_start
            u.min_count = el.min_count
            u.max_count = (el.max_count if el.max_count !=
                           CountStateElement.ANY else 0x7FFFFFFF)
            return u, u, [u]
        raise SiddhiAppCreationError(f"Unsupported state element {el!r}")

    def _subtree_units(self, el) -> List[StateUnit]:
        refs: List[StateUnit] = []

        def rec(e):
            if isinstance(e, StreamStateElement):
                refs.extend(u for u in self.units
                            if u.stream_id == e.stream.stream_id and
                            u._handlers is e.stream.handlers)
            elif isinstance(e, NextStateElement):
                rec(e.state)
                rec(e.next)
            elif isinstance(e, EveryStateElement):
                rec(e.state)
            elif isinstance(e, LogicalStateElement):
                rec(e.state1)
                rec(e.state2)
            elif isinstance(e, CountStateElement):
                rec(e.state)
        rec(el)
        return refs

    def _apply_within(self, units: List[StateUnit], within_ms: int,
                      starts: List[StateUnit]):
        ids = [u.state_id for u in starts]
        for u in units:
            if u.within_ms is None:
                u.within_ms = within_ms
                u.start_state_ids = ids

    # -------------------------------------------------- expression scopes

    def _index_range_used(self) -> Tuple[int, int]:
        """(highest, lowest) e1[i] index mentioned anywhere in the query
        (lowest covers `e1[last-N]` → -1-N; one extra for the self-state
        shift below)."""
        from ..query_api.expression import Variable
        hi, lo = 4, -3

        def scan(e):
            nonlocal hi, lo
            if isinstance(e, Variable) and e.stream_index is not None:
                if e.stream_index >= 0:
                    hi = max(hi, e.stream_index)
                else:
                    lo = min(lo, e.stream_index - 1)
            for f in getattr(e, "__dataclass_fields__", {}):
                v = getattr(e, f)
                if isinstance(v, list):
                    for x in v:
                        scan(x) if hasattr(x, "__dataclass_fields__") else None
                elif hasattr(v, "__dataclass_fields__"):
                    scan(v)
        q = self.qr.query
        for oa in q.selector.attributes:
            scan(oa.expr)
        if q.selector.having is not None:
            scan(q.selector.having)
        for u in self.units:
            for h in u._handlers:
                if isinstance(h, Filter):
                    scan(h.expr)
        return hi, lo

    def _register_qualified(self, scope: Scope, skip_unit=None,
                            max_idx: int = 4, min_idx: int = -3,
                            self_unit=None):
        """self_unit: inside a state's own condition, negative indexes
        exclude the just-appended candidate event — the reference keeps the
        raw LAST index for same-state references instead of shifting it to
        the chain tail (ExpressionParser.java:1366, StateEvent.java:158)."""
        stream_count: Dict[str, int] = {}
        for u in self.units:
            stream_count[u.stream_id] = stream_count.get(u.stream_id, 0) + 1
        for u in self.units:
            if u is skip_unit:
                continue
            qualifiers = [u.ref]
            if stream_count[u.stream_id] == 1 and u.stream_id != u.ref:
                qualifiers.append(u.stream_id)
            idxs = list(range(0, max_idx + 1)) + \
                list(range(-1, min_idx - 1, -1))
            for a in u.definition.attributes:
                for q in qualifiers:
                    for i in idxs:
                        eff = i - 1 if (u is self_unit and i < 0) else i
                        def g(ctx, _q=q, _i=eff, _a=a.name):
                            d = ctx.qualified.get((_q, _i))
                            if d is None:
                                return np.asarray([None], object)
                            return d.get(_a)
                        scope.add(q, a.name, a.type, g, index=i)

    def _compile_filters(self, factory):
        max_idx, min_idx = self._index_range_used()
        self._max_idx = max_idx
        self._min_idx = min_idx
        for u in self.units:
            filters = [h for h in u._handlers if isinstance(h, Filter)]
            others = [h for h in u._handlers if not isinstance(h, Filter)]
            if others:
                raise SiddhiAppCreationError(
                    "Only [filter] handlers are supported inside "
                    "pattern/sequence conditions")
            if not filters:
                u.filter = None
                continue
            scope = Scope()
            self._register_qualified(scope, skip_unit=None, max_idx=max_idx,
                                     min_idx=min_idx, self_unit=u)
            # current-event bindings override for this unit (added last)
            for a in u.definition.attributes:
                def g(ctx, _a=a.name):
                    return ctx.columns[_a]
                scope.add(None, a.name, a.type, g)
                scope.add(u.stream_id, a.name, a.type, g)
                scope.add(u.ref, a.name, a.type, g)
            compiler = factory(scope)
            from ..query_api.expression import And
            expr = filters[0].expr
            for f in filters[1:]:
                expr = And(expr, f.expr)
            u.filter = compiler.compile(expr)

    def _selector_scope(self):
        scope = Scope()
        max_idx = getattr(self, "_max_idx", 4)
        min_idx = getattr(self, "_min_idx", -3)
        self._register_qualified(scope, max_idx=max_idx, min_idx=min_idx)
        # unqualified fallback: first unit defining each attribute
        seen: Dict[str, StateUnit] = {}
        union_attrs: List[Attribute] = []
        for u in self.units:
            for a in u.definition.attributes:
                if a.name not in seen:
                    seen[a.name] = u
                    union_attrs.append(a)
                    def g(ctx, _q=u.ref, _a=a.name):
                        d = ctx.qualified.get((_q, 0))
                        if d is None:
                            return np.asarray([None], object)
                        return d.get(_a)
                    scope.add(None, a.name, a.type, g)
        union_def = StreamDefinition("__pattern", union_attrs)
        return scope, union_def

    # ------------------------------------------------------------ runtime

    def now(self) -> int:
        return self.app.app_ctx.timestamp_generator.current_time()

    def schedule(self, ts: int, unit: StateUnit):
        def fire(now, _u=unit):
            with self.lock:
                _u.absent_tick(now)
                self.flush_matches()
        self.app.app_ctx.scheduler.notify_at(ts, fire)

    def start(self):
        for u in self.units:
            u.start()

    def process_event(self, receiver: PatternReceiver, row: Row):
        # stabilize (reference stabilizeStates)
        if self.state_type == StateType.SEQUENCE:
            for u in reversed(self.units):
                u.reset_state()
            for u in self.units:
                u.update_state()
        else:
            for u in receiver.units:
                u.update_state()
        for u in receiver.units:
            u.process_and_return(row)
            self.flush_matches()

    def collect_match(self, se: StateEvent):
        self._matches.append(se)

    def flush_matches(self):
        if not self._matches:
            return
        matches, self._matches = self._matches, []
        for se in matches:
            self.selector_head.process(self._match_chunk(se))

    def qualified_of(self, se: StateEvent) -> Dict:
        q: Dict = {}
        for u in self.units:
            e = se.events[u.state_id]
            qualifiers = [u.ref]
            if u.stream_id not in [x.stream_id for x in self.units
                                   if x is not u]:
                qualifiers.append(u.stream_id)
            rows = e if isinstance(e, list) else ([e] if e is not None else [])
            min_idx = getattr(self, "_min_idx", -3) - 1
            for name in qualifiers:
                # a duplicated reference resolves to the FIRST unit carrying
                # it (reference position lookup breaks at the first
                # meta-stream hit, ExpressionParser.java parseVariable)
                for i, row in enumerate(rows):
                    if (name, i) not in q:
                        q[(name, i)] = row[1]
                n = len(rows)
                for neg in range(-1, min_idx - 1, -1):
                    if n + neg >= 0 and (name, neg) not in q:
                        q[(name, neg)] = rows[n + neg][1]
        return q

    def _match_chunk(self, se: StateEvent) -> EventChunk:
        qualified = {}
        for key, data in self.qualified_of(se).items():
            qualified[key] = {k: _col1(v) for k, v in data.items()}
        ts = se.timestamp if se.timestamp >= 0 else self.now()
        chunk = EventChunk([], np.asarray([ts], np.int64),
                           np.asarray([CURRENT], np.int8), {})
        chunk.qualified = qualified
        return chunk

    # ------------------------------------------------------------ snapshot

    def current_state(self):
        seen: Dict[int, int] = {}
        store: List[dict] = []

        def enc(se: StateEvent):
            key = id(se)
            if key in seen:
                return {"ref": seen[key]}
            n = len(store)
            seen[key] = n
            store.append({"ts": se.timestamp,
                          "events": [list(e) if isinstance(e, list) else e
                                     for e in se.events]})
            return {"ref": n}
        units = [u.unit_state(enc) for u in self.units]
        return {"store": store, "units": units}

    def restore_state(self, state):
        objs: List[StateEvent] = []
        for rec in state["store"]:
            se = StateEvent(self.n_states)
            se.timestamp = rec["ts"]
            se.events = [list(e) if isinstance(e, list) else
                         (tuple(e) if isinstance(e, tuple) else e)
                         for e in rec["events"]]
            se.events = [_fix_rows(e) for e in se.events]
            objs.append(se)

        def dec(x):
            return objs[x["ref"]]
        for u, s in zip(self.units, state["units"]):
            u.restore_unit_state(s, dec)


def _fix_rows(e):
    if e is None:
        return None
    if isinstance(e, list):
        out = []
        for r in e:
            if isinstance(r, (list, tuple)) and len(r) == 2 and \
                    isinstance(r[1], dict):
                out.append((r[0], r[1]))
            else:
                out.append(r)
        return out
    if isinstance(e, (list, tuple)) and len(e) == 2 and isinstance(e[1], dict):
        return (e[0], e[1])
    return e


def _py(v):
    return v.item() if isinstance(v, np.generic) else v


def _col1(v) -> np.ndarray:
    """One-element column preserving python-object payloads."""
    if v is None or isinstance(v, (str, bytes, dict, list, set)):
        out = np.empty(1, object)
        out[0] = v
        return out
    return np.asarray([v])
