"""Attribute aggregators: sum, avg, count, distinctCount, min, max,
minForever, maxForever, stdDev, and, or, unionSet.

(reference: query/selector/attribute/aggregator/*.java — 13 incremental
aggregators with add-on-CURRENT / subtract-on-EXPIRED / reset-on-RESET
semantics.)

Each aggregator processes a (values, types) column pair for one group-by key
and returns the *running* output per row — the batched equivalent of the
reference's per-event processAdd/processRemove calls.  Sum/count/avg/stdDev/
and/or are fully vectorised (cumulative sums); order-statistics (min/max) use
a lazy-deletion heap; set aggregators use counters.
"""
from __future__ import annotations

import heapq
from collections import Counter
from typing import Dict, List, Optional, Type

import numpy as np

from ..query_api.definition import AttrType
from .event import CURRENT, EXPIRED, RESET


class AttributeAggregator:
    name = ""

    def __init__(self, input_type: Optional[AttrType]):
        self.input_type = input_type

    @property
    def output_type(self) -> AttrType:
        raise NotImplementedError

    def process(self, values: Optional[np.ndarray],
                types: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def state(self) -> dict:
        raise NotImplementedError

    def restore(self, state: dict):
        raise NotImplementedError


def _signs(types: np.ndarray) -> np.ndarray:
    return np.where(types == CURRENT, 1,
                    np.where(types == EXPIRED, -1, 0)).astype(np.int64)


def _has_reset(types: np.ndarray) -> bool:
    return bool((types == RESET).any())


class _CumulativeAggregator(AttributeAggregator):
    """Base for aggregators expressible as running sums of signed deltas."""

    def _segments(self, values, types):
        """Split on RESET rows; yields (slice, is_reset_row_mask)."""
        resets = np.flatnonzero(types == RESET)
        start = 0
        for r in resets:
            yield start, int(r)
            self._reset()
            start = int(r) + 1
        yield start, len(types)

    def _reset(self):
        raise NotImplementedError


class SumAggregator(_CumulativeAggregator):
    name = "sum"

    def __init__(self, input_type):
        super().__init__(input_type)
        self._float = input_type in (AttrType.FLOAT, AttrType.DOUBLE)
        self.total = 0.0 if self._float else 0

    @property
    def output_type(self):
        return AttrType.DOUBLE if self._float else AttrType.LONG

    def _reset(self):
        self.total = 0.0 if self._float else 0

    def process(self, values, types):
        dt = np.float64 if self._float else np.int64
        out = np.empty(len(types), dt)
        for a, b in self._segments(values, types):
            if b > a:
                delta = np.asarray(values[a:b], dt) * _signs(types[a:b])
                run = self.total + np.cumsum(delta)
                out[a:b] = run
                self.total = dt(run[-1]).item()
        # rows at RESET positions output the reset value
        out[types == RESET] = self.total
        return out

    def state(self):
        return {"total": self.total}

    def restore(self, s):
        self.total = s["total"]


class CountAggregator(_CumulativeAggregator):
    name = "count"

    def __init__(self, input_type=None):
        super().__init__(input_type)
        self.count = 0

    @property
    def output_type(self):
        return AttrType.LONG

    def _reset(self):
        self.count = 0

    def process(self, values, types):
        out = np.empty(len(types), np.int64)
        for a, b in self._segments(values, types):
            if b > a:
                run = self.count + np.cumsum(_signs(types[a:b]))
                out[a:b] = run
                self.count = int(run[-1])
        out[types == RESET] = self.count
        return out

    def state(self):
        return {"count": self.count}

    def restore(self, s):
        self.count = s["count"]


class AvgAggregator(_CumulativeAggregator):
    name = "avg"

    def __init__(self, input_type):
        super().__init__(input_type)
        self.total = 0.0
        self.count = 0

    @property
    def output_type(self):
        return AttrType.DOUBLE

    def _reset(self):
        self.total, self.count = 0.0, 0

    def process(self, values, types):
        out = np.empty(len(types), np.float64)
        for a, b in self._segments(values, types):
            if b > a:
                s = _signs(types[a:b])
                run_t = self.total + np.cumsum(
                    np.asarray(values[a:b], np.float64) * s)
                run_c = self.count + np.cumsum(s)
                with np.errstate(divide="ignore", invalid="ignore"):
                    out[a:b] = np.where(run_c > 0, run_t / np.maximum(run_c, 1),
                                        0.0)
                self.total = float(run_t[-1])
                self.count = int(run_c[-1])
        out[types == RESET] = 0.0
        return out

    def state(self):
        return {"total": self.total, "count": self.count}

    def restore(self, s):
        self.total, self.count = s["total"], s["count"]


class StdDevAggregator(_CumulativeAggregator):
    name = "stddev"

    def __init__(self, input_type):
        super().__init__(input_type)
        self.n = 0
        self.s1 = 0.0
        self.s2 = 0.0

    @property
    def output_type(self):
        return AttrType.DOUBLE

    def _reset(self):
        self.n, self.s1, self.s2 = 0, 0.0, 0.0

    def process(self, values, types):
        out = np.empty(len(types), np.float64)
        for a, b in self._segments(values, types):
            if b > a:
                sg = _signs(types[a:b])
                v = np.asarray(values[a:b], np.float64)
                n = self.n + np.cumsum(sg)
                s1 = self.s1 + np.cumsum(v * sg)
                s2 = self.s2 + np.cumsum(v * v * sg)
                with np.errstate(divide="ignore", invalid="ignore"):
                    mean = np.where(n > 0, s1 / np.maximum(n, 1), 0.0)
                    var = np.where(n > 0, s2 / np.maximum(n, 1) - mean * mean,
                                   0.0)
                out[a:b] = np.sqrt(np.maximum(var, 0.0))
                self.n, self.s1, self.s2 = int(n[-1]), float(s1[-1]), float(s2[-1])
        out[types == RESET] = 0.0
        return out

    def state(self):
        return {"n": self.n, "s1": self.s1, "s2": self.s2}

    def restore(self, s):
        self.n, self.s1, self.s2 = s["n"], s["s1"], s["s2"]


class _HeapExtremum(AttributeAggregator):
    """min/max with expiry: lazy-deletion heap + live counter."""
    sign = 1  # 1 = min, -1 = max

    def __init__(self, input_type):
        super().__init__(input_type)
        self.heap: List[float] = []
        self.live: Counter = Counter()

    @property
    def output_type(self):
        return self.input_type

    def _push(self, v):
        heapq.heappush(self.heap, self.sign * v)
        self.live[v] += 1

    def _remove(self, v):
        self.live[v] -= 1
        if self.live[v] <= 0:
            del self.live[v]

    def _top(self):
        while self.heap:
            v = self.sign * self.heap[0]
            if self.live.get(v, 0) > 0:
                return v
            heapq.heappop(self.heap)
        return None

    def process(self, values, types):
        from .event import dtype_for
        dt = dtype_for(self.input_type)
        out = np.zeros(len(types), dt)
        vals = values
        for i in range(len(types)):
            t = types[i]
            if t == CURRENT:
                self._push(vals[i].item() if hasattr(vals[i], "item")
                           else vals[i])
            elif t == EXPIRED:
                self._remove(vals[i].item() if hasattr(vals[i], "item")
                             else vals[i])
            elif t == RESET:
                self.heap.clear()
                self.live.clear()
            top = self._top()
            out[i] = top if top is not None else 0
        return out

    def state(self):
        return {"live": dict(self.live)}

    def restore(self, s):
        self.live = Counter(s["live"])
        self.heap = [self.sign * v for v in self.live]
        heapq.heapify(self.heap)


class MinAggregator(_HeapExtremum):
    name = "min"
    sign = 1


class MaxAggregator(_HeapExtremum):
    name = "max"
    sign = -1


class MinForeverAggregator(AttributeAggregator):
    name = "minforever"
    _cmp = np.minimum

    def __init__(self, input_type):
        super().__init__(input_type)
        self.best = None

    @property
    def output_type(self):
        return self.input_type

    def process(self, values, types):
        from .event import dtype_for
        dt = dtype_for(self.input_type)
        v = np.asarray(values, dt).copy()
        # forever-variants consider every data event, even EXPIRED
        # (reference Min/MaxForeverAttributeAggregator processRemove also
        # updates toward the extremum)
        data = (types == CURRENT) | (types == EXPIRED)
        neutral = np.iinfo(dt).max if np.issubdtype(dt, np.integer) \
            else np.inf
        if type(self)._cmp is np.maximum:
            neutral = np.iinfo(dt).min if np.issubdtype(dt, np.integer) \
                else -np.inf
        v[~data] = neutral
        if self.best is not None:
            v = np.concatenate([[dt(self.best)], v])
            out = type(self)._cmp.accumulate(v)[1:]
        else:
            out = type(self)._cmp.accumulate(v)
        self.best = out[-1].item() if len(out) else self.best
        return out

    def state(self):
        return {"best": self.best}

    def restore(self, s):
        self.best = s["best"]


class MaxForeverAggregator(MinForeverAggregator):
    name = "maxforever"
    _cmp = np.maximum


class DistinctCountAggregator(AttributeAggregator):
    name = "distinctcount"

    def __init__(self, input_type):
        super().__init__(input_type)
        self.counter: Counter = Counter()

    @property
    def output_type(self):
        return AttrType.LONG

    def process(self, values, types):
        out = np.empty(len(types), np.int64)
        vals = values
        for i in range(len(types)):
            t = types[i]
            v = vals[i].item() if hasattr(vals[i], "item") else vals[i]
            if t == CURRENT:
                self.counter[v] += 1
            elif t == EXPIRED:
                self.counter[v] -= 1
                if self.counter[v] <= 0:
                    del self.counter[v]
            elif t == RESET:
                self.counter.clear()
            out[i] = len(self.counter)
        return out

    def state(self):
        return {"counter": dict(self.counter)}

    def restore(self, s):
        self.counter = Counter(s["counter"])


class BoolAndAggregator(AttributeAggregator):
    """and(bool) — true while every live event is true
    (reference AndAttributeAggregator: counts of false)."""
    name = "and"

    def __init__(self, input_type):
        super().__init__(input_type)
        self.false_count = 0
        self.true_count = 0

    @property
    def output_type(self):
        return AttrType.BOOL

    def process(self, values, types):
        out = np.empty(len(types), np.bool_)
        v = np.asarray(values, bool)
        for i in range(len(types)):
            t = types[i]
            if t == CURRENT:
                if v[i]:
                    self.true_count += 1
                else:
                    self.false_count += 1
            elif t == EXPIRED:
                if v[i]:
                    self.true_count -= 1
                else:
                    self.false_count -= 1
            elif t == RESET:
                self.false_count = self.true_count = 0
            out[i] = self._value()
        return out

    def _value(self):
        return self.false_count == 0 and self.true_count > 0

    def state(self):
        return {"f": self.false_count, "t": self.true_count}

    def restore(self, s):
        self.false_count, self.true_count = s["f"], s["t"]


class BoolOrAggregator(BoolAndAggregator):
    name = "or"

    def _value(self):
        return self.true_count > 0


class UnionSetAggregator(AttributeAggregator):
    name = "unionset"

    def __init__(self, input_type):
        super().__init__(input_type)
        self.counter: Counter = Counter()

    @property
    def output_type(self):
        return AttrType.OBJECT

    def process(self, values, types):
        out = np.empty(len(types), object)
        for i in range(len(types)):
            t = types[i]
            v = values[i]
            items = v if isinstance(v, (set, frozenset, list, tuple)) else [v]
            if t == CURRENT:
                for x in items:
                    self.counter[x] += 1
            elif t == EXPIRED:
                for x in items:
                    self.counter[x] -= 1
                    if self.counter[x] <= 0:
                        del self.counter[x]
            elif t == RESET:
                self.counter.clear()
            out[i] = set(self.counter.keys())
        return out

    def state(self):
        return {"counter": {repr(k): v for k, v in self.counter.items()}}

    def restore(self, s):
        # keys were repr()'d for serialisation; best-effort literal restore
        import ast
        c = Counter()
        for k, v in s["counter"].items():
            try:
                c[ast.literal_eval(k)] = v
            except (ValueError, SyntaxError):
                c[k] = v
        self.counter = c


AGGREGATORS: Dict[str, Type[AttributeAggregator]] = {
    "sum": SumAggregator,
    "avg": AvgAggregator,
    "count": CountAggregator,
    "distinctcount": DistinctCountAggregator,
    "min": MinAggregator,
    "max": MaxAggregator,
    "minforever": MinForeverAggregator,
    "maxforever": MaxForeverAggregator,
    "stddev": StdDevAggregator,
    "and": BoolAndAggregator,
    "or": BoolOrAggregator,
    "unionset": UnionSetAggregator,
}


def is_aggregator(namespace: Optional[str], name: str, nargs: int) -> bool:
    if namespace:
        return False
    low = name.lower()
    if low not in AGGREGATORS:
        return False
    # min/max with >1 args are the scalar minimum/maximum functions
    if low in ("min", "max") and nargs > 1:
        return False
    return True
