"""Execution contexts.

(reference: config/SiddhiContext.java — shared across apps: extensions,
persistence store, config manager; config/SiddhiAppContext.java — per app:
executors, ThreadBarrier, SnapshotService, TimestampGenerator, scheduler list,
statistics; config/SiddhiQueryContext.java — per query.)
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from .scheduler import Scheduler
from .statistics import StatisticsManager
from .timestamp import TimestampGenerator


class SiddhiContext:
    """Shared, manager-level context."""

    def __init__(self):
        self.extensions: Dict[str, Any] = {}
        self.persistence_store = None
        self.incremental_persistence_store = None
        self.error_store = None             # manager-level default
        self.config_manager = None
        self.attributes: Dict[str, Any] = {}

    def set_extension(self, name: str, impl):
        self.extensions[name.lower()] = impl

    def get_extension(self, name: str):
        return self.extensions.get(name.lower())


class ThreadBarrier:
    """Ingestion gate: snapshots lock it so no events are in flight while state
    is captured (reference util/ThreadBarrier.java)."""

    def __init__(self):
        self._lock = threading.RLock()

    def pass_through(self):
        with self._lock:
            pass

    def lock(self):
        self._lock.acquire()

    def unlock(self):
        self._lock.release()


class SiddhiAppContext:
    def __init__(self, siddhi_context: SiddhiContext, name: str):
        self.siddhi_context = siddhi_context
        self.name = name
        self.timestamp_generator = TimestampGenerator()
        self.scheduler = Scheduler(self.timestamp_generator)
        self.thread_barrier = ThreadBarrier()
        self.snapshot_service = None        # set by runtime builder
        self.statistics_manager: Optional[StatisticsManager] = None
        self.stats_enabled = False
        self.playback = False
        self.root_metrics_level = 0
        self.script_functions: Dict[str, Any] = {}
        self.exception_listeners: List[Any] = []
        self.runtime = None                 # back-pointer (set by runtime)
        self.watchdog = None                # DispatchWatchdog (core/overload)
        self.async_mode = False

    def current_time(self) -> int:
        return self.timestamp_generator.current_time()


class SiddhiQueryContext:
    def __init__(self, app_ctx: SiddhiAppContext, query_name: str,
                 partition_id: Optional[str] = None):
        self.app_ctx = app_ctx
        self.name = query_name
        self.partition_id = partition_id
        self.latency_tracker = None
