"""Columnar event batch model.

TPU-native replacement for the reference's event model
(siddhi-core event/: Event.java, ComplexEvent.java, StreamEvent.java,
StateEvent.java, ComplexEventChunk.java, StreamEventPool.java).

The reference represents in-flight events as pooled, linked-list node objects
(`StreamEvent.next`) walked one at a time.  Here an event micro-batch is a
struct-of-arrays `EventChunk`: one numpy/JAX column per attribute + a timestamp
column + an event-type lane implementing the CURRENT/EXPIRED/TIMER/RESET
temporal algebra (reference ComplexEvent.Type, docs/siddhi-architecture.md:243-259).
Chunks are what processors exchange; device kernels consume the numeric columns
directly (strings are dictionary-encoded before shipping to device).
"""
from __future__ import annotations

import time

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..query_api.definition import AbstractDefinition, AttrType
from .profiling import rim_stats

_RIM = rim_stats()

# ComplexEvent.Type lanes
CURRENT = 0
EXPIRED = 1
TIMER = 2
RESET = 3

TYPE_NAMES = {CURRENT: "CURRENT", EXPIRED: "EXPIRED", TIMER: "TIMER",
              RESET: "RESET"}

_DTYPES = {
    AttrType.INT: np.int32,
    AttrType.LONG: np.int64,
    AttrType.FLOAT: np.float32,
    AttrType.DOUBLE: np.float64,
    AttrType.BOOL: np.bool_,
    AttrType.STRING: object,
    AttrType.OBJECT: object,
}


def dtype_for(t: AttrType):
    return _DTYPES[t]


def zero_for(t: AttrType):
    if t in (AttrType.STRING, AttrType.OBJECT):
        return None
    return dtype_for(t)(0)


class Event:
    """User-facing event (reference event/Event.java: timestamp + Object[]).

    A plain ``__slots__`` class rather than a dataclass: the legacy
    per-event rim builds millions of these per second and the dataclass
    constructor is ~1.6x slower.  Like the eq-without-frozen dataclass it
    replaced, instances are unhashable."""

    __slots__ = ("timestamp", "data")

    def __init__(self, timestamp: int, data: List[Any]):
        self.timestamp = timestamp
        self.data = data

    def __iter__(self):
        return iter(self.data)

    def __eq__(self, other):
        return (other.__class__ is Event and
                self.timestamp == other.timestamp and
                self.data == other.data)

    def __repr__(self):
        return f"Event(timestamp={self.timestamp!r}, data={self.data!r})"


class EventChunk:
    """A columnar micro-batch of events flowing through a query pipeline.

    `qualified` (optional) carries per-(stream_ref, index) attribute columns
    for multi-stream events — the columnar analogue of the reference's
    StateEvent (join/pattern output rows, event/state/StateEvent.java)."""

    __slots__ = ("timestamps", "types", "columns", "names", "qualified",
                 "is_batch", "ledger_ns")

    def __init__(self, names: Sequence[str], timestamps: np.ndarray,
                 types: np.ndarray, columns: Dict[str, np.ndarray],
                 qualified: Optional[Dict] = None, is_batch: bool = False):
        self.names = list(names)
        self.timestamps = timestamps
        self.types = types
        self.columns = columns
        self.qualified = qualified
        # batch-marked chunks summarize in aggregated selects (reference
        # ComplexEventChunk.isBatch, set by tumbling-batch windows); the
        # transforms below all carry it so intervening processors (filters,
        # stream functions) don't strip batch semantics
        self.is_batch = is_batch
        # latency-ledger boundary stamp (monotonic ns): set at ingress
        # admit / junction enqueue, consumed at the next stage boundary
        # (queue-wait and dispatch-gap attribution, core/ledger.py); NOT
        # carried by transforms — a derived chunk is a new timeline
        self.ledger_ns = None

    # ------------------------------------------------------------ constructors

    @staticmethod
    def empty(names: Sequence[str]) -> "EventChunk":
        return EventChunk(names, np.empty(0, np.int64), np.empty(0, np.int8),
                          {n: np.empty(0, object) for n in names})

    @staticmethod
    def from_rows(definition: AbstractDefinition, rows: Sequence[Sequence[Any]],
                  timestamps: Sequence[int],
                  types: Optional[Sequence[int]] = None) -> "EventChunk":
        n = len(rows)
        names = definition.attribute_names
        cols: Dict[str, np.ndarray] = {}
        for j, attr in enumerate(definition.attributes):
            dt = dtype_for(attr.type)
            if dt is object:
                arr = np.empty(n, object)
                for i, r in enumerate(rows):
                    arr[i] = r[j]
            else:
                try:
                    arr = np.asarray([r[j] for r in rows], dtype=dt)
                except (TypeError, ValueError):
                    # None payloads fall back to zeros (null lane not modelled
                    # per column; Siddhi nulls only arise from outer joins /
                    # absent captures which are handled there)
                    arr = np.asarray(
                        [0 if r[j] is None else r[j] for r in rows], dtype=dt)
            cols[attr.name] = arr
        ts = np.asarray(timestamps, np.int64)
        tp = (np.asarray(types, np.int8) if types is not None
              else np.zeros(n, np.int8))
        return EventChunk(names, ts, tp, cols)

    @staticmethod
    def from_columns(names: Sequence[str], timestamps: np.ndarray,
                     columns: Dict[str, np.ndarray],
                     types: Optional[np.ndarray] = None) -> "EventChunk":
        if types is None:
            types = np.zeros(len(timestamps), np.int8)
        return EventChunk(names, np.asarray(timestamps, np.int64), types,
                          {k: np.asarray(v) for k, v in columns.items()})

    # ------------------------------------------------------------ accessors

    def __len__(self) -> int:
        return len(self.timestamps)

    @property
    def is_empty(self) -> bool:
        return len(self.timestamps) == 0

    def col(self, name: str) -> np.ndarray:
        return self.columns[name]

    def row(self, i: int) -> Tuple[int, List[Any]]:
        return int(self.timestamps[i]), [_to_py(self.columns[n][i])
                                         for n in self.names]

    def to_events(self) -> List[Event]:
        # vectorized row materialization: ndarray.tolist() converts each
        # column to python scalars in C, and zip/map build the row lists
        # and Event objects without per-row bytecode.  Every call feeds
        # the always-on events-materialized counter — the columnar fast
        # path is asserted to never reach here (bench --smoke rim phase)
        n = len(self)
        if n == 0:
            return []
        _RIM.events_materialized += n
        ts_list = self.timestamps.tolist()
        col_lists = [self.columns[name].tolist() for name in self.names]
        return list(map(Event, ts_list, map(list, zip(*col_lists))))

    # ------------------------------------------------------------ transforms

    def mask(self, m: np.ndarray) -> "EventChunk":
        return EventChunk(self.names, self.timestamps[m], self.types[m],
                          {k: v[m] for k, v in self.columns.items()},
                          _sel_qualified(self.qualified, m), self.is_batch)

    def take(self, idx: np.ndarray) -> "EventChunk":
        return EventChunk(self.names, self.timestamps[idx], self.types[idx],
                          {k: v[idx] for k, v in self.columns.items()},
                          _sel_qualified(self.qualified, idx), self.is_batch)

    def slice(self, start: int, stop: int) -> "EventChunk":
        return EventChunk(self.names, self.timestamps[start:stop],
                          self.types[start:stop],
                          {k: v[start:stop] for k, v in self.columns.items()},
                          _sel_qualified(self.qualified, slice(start, stop)),
                          self.is_batch)

    def with_types(self, t: int) -> "EventChunk":
        return EventChunk(self.names, self.timestamps,
                          np.full(len(self), t, np.int8), self.columns,
                          self.qualified, self.is_batch)

    def with_timestamps(self, ts: np.ndarray) -> "EventChunk":
        return EventChunk(self.names, np.asarray(ts, np.int64), self.types,
                          self.columns, self.qualified, self.is_batch)

    def rename(self, names: Sequence[str]) -> "EventChunk":
        assert len(names) == len(self.names)
        return EventChunk(list(names), self.timestamps, self.types,
                          {new: self.columns[old]
                           for old, new in zip(self.names, names)},
                          self.qualified, self.is_batch)

    def only(self, *event_types: int) -> "EventChunk":
        m = (self.types == event_types[0] if len(event_types) == 1
             else np.isin(self.types, event_types))
        if m.all():
            # all-match fast path: chunks are treated as immutable values
            # by every processor, so the filter can return self — match
            # slabs are all-CURRENT and this sits on the delivery rim
            return self
        return self.mask(m)

    def copy(self) -> "EventChunk":
        return EventChunk(self.names, self.timestamps.copy(), self.types.copy(),
                          {k: v.copy() for k, v in self.columns.items()},
                          _sel_qualified(self.qualified, slice(None)),
                          self.is_batch)

    @staticmethod
    def concat(chunks: Sequence["EventChunk"]) -> "EventChunk":
        chunks = [c for c in chunks if c is not None and not c.is_empty]
        if not chunks:
            return EventChunk.empty([])
        if len(chunks) == 1:
            return chunks[0]
        names = chunks[0].names
        qualified = None
        if any(c.qualified is not None for c in chunks):
            qualified = {}
            keys = set()
            for c in chunks:
                keys |= set((c.qualified or {}).keys())
            for key in keys:
                attrs = set()
                for c in chunks:
                    attrs |= set((c.qualified or {}).get(key, {}).keys())
                qualified[key] = {
                    a: np.concatenate([
                        (c.qualified or {}).get(key, {}).get(
                            a, np.full(len(c), None, object))
                        for c in chunks])
                    for a in attrs}
        return EventChunk(
            names,
            np.concatenate([c.timestamps for c in chunks]),
            np.concatenate([c.types for c in chunks]),
            {n: np.concatenate([c.columns[n] for c in chunks]) for n in names},
            qualified,
            # conservative: merging a batch flush with non-batch traffic
            # (e.g. async junction re-batching) must not batch-mark the result
            all(c.is_batch for c in chunks))

    def __repr__(self):
        return (f"EventChunk(n={len(self)}, names={self.names}, "
                f"types={[TYPE_NAMES.get(int(t), t) for t in self.types[:8]]})")


class LazyEvents:
    """Deferred chunk→``Event[]`` materialization for cold paths.

    The legacy ``StreamCallback``/``QueryCallback`` rim, the sink retry
    queue and the error stores carry "the events" of a chunk; handing
    them this wrapper instead of an eager ``to_events()`` keeps every
    path that never touches an element zero-materialization — the Event
    objects (and the counter increment) only exist on first element
    access.  Sized/iterable/indexable like the list it stands in for."""

    __slots__ = ("chunk", "_events")

    def __init__(self, chunk: EventChunk):
        self.chunk = chunk
        self._events: Optional[List[Event]] = None

    def materialize(self) -> List[Event]:
        if self._events is None:
            t0 = time.perf_counter_ns()
            self._events = self.chunk.to_events()
            _RIM.rim_ns += time.perf_counter_ns() - t0
        return self._events

    def __len__(self) -> int:
        return len(self.chunk)

    def __bool__(self) -> bool:
        return len(self.chunk) > 0

    def __iter__(self):
        return iter(self.materialize())

    def __getitem__(self, i):
        return self.materialize()[i]

    def __repr__(self):
        # must NOT materialize: repr of a pending view is a debugging /
        # logging path and the zero-copy property (events_materialized
        # == 0) has to survive it
        state = ("pending" if self._events is None
                 else f"materialized={len(self._events)}")
        return f"LazyEvents(n={len(self.chunk)}, {state})"


def _sel_qualified(q, sel):
    if q is None:
        return None
    return {key: {a: col[sel] for a, col in d.items()} for key, d in q.items()}


def _to_py(v):
    """numpy scalar → python scalar for user-facing Event payloads."""
    if isinstance(v, np.generic):
        return v.item()
    return v


def timer_chunk(names: Sequence[str], timestamp: int) -> EventChunk:
    """A single TIMER event (reference: Scheduler-injected timer StreamEvents,
    util/Scheduler.java:180-211).  Data columns are empty placeholders."""
    cols = {}
    for n in names:
        cols[n] = np.array([None], object)
    return EventChunk(names, np.asarray([timestamp], np.int64),
                      np.asarray([TIMER], np.int8), cols)
