"""Device/kernel profiling.

Wraps every jitted step function the planner and the plan/* compilers
build (NFA step, bank step, egress pack, dwin/gagg/wagg steps, device
filter program) in a ``ProfiledKernel`` that — when profiling is enabled
— records per kernel:

  * call count and host-side dispatch time,
  * compile/retrace count (via the jitted callable's ``_cache_size()``
    when JAX exposes it, argument-signature tracking otherwise) — so a
    BENCH regression can be attributed to "NFA step retraced 40x"
    instead of guessed at,
  * blocked device time (``jax.block_until_ready`` deltas) when
    ``device_timing`` is on — this serializes the pipeline, so it is a
    separate, opt-in level,
  * batch sizes (events carried per call, from a per-site hint) and
    host→device transfer bytes (host-resident ndarray arguments);
    device→host bytes are reported by the egress/retire sites via
    ``record_d2h``.

Disabled (the default) the wrapper is one attribute check + a passthrough
call per *block* — zero extra device syncs, nothing registered.  The
profiler is process-global (kernels are built by standalone compiled
objects as well as app runtimes); ``@app:statistics`` enables it for the
process, ``enable_profiling()`` does so explicitly.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional


class KernelStats:
    __slots__ = ("name", "calls", "compile_count", "dispatch_ns",
                 "device_ns", "batch_events", "h2d_bytes", "d2h_bytes",
                 "max_batch", "signatures", "live_bytes", "scan_ticks",
                 "batch_b", "dispatch_count")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.compile_count = 0
        self.dispatch_ns = 0
        self.device_ns = 0
        self.batch_events = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.max_batch = 0
        self.signatures: set = set()
        # persistent device state bytes (a gauge, not a counter): set by
        # the carry-placement sites; the measured side of the static cost
        # model's HBM prediction (analysis/cost_model.py, bench.py)
        self.live_bytes = 0
        # sequential scan ticks issued (counter) and events-per-tick B
        # (gauge) — set by scan-shaped kernels via a ticks_of hint; the
        # T→⌈T/B⌉ reduction of the fatter-tick NFA restructuring shows up
        # here (and is asserted in tests/test_nfa_batch.py)
        self.scan_ticks = 0
        self.batch_b = 0
        # device executions launched (counter).  Usually == calls, but a
        # site that launches several executables per wrapper call (or
        # none, e.g. a cache hit) can correct it via record_dispatches;
        # the C→1 claim of the stacked bank is asserted against this
        self.dispatch_count = 0

    def as_dict(self) -> Dict[str, Any]:
        return {"calls": self.calls,
                "dispatch_count": self.dispatch_count,
                "compile_count": self.compile_count,
                "dispatch_time_s": self.dispatch_ns / 1e9,
                "device_time_s": self.device_ns / 1e9,
                "batch_events": self.batch_events,
                "max_batch": self.max_batch,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "live_bytes": self.live_bytes,
                "scan_ticks": self.scan_ticks,
                "batch_b": self.batch_b}


def _signature(args) -> tuple:
    """Shape/dtype signature of the positional args — retrace detector
    for callables that don't expose a compile-cache size."""
    import numpy as np
    sig: List[Any] = []
    for a in args:
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            sig.append((tuple(a.shape), str(a.dtype)))
        elif isinstance(a, dict):
            sig.append(tuple(sorted(
                (k, tuple(v.shape), str(v.dtype))
                for k, v in a.items()
                if hasattr(v, "shape") and hasattr(v, "dtype"))))
        elif isinstance(a, (int, float, bool, str, type(None))):
            sig.append(a)
        elif isinstance(a, np.ndarray):
            sig.append((tuple(a.shape), str(a.dtype)))
        else:
            sig.append(type(a).__name__)
    return tuple(sig)


def _host_bytes(args) -> int:
    """nbytes of host-resident ndarray leaves (≈ the H2D transfer the
    call implies; device-resident jax arrays transfer nothing)."""
    import numpy as np
    total = 0
    stack = list(args)
    while stack:
        a = stack.pop()
        if isinstance(a, np.ndarray):
            total += a.nbytes
        elif isinstance(a, dict):
            stack.extend(a.values())
        elif isinstance(a, (list, tuple)):
            stack.extend(a)
    return total


class ProfiledKernel:
    """Transparent wrapper around a jitted callable."""

    __slots__ = ("fn", "stats", "profiler", "batch_of", "ticks_of",
                 "_cache_size_fn", "_last_cs")

    def __init__(self, fn: Callable, stats: KernelStats,
                 profiler: "KernelProfiler",
                 batch_of: Optional[Callable[..., int]] = None,
                 ticks_of: Optional[Callable[..., tuple]] = None):
        self.fn = fn
        self.stats = stats
        self.profiler = profiler
        self.batch_of = batch_of
        self.ticks_of = ticks_of
        self._cache_size_fn = getattr(fn, "_cache_size", None)
        self._last_cs = 0

    def __call__(self, *args, **kwargs):
        prof = self.profiler
        if not prof.enabled:
            return self.fn(*args, **kwargs)
        st = self.stats
        t0 = time.perf_counter_ns()
        out = self.fn(*args, **kwargs)
        t1 = time.perf_counter_ns()
        compiled = False
        with prof._lock:
            st.calls += 1
            st.dispatch_count += 1
            st.dispatch_ns += t1 - t0
            if self._cache_size_fn is not None:
                try:
                    # per-wrapper delta: stats with one name can span
                    # several rebuilt jit instances (slot growth rebuilds
                    # the step), each with its own compile cache
                    cs = self._cache_size_fn()
                    if cs > self._last_cs:
                        compiled = True
                        st.compile_count += cs - self._last_cs
                        self._last_cs = cs
                except Exception:   # noqa: BLE001 — fall back to sigs
                    self._cache_size_fn = None
            if self._cache_size_fn is None:
                sig = _signature(args)
                if sig not in st.signatures:
                    st.signatures.add(sig)
                    st.compile_count += 1
                    compiled = True
            if self.batch_of is not None:
                try:
                    b = int(self.batch_of(*args, **kwargs))
                    st.batch_events += b
                    if b > st.max_batch:
                        st.max_batch = b
                except Exception:   # noqa: BLE001 — hint only
                    pass
            if self.ticks_of is not None:
                try:
                    ticks, bb = self.ticks_of(*args, **kwargs)
                    st.scan_ticks += int(ticks)
                    st.batch_b = int(bb)
                except Exception:   # noqa: BLE001 — hint only
                    pass
            st.h2d_bytes += _host_bytes(args)
        from .tracing import tracer
        tr = tracer()
        if tr.enabled:
            if compiled:
                tr.instant(f"jit-compile:{st.name}", cat="jit")
            tr.complete(f"kernel.{st.name}", t0, t1, cat="kernel")
        if prof.device_timing:
            import jax
            t2 = time.perf_counter_ns()
            out = jax.block_until_ready(out)
            with prof._lock:
                st.device_ns += (t1 - t0) + (time.perf_counter_ns() - t2)
        return out


class KernelProfiler:
    def __init__(self):
        self.kernels: Dict[str, KernelStats] = {}
        # per-app {name: [dispatches, ingest_blocks]} — the runtimes
        # report the device-dispatch delta of every ingest block here;
        # the exported gauge is the running dispatches/block average
        self.app_blocks: Dict[str, List[int]] = {}
        self.enabled = False
        self.device_timing = False
        self._lock = threading.Lock()

    # ------------------------------------------------------------ control

    def enable(self, device_timing: bool = False):
        self.enabled = True
        self.device_timing = device_timing

    def disable(self):
        self.enabled = False
        self.device_timing = False

    def reset(self):
        with self._lock:
            self.kernels.clear()
            self.app_blocks.clear()

    # ------------------------------------------------------------ recording

    def stats(self, name: str) -> KernelStats:
        with self._lock:
            return self.kernels.setdefault(name, KernelStats(name))

    def wrap(self, name: str, fn: Callable,
             batch_of: Optional[Callable[..., int]] = None,
             ticks_of: Optional[Callable[..., tuple]] = None
             ) -> ProfiledKernel:
        return ProfiledKernel(fn, self.stats(name), self, batch_of,
                              ticks_of)

    def record_d2h(self, name: str, nbytes: int):
        if not self.enabled:
            return
        self.stats(name).d2h_bytes += int(nbytes)

    def record_dispatches(self, name: str, n: int):
        """Adjust a kernel's device-execution counter out-of-band: a
        site that re-launches (egress overflow re-pack) adds, a cached
        result subtracts nothing — __call__ already counted one."""
        if not self.enabled:
            return
        self.stats(name).dispatch_count += int(n)

    def total_dispatches(self) -> int:
        """Sum of every kernel's dispatch_count — the runtimes diff this
        around an ingest block to report dispatches/block per app."""
        with self._lock:
            return sum(st.dispatch_count for st in self.kernels.values())

    def total_scan_ticks(self) -> int:
        """Sum of every kernel's scan_ticks — the flight recorder diffs
        this around an ingest block for the per-block record."""
        with self._lock:
            return sum(st.scan_ticks for st in self.kernels.values())

    def total_dispatch_ns(self) -> int:
        """Sum of every kernel's host-side dispatch time — diffed per
        ingest block for the flight ring's rim-vs-kernel ms split."""
        with self._lock:
            return sum(st.dispatch_ns for st in self.kernels.values())

    def record_app_block(self, app: str, dispatches: int):
        """One ingest block for `app` cost `dispatches` device launches."""
        if not self.enabled:
            return
        with self._lock:
            tot = self.app_blocks.setdefault(app, [0, 0])
            tot[0] += int(dispatches)
            tot[1] += 1

    def dispatches_per_block(self, app: str) -> float:
        with self._lock:
            tot = self.app_blocks.get(app)
        if not tot or not tot[1]:
            return 0.0
        return tot[0] / tot[1]

    def set_live_bytes(self, name: str, nbytes: int):
        """Gauge: current persistent device state owned by a kernel
        (carry slabs, rings, capture banks).  Overwritten on growth/
        restore so it always reflects the live footprint."""
        if not self.enabled:
            return
        self.stats(name).live_bytes = int(nbytes)

    # ------------------------------------------------------------ reads

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {name: st.as_dict() for name, st in self.kernels.items()}

    def prometheus_lines(self) -> List[str]:
        lines: List[str] = []
        for name, st in list(self.kernels.items()):
            lb = '{kernel="' + name + '"}'
            lines.append(f"siddhi_kernel_calls_total{lb} {st.calls}")
            lines.append(
                f"siddhi_kernel_compile_count{lb} {st.compile_count}")
            lines.append("siddhi_kernel_device_time_seconds_total"
                         f"{lb} {st.device_ns / 1e9:.9g}")
            lines.append("siddhi_kernel_dispatch_time_seconds_total"
                         f"{lb} {st.dispatch_ns / 1e9:.9g}")
            lines.append(f"siddhi_kernel_h2d_bytes_total{lb} {st.h2d_bytes}")
            lines.append(f"siddhi_kernel_d2h_bytes_total{lb} {st.d2h_bytes}")
            lines.append(f"siddhi_kernel_live_bytes{lb} {st.live_bytes}")
            lines.append(
                f"siddhi_kernel_batch_events_total{lb} {st.batch_events}")
            lines.append(
                f"siddhi_kernel_scan_ticks_total{lb} {st.scan_ticks}")
            lines.append(f"siddhi_kernel_batch_b{lb} {st.batch_b}")
            lines.append(
                f"siddhi_kernel_dispatches_total{lb} {st.dispatch_count}")
        for app, (disp, blocks) in list(self.app_blocks.items()):
            if not blocks:
                continue
            lines.append('siddhi_app_dispatches_per_block{app="' + app +
                         f'"}} {disp / blocks:.9g}')
        return lines


class RimStats:
    """Always-on host-rim accounting (the measured side of the columnar
    end-to-end claim).  Two process-global counters:

      * ``events_materialized`` — per-event ``Event`` objects built from
        columnar chunks (``EventChunk.to_events``).  Zero across a
        columnar ingest→match→columnar-sink run IS the zero-copy
        property; bench ``--smoke`` asserts it and
        ``--fail-on-rim-materialize`` gates on it.
      * ``rim_ns`` — host-rim wall time (ingress conversion/validation +
        egress callback/sink delivery), so the flight ring can carry a
        per-block rim-vs-kernel ms split.

    Unlike ``KernelProfiler`` this is NOT gated on ``enabled`` — the
    counters must hold even when @app:statistics is off (the smoke gate
    runs unprofiled).  Increments are plain int adds under the GIL: the
    materialization counter's contract is exact on single-threaded
    paths and monotone everywhere, which is all the gates need."""

    __slots__ = ("events_materialized", "rim_ns")

    def __init__(self):
        self.events_materialized = 0
        self.rim_ns = 0

    # hot paths add to the attributes directly; these are for readers
    def snapshot(self) -> Dict[str, Any]:
        return {"events_materialized": self.events_materialized,
                "host_rim_seconds": self.rim_ns / 1e9}

    def reset(self) -> None:
        self.events_materialized = 0
        self.rim_ns = 0

    def prometheus_lines(self) -> List[str]:
        return [
            f"siddhi_events_materialized_total {self.events_materialized}",
            f"siddhi_host_rim_seconds_total {self.rim_ns / 1e9:.9g}",
        ]


_GLOBAL = KernelProfiler()
_RIM = RimStats()


def profiler() -> KernelProfiler:
    return _GLOBAL


def rim_stats() -> RimStats:
    return _RIM


def storm_snapshot() -> Dict[str, Any]:
    """Dispatch context attached to watchdog WD0xx incidents while
    profiling is on: total kernel dispatches plus per-app
    dispatches-per-block averages (the session-timer storm signature was
    this ratio exploding — 300k+ dispatches on 60 events)."""
    p = _GLOBAL
    with p._lock:
        per_block = {app: (tot[0] / tot[1] if tot[1] else 0.0)
                     for app, tot in p.app_blocks.items()}
    return {"total_dispatches": p.total_dispatches(),
            "dispatches_per_block": per_block}


def wrap_kernel(name: str, fn: Callable,
                batch_of: Optional[Callable[..., int]] = None,
                ticks_of: Optional[Callable[..., tuple]] = None
                ) -> ProfiledKernel:
    """Wrap a jitted callable under the process-global profiler.  The
    wrapper is always installed (so later enabling profiles already-built
    kernels); while disabled it is a single-attribute-check passthrough.
    ``ticks_of(*args) -> (scan_ticks, batch_b)`` lets scan-shaped kernels
    report their sequential tick count per call."""
    return _GLOBAL.wrap(name, fn, batch_of, ticks_of)


def enable_profiling(device_timing: bool = False):
    _GLOBAL.enable(device_timing=device_timing)


def disable_profiling():
    _GLOBAL.disable()
