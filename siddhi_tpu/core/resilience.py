"""Resilience subsystem: retry policies, circuit breakers, error stores,
non-blocking sink retry queues, and the periodic checkpoint scheduler.

(reference: Siddhi's `core.util.transport` back-off retries on
ConnectionUnavailableException, `core.util.error.handler.ErrorStore` with
`@OnError(action='STORE')`, and the periodic `PersistenceService` started
from SiddhiAppRuntime.startPeriodicPersistence.)

Design notes, in the order they matter:

  * **Nothing here blocks the junction thread.**  A sink's first publish
    attempt runs inline; every subsequent attempt runs on that sink's
    dedicated retry worker, which backs off via ``RetryPolicy``.  A sink
    that stays down trips its ``CircuitBreaker`` so the junction
    fast-fails (event → error store or counted drop) instead of queueing
    behind a dead endpoint.
  * **Determinism for tests.**  Every time source is injectable: the
    retry policy takes a ``seed`` for jitter, the breaker takes a
    ``clock`` callable, and the retry worker waits on an Event (so
    shutdown interrupts sleeps immediately and tests can use 0-delay
    policies).  ``SinkRetryWorker.join`` gives tests a sleep-free
    rendezvous with "every queued retry has been resolved".
  * **At-least-once, never silent loss.**  Every terminal failure path
    either lands the events in the ``ErrorStore`` (replayable) or
    increments a drop counter that tests and ``/metrics`` can see.
"""
from __future__ import annotations

import logging
import pickle
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from .lockwitness import maybe_wrap
from .statistics import Counter, Gauge
from .threads import engine_thread_name

log = logging.getLogger(__name__)


# ------------------------------------------------------------------ retry


def _opt_float(options: Dict[str, str], key: str, default: float) -> float:
    v = options.get(key)
    return float(v) if v is not None else default


def _opt_int(options: Dict[str, str], key: str, default: int) -> int:
    v = options.get(key)
    return int(v) if v is not None else default


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter, a per-attempt cap
    and an overall time budget.

    ``delay(attempt)`` is pure: attempt ``k`` (0-based, i.e. the k-th
    *retry*) waits ``base * multiplier**k`` seconds, capped at
    ``max_delay_s``, then spread by ``jitter`` (a fraction: 0.2 → final
    delay in [0.9d, 1.1d]) keyed off ``seed`` so runs are repeatable.
    """

    max_attempts: int = 6              # total attempts incl. the first
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.2
    budget_s: Optional[float] = 30.0   # total time across all retries
    seed: int = 0

    def delay(self, attempt: int) -> float:
        d = min(self.base_delay_s * (self.multiplier ** attempt),
                self.max_delay_s)
        if self.jitter > 0 and d > 0:
            # deterministic per-(seed, attempt) spread around d
            r = random.Random((self.seed << 16) ^ attempt).random()
            d *= 1.0 + self.jitter * (r - 0.5)
        return d

    def delays(self) -> List[float]:
        """The full retry ladder (len == max_attempts - 1), budget-capped."""
        out, spent = [], 0.0
        for k in range(max(self.max_attempts - 1, 0)):
            d = self.delay(k)
            if self.budget_s is not None and spent + d > self.budget_s:
                break
            out.append(d)
            spent += d
        return out

    @classmethod
    def from_options(cls, options: Dict[str, str],
                     defaults: "RetryPolicy" = None) -> "RetryPolicy":
        """Build from sink/source annotation options.  Delay knobs are in
        milliseconds (``retry.base.delay.ms='50'``) to match the
        reference transports' ms-denominated options."""
        base = defaults or cls()
        return replace(
            base,
            max_attempts=_opt_int(options, "retry.max.attempts",
                                  base.max_attempts),
            base_delay_s=_opt_float(options, "retry.base.delay.ms",
                                    base.base_delay_s * 1000.0) / 1000.0,
            multiplier=_opt_float(options, "retry.multiplier",
                                  base.multiplier),
            max_delay_s=_opt_float(options, "retry.max.delay.ms",
                                   base.max_delay_s * 1000.0) / 1000.0,
            jitter=_opt_float(options, "retry.jitter", base.jitter),
            budget_s=(_opt_float(options, "retry.budget.ms",
                                 (base.budget_s or 0.0) * 1000.0) / 1000.0
                      if (options.get("retry.budget.ms") is not None
                          or base.budget_s is not None) else None),
            seed=_opt_int(options, "retry.seed", base.seed),
        )


# ------------------------------------------------------------------ breaker

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """CLOSED → (failure_threshold consecutive failures) → OPEN →
    (reset_timeout elapses) → HALF_OPEN probe → success closes /
    failure re-opens.  ``clock`` is injectable for deterministic tests."""

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Callable[[str, str], None] = None):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self.on_transition = on_transition
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._lock = maybe_wrap(
            threading.Lock(), "core.resilience.CircuitBreaker._lock")
        self._pending: list = []     # transitions awaiting callback

    @classmethod
    def from_options(cls, options: Dict[str, str],
                     **kw) -> "CircuitBreaker":
        return cls(
            failure_threshold=_opt_int(options, "circuit.failure.threshold",
                                       5),
            reset_timeout_s=_opt_float(options, "circuit.reset.ms",
                                       5000.0) / 1000.0,
            **kw)

    def _transition(self, new: str):
        """Record a state change; the callback fires AFTER the lock is
        released (_fire_pending) — on_transition hooks may read breaker
        state (the circuit_state gauge does, and the flight-recorder
        incident bundle renders that gauge), which would self-deadlock
        on this non-reentrant lock if called inline."""
        old, self._state = self._state, new
        if old != new and self.on_transition is not None:
            self._pending.append((old, new))

    def _fire_pending(self):
        while True:
            with self._lock:
                if not self._pending:
                    return
                old, new = self._pending.pop(0)
            cb = self.on_transition
            if cb is None:
                continue
            try:
                cb(old, new)
            except Exception:   # noqa: BLE001 — metrics must not break flow
                pass

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            st = self._state
        self._fire_pending()
        return st

    @property
    def state_code(self) -> int:
        """0=closed 1=open 2=half_open (the /metrics encoding)."""
        return _STATE_CODE[self.state]

    def _maybe_half_open(self):
        if self._state == OPEN and \
                self.clock() - self._opened_at >= self.reset_timeout_s:
            self._transition(HALF_OPEN)

    def allow(self) -> bool:
        """May a publish attempt proceed right now?"""
        with self._lock:
            self._maybe_half_open()
            ok = self._state != OPEN
        self._fire_pending()
        return ok

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._transition(CLOSED)
        self._fire_pending()

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN or \
                    self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._transition(OPEN)
        self._fire_pending()


# ------------------------------------------------------------------ metrics


class ResilienceMetrics:
    """Always-on, allocation-light counters for the resilience layer.

    Deliberately independent of ``@app:statistics`` (which gates the
    perf trackers): you want to know about dropped events even when
    latency profiling is off.  Rendered onto ``GET /metrics`` for every
    runtime by service/rest.py.
    """

    def __init__(self, app_name: str):
        self.app_name = app_name
        self.sink_retry_total = Counter("sink_retry_total")
        self.sink_publish_failed_total = Counter("sink_publish_failed_total")
        self.sink_dropped_total = Counter("sink_dropped_total")
        self.circuit_transitions_total = Counter("circuit_transitions_total")
        self.circuit_state = Gauge("circuit_state")
        self.errors_stored_total = Counter("errors_stored_total")
        self.errors_replayed_total = Counter("errors_replayed_total")
        self.errors_purged_total = Counter("errors_purged_total")
        self.onerror_wait_retries_total = Counter(
            "onerror_wait_retries_total")
        self.checkpoints_total = Counter("checkpoints_total")
        self.checkpoint_failures_total = Counter("checkpoint_failures_total")
        self.recovered = Gauge("recovered")   # 1 after recover=True restore

    def prometheus_lines(self) -> List[str]:
        from .statistics import _fmt_labels
        out: List[str] = []

        def emit(metric: str, series, fmt=str):
            for lkey, v in series.items():
                lb = _fmt_labels({"app": self.app_name, **dict(lkey)})
                out.append(f"siddhi_{metric}{lb} {fmt(v)}")

        emit("sink_retry_total", self.sink_retry_total.series())
        emit("sink_publish_failed_total",
             self.sink_publish_failed_total.series())
        emit("sink_dropped_total", self.sink_dropped_total.series())
        emit("circuit_transitions_total",
             self.circuit_transitions_total.series())
        emit("circuit_state", self.circuit_state.series(),
             lambda v: f"{v:.9g}")
        emit("errors_stored_total", self.errors_stored_total.series())
        emit("errors_replayed_total", self.errors_replayed_total.series())
        emit("errors_purged_total", self.errors_purged_total.series())
        emit("onerror_wait_retries_total",
             self.onerror_wait_retries_total.series())
        emit("checkpoints_total", self.checkpoints_total.series())
        emit("checkpoint_failures_total",
             self.checkpoint_failures_total.series())
        emit("recovered", self.recovered.series(), lambda v: f"{v:.9g}")
        return out


#: HELP/TYPE headers merged into statistics._TYPES-driven exposition
RESILIENCE_TYPES = [
    ("siddhi_sink_retry_total", "counter",
     "Sink publish retry attempts (off the junction thread)"),
    ("siddhi_sink_publish_failed_total", "counter",
     "Sink publish attempts that raised ConnectionUnavailableError"),
    ("siddhi_sink_dropped_total", "counter",
     "Events terminally dropped by a sink (no error store configured)"),
    ("siddhi_circuit_transitions_total", "counter",
     "Circuit-breaker state transitions per sink"),
    ("siddhi_circuit_state", "gauge",
     "Per-sink circuit state: 0=closed 1=open 2=half_open"),
    ("siddhi_errors_stored_total", "counter",
     "Events captured by the error store"),
    ("siddhi_errors_replayed_total", "counter",
     "Events replayed out of the error store"),
    ("siddhi_errors_purged_total", "counter",
     "Error-store entries purged"),
    ("siddhi_onerror_wait_retries_total", "counter",
     "@OnError(action='WAIT') bounded-blocking retry attempts"),
    ("siddhi_checkpoints_total", "counter",
     "Periodic checkpoints persisted by @app:persist"),
    ("siddhi_checkpoint_failures_total", "counter",
     "Periodic checkpoints that raised"),
    ("siddhi_recovered", "gauge",
     "1 once a runtime restored state via recover=True"),
]


# ------------------------------------------------------------------ error store


@dataclass
class ErrorEntry:
    """One failed delivery: the events plus enough context to replay them."""

    id: int
    app_name: str
    stream_id: str
    origin: str     # 'sink' | 'stream' | 'ingest' | 'overload' | 'watchdog'
    error: str
    timestamp_ms: int
    events: List[Tuple[int, tuple]]   # (event timestamp, data row)
    attempts: int = 0

    def summary(self) -> Dict[str, Any]:
        return {"id": self.id, "app": self.app_name,
                "stream": self.stream_id, "origin": self.origin,
                "error": self.error, "timestamp": self.timestamp_ms,
                "events": len(self.events), "attempts": self.attempts}


class ErrorStore:
    """Store/list/purge failed events.  Implementations must be
    thread-safe: junction workers and retry workers both store."""

    def store(self, entry: ErrorEntry) -> int:
        raise NotImplementedError

    def list(self, app_name: str = None,
             stream_id: str = None) -> List[ErrorEntry]:
        raise NotImplementedError

    def purge(self, app_name: str = None, ids: List[int] = None) -> int:
        raise NotImplementedError

    def count(self, app_name: str = None) -> int:
        return len(self.list(app_name))


class InMemoryErrorStore(ErrorStore):
    def __init__(self, capacity: int = 10_000):
        self.capacity = capacity
        self._entries: "deque[ErrorEntry]" = deque(maxlen=capacity)
        self._next_id = 1
        self._lock = maybe_wrap(
            threading.Lock(), "core.resilience.InMemoryErrorStore._lock")

    def store(self, entry: ErrorEntry) -> int:
        with self._lock:
            entry.id = self._next_id
            self._next_id += 1
            self._entries.append(entry)
            return entry.id

    def list(self, app_name=None, stream_id=None):
        with self._lock:
            return [e for e in self._entries
                    if (app_name is None or e.app_name == app_name)
                    and (stream_id is None or e.stream_id == stream_id)]

    def purge(self, app_name=None, ids=None):
        with self._lock:
            keep, purged = deque(maxlen=self.capacity), 0
            id_set = set(ids) if ids is not None else None
            for e in self._entries:
                match = (app_name is None or e.app_name == app_name) and \
                        (id_set is None or e.id in id_set)
                if match:
                    purged += 1
                else:
                    keep.append(e)
            self._entries = keep
            return purged


def serialize_events(events) -> List[Tuple[int, tuple]]:
    """Event objects → picklable (timestamp, data-row) pairs."""
    return [(int(e.timestamp), tuple(e.data)) for e in events]


def make_entry(app_name: str, stream_id: str, origin: str, error: Exception,
               events, now_ms: int = None, attempts: int = 0) -> ErrorEntry:
    return ErrorEntry(
        id=0, app_name=app_name, stream_id=stream_id, origin=origin,
        error=f"{type(error).__name__}: {error}",
        timestamp_ms=now_ms if now_ms is not None
        else int(time.time() * 1000),
        events=serialize_events(events), attempts=attempts)


def pickle_events(events: List[Tuple[int, tuple]]) -> bytes:
    return pickle.dumps(events, protocol=pickle.HIGHEST_PROTOCOL)


def unpickle_events(blob: bytes) -> List[Tuple[int, tuple]]:
    return pickle.loads(blob)


# ------------------------------------------------------------------ sink retry


@dataclass
class _RetryTask:
    payload: Any
    event: Any
    events: List[Any]
    attempt: int = 0
    first_failed_at: float = 0.0
    last_error: Optional[Exception] = None


class SinkRetryWorker:
    """Bounded per-sink retry queue + worker thread.

    The junction thread calls ``submit`` (non-blocking); the worker
    owns every delay.  Terminal outcomes go through ``on_exhausted``
    (→ error store / counted drop).  ``join`` blocks until the queue is
    empty *and* no task is in flight — the sleep-free way for tests and
    shutdown to wait for "all retries resolved".
    """

    def __init__(self, name: str,
                 publish_fn: Callable[[Any, Any], None],
                 policy: RetryPolicy,
                 breaker: Optional[CircuitBreaker],
                 on_exhausted: Callable[[_RetryTask], None],
                 on_retry: Callable[[_RetryTask], None] = None,
                 capacity: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.publish_fn = publish_fn
        self.policy = policy
        self.breaker = breaker
        self.on_exhausted = on_exhausted
        self.on_retry = on_retry
        self.capacity = capacity
        self.clock = clock
        self._tasks: "deque[_RetryTask]" = deque()
        self._in_flight = 0
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- junction side ------------------------------------------------

    def submit(self, payload, event, events, error: Exception) -> bool:
        """Queue a failed publish for retry.  Returns False when the
        queue is full (caller routes to the exhausted path instead)."""
        task = _RetryTask(payload=payload, event=event, events=events,
                          attempt=1, first_failed_at=self.clock(),
                          last_error=error)
        with self._cond:
            if self._stop.is_set() or len(self._tasks) >= self.capacity:
                return False
            self._tasks.append(task)
            self._ensure_thread()
            self._cond.notify()
            return True

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run,
                name=engine_thread_name("siddhi-retry-", self.name),
                daemon=True)
            self._thread.start()

    # ---- worker side --------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                while not self._tasks and not self._stop.is_set():
                    self._cond.wait(timeout=0.5)
                if self._stop.is_set() and not self._tasks:
                    self._cond.notify_all()
                    return
                task = self._tasks.popleft()
                self._in_flight += 1
            try:
                self._process(task)
            finally:
                with self._cond:
                    self._in_flight -= 1
                    self._cond.notify_all()

    def _process(self, task: _RetryTask):
        while True:
            budget = self.policy.budget_s
            over_budget = (budget is not None and
                           self.clock() - task.first_failed_at > budget)
            if task.attempt >= self.policy.max_attempts or over_budget:
                self._exhaust(task)
                return
            # back off before the next attempt; stop() interrupts.
            # On stop we fall through to one last immediate attempt so
            # shutdown drains the queue instead of losing it.
            self._stop.wait(self.policy.delay(task.attempt - 1))
            if self.breaker is not None and not self.breaker.allow():
                if self._stop.is_set():
                    self._exhaust(task)
                    return
                task.attempt += 1
                continue
            try:
                if self.on_retry is not None:
                    self.on_retry(task)
                self.publish_fn(task.payload, task.event)
                if self.breaker is not None:
                    self.breaker.record_success()
                return
            except Exception as e:     # noqa: BLE001 — any failure retries
                task.last_error = e
                task.attempt += 1
                if self.breaker is not None:
                    self.breaker.record_failure()
                if self._stop.is_set():
                    self._exhaust(task)
                    return

    def _exhaust(self, task: _RetryTask):
        try:
            self.on_exhausted(task)
        except Exception:       # noqa: BLE001 — last-resort path must not die
            log.exception("sink %s: exhausted-handler failed", self.name)

    # ---- lifecycle ----------------------------------------------------

    def pending(self) -> int:
        with self._cond:
            return len(self._tasks) + self._in_flight

    def join(self, timeout: float = 30.0) -> bool:
        """Wait until every queued/in-flight task has been resolved."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._tasks or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    def stop(self, drain_timeout: float = 5.0):
        """Interrupt backoff sleeps; give queued tasks one immediate
        final attempt each (failures land in on_exhausted), then stop."""
        with self._cond:
            self._stop.set()
            self._cond.notify_all()
            t = self._thread
        if t is not None and t.is_alive():
            self.join(timeout=drain_timeout)
            t.join(timeout=1.0)


# ------------------------------------------------------------------ checkpoints


class CheckpointScheduler:
    """Drives ``SnapshotService.persist`` every ``interval_ms`` through the
    app's Scheduler (so `@app:playback` virtual time works and tests can
    advance it deterministically).  Serialization with external
    ``persist()`` callers is inherited from the single
    ``SnapshotService._lock`` — both paths funnel through it."""

    def __init__(self, runtime, interval_ms: int, incremental: bool = False):
        self.runtime = runtime
        self.interval_ms = max(int(interval_ms), 1)
        self.incremental = incremental
        self.metrics: Optional[ResilienceMetrics] = None
        self._stopped = threading.Event()

    def start(self):
        self._stopped.clear()
        self._arm(self.runtime.app_ctx.current_time())

    def _arm(self, now_ms: int):
        if not self._stopped.is_set():
            self.runtime.app_ctx.scheduler.notify_at(
                now_ms + self.interval_ms, self._fire)

    def _fire(self, now_ms: int):
        if self._stopped.is_set():
            return
        try:
            self.runtime.persist(incremental=self.incremental)
            if self.metrics is not None:
                self.metrics.checkpoints_total.inc()
        except Exception:       # noqa: BLE001 — keep checkpointing
            if self.metrics is not None:
                self.metrics.checkpoint_failures_total.inc()
            log.exception("periodic checkpoint failed for app %s",
                          self.runtime.name)
        self._arm(now_ms)

    def stop(self):
        # the armed heap entry stays queued but _fire no-ops once stopped
        self._stopped.set()
