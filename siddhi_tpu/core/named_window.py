"""Named windows: `define window W (...) length(5) output all events`.

(reference: core/window/Window.java — a shared window definition usable by
many queries: inserts go through the inner window processor, published events
(current/expired per the output clause) reach every subscribed query, and
joins probe its buffer via the Findable interface.)
"""
from __future__ import annotations

import threading

from ..query_api.definition import WindowDefinition
from .event import CURRENT, EXPIRED, EventChunk
from .processor import Processor
from .stateschema import Sub, persistent_schema
from .window import create_window_processor


class _Publisher(Processor):
    def __init__(self, named_window: "NamedWindow"):
        super().__init__()
        self.named_window = named_window

    def process(self, chunk: EventChunk):
        self.named_window._publish(chunk)


@persistent_schema("named-window", schema=Sub("processor"),
                   doc="persists exactly its wrapped window processor's state")
class NamedWindow:
    def __init__(self, definition: WindowDefinition, app_ctx, compile_expr,
                 extension_registry=None):
        self.definition = definition
        self.app_ctx = app_ctx
        self.lock = threading.RLock()
        name = definition.window_name or "length"
        self.processor = create_window_processor(
            name, definition.window_params, app_ctx,
            definition.attribute_names, compile_expr,
            namespace=definition.window_namespace or "",
            extension_registry=extension_registry)
        self.processor.lock = self.lock
        self.processor.next = _Publisher(self)
        self.subscribers = []        # query receivers (receive_chunk)
        self.output_event_type = definition.output_event_type

    def add(self, chunk: EventChunk):
        with self.lock:
            self.processor.process(chunk)

    def _publish(self, chunk: EventChunk):
        if self.output_event_type == "current":
            chunk = chunk.only(CURRENT)
        elif self.output_event_type == "expired":
            chunk = chunk.only(EXPIRED)
        if chunk.is_empty:
            return
        for s in list(self.subscribers):
            s.receive_chunk(chunk)

    def subscribe(self, receiver):
        self.subscribers.append(receiver)

    def unsubscribe(self, receiver):
        if receiver in self.subscribers:
            self.subscribers.remove(receiver)

    # joins / store queries probe the live buffer
    def find_chunk(self):
        return self.processor.find_chunk()

    # snapshot
    def current_state(self):
        return self.processor.current_state()

    def restore_state(self, s):
        self.processor.restore_state(s)
