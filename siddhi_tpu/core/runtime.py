"""SiddhiManager + SiddhiAppRuntime — the top-level API.

(reference: SiddhiManager.java:46-253 — create/validate runtimes, persistence
stores, extensions; SiddhiAppRuntime.java:93-804 — per-app isolate: definition
maps, junctions, queries, partitions, lifecycle, persist/restore, store
queries, playback; util/SiddhiAppRuntimeBuilder.java — junction/table/window/
trigger wiring; util/parser/SiddhiAppParser.java — @app annotations.)
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..compiler import SiddhiCompiler
from ..plan.expr_compiler import ExprCompiler, Scope
from ..query_api import (AttrType, Query, SiddhiApp, StreamDefinition,
                         find_annotation)
from ..utils.errors import (DefinitionNotExistError, NoPersistenceStoreError,
                            SiddhiAppCreationError)
from ..utils.extension import ExtensionRegistry
from .context import SiddhiAppContext, SiddhiContext
from .named_window import NamedWindow
from .query_runtime import QueryRuntime
from .snapshot import PersistenceStore, SnapshotService
from .statistics import StatisticsManager
from .stream import InputHandler, QueryCallback, StreamCallback, StreamJunction
from .table import InMemoryTable
from .trigger import TriggerRuntime, trigger_stream_definition

log = logging.getLogger(__name__)


class ScriptFunction:
    """`define function f[python] return T { body }` — compiled python script
    (reference: function/Script SPI via JSR-223; here native python)."""

    def __init__(self, fn_def):
        self.fn_def = fn_def
        body = fn_def.body.strip()
        if fn_def.language not in ("python", "py"):
            raise SiddhiAppCreationError(
                f"Unsupported script language '{fn_def.language}' "
                f"(python only)")
        ns: Dict[str, Any] = {}
        if "\n" in body or body.startswith("return"):
            lines = body.split("\n")
            src = "def __fn__(data):\n" + "\n".join(
                "    " + ln for ln in lines)
        else:
            src = f"def __fn__(data):\n    return ({body})"
        exec(src, ns)  # noqa: S102 — user-defined function body, like the
        # reference's JSR-223 script engines
        self._fn = ns["__fn__"]

    def compile_call(self, compiled_args):
        from ..plan.expr_compiler import CompiledExpr
        from .event import dtype_for
        rt = self.fn_def.return_type or AttrType.OBJECT
        dt = dtype_for(rt)
        fn_ = self._fn

        def fn(ctx):
            n = ctx.n
            vals = []
            for a in compiled_args:
                v = a.fn(ctx)
                if isinstance(v, np.ndarray) and v.ndim > 0:
                    vals.append(v)
                else:
                    vals.append(np.full(n, v))
            out = np.empty(n, dt if dt is object else dt)
            for i in range(n):
                out[i] = fn_([v[i] for v in vals])
            return out
        from ..plan.expr_compiler import CompiledExpr
        return CompiledExpr(fn, rt)


class SiddhiAppRuntime:
    #: AnalysisResult from the compile-time semantic analyzer (set by
    #: SiddhiManager.create_siddhi_app_runtime; None for runtimes built
    #: directly).  Surfaced by GET /stats on the REST service.
    analysis = None
    #: StateSchemaReport over the registered snapshot elements (set by
    #: attach_schema_analysis at creation; None for runtimes built
    #: directly).  Also rides rt.analysis.schema and GET /stats.
    state_schema = None

    def __init__(self, app: SiddhiApp, siddhi_context: SiddhiContext,
                 app_string: Optional[str] = None):
        self.app = app
        self.siddhi_context = siddhi_context
        name = app.name
        if name is None:
            # stable content-derived default so persistence revisions of an
            # unnamed app resolve across restarts
            import hashlib
            basis = app_string if app_string else repr(app)
            name = "app_" + hashlib.sha1(basis.encode()).hexdigest()[:8]
        self.name = name
        self.app_ctx = SiddhiAppContext(siddhi_context, name)
        self.app_ctx.runtime = self
        self.extension_registry: ExtensionRegistry = getattr(
            siddhi_context, "extension_registry", None) or ExtensionRegistry()
        for k, v in siddhi_context.extensions.items():
            self.extension_registry.register(k, v)

        self.stream_definitions: Dict[str, StreamDefinition] = {}
        self.junctions: Dict[str, StreamJunction] = {}
        self.tables: Dict[str, InMemoryTable] = {}
        self.named_windows: Dict[str, NamedWindow] = {}
        self.aggregations: Dict[str, Any] = {}
        self.triggers: List[TriggerRuntime] = []
        self.query_runtimes: Dict[str, QueryRuntime] = {}
        self.partition_runtimes: List[Any] = []
        self.input_handlers: Dict[str, InputHandler] = {}
        self.sources: List[Any] = []
        self.sinks: List[Any] = []
        self._started = False
        # bounded LRU of compiled store-query runtimes (reference
        # SiddhiAppRuntime.query:280-316 uses a size-capped LRU map)
        from collections import OrderedDict
        self._store_query_cache: "OrderedDict[str, Any]" = OrderedDict()
        self._store_query_cache_size = 50

        # resilience: always-on counters, optional error store and
        # periodic checkpointing (see core/resilience.py)
        from .resilience import ResilienceMetrics
        self.resilience_metrics = ResilienceMetrics(self.name)
        self.error_store = getattr(siddhi_context, "error_store", None)

        # ingest protection: always-on counters plus (unless the
        # SIDDHI_TPU_INGEST_GUARD kill switch is off) the dispatch-storm
        # watchdog riding every scheduler fire (see core/overload.py)
        from .overload import DispatchWatchdog, IngestMetrics, guard_enabled
        self.ingest_metrics = IngestMetrics(self.name)
        self.watchdog = None
        if guard_enabled():
            self.watchdog = DispatchWatchdog(self.name,
                                             metrics=self.ingest_metrics)
            self.watchdog.runtime = self
            self.app_ctx.watchdog = self.watchdog
            self.app_ctx.scheduler.watchdog = self.watchdog
        self.checkpoint_scheduler = None
        self.recovered_revision: Optional[str] = None

        self.snapshot_service = SnapshotService(self.app_ctx)
        self.app_ctx.snapshot_service = self.snapshot_service
        self.snapshot_service.pre_snapshot = self.flush
        self._parse_app_annotations()
        self._build()

    # ------------------------------------------------------------ build

    def _parse_app_annotations(self):
        ann = find_annotation(self.app.annotations, "app:playback")
        if ann is None:
            ann = find_annotation(self.app.annotations, "playback")
        if ann is not None:
            idle = ann.get("idle.time")
            inc = ann.get("increment")
            self.app_ctx.playback = True
            self.app_ctx.timestamp_generator.enable_playback(
                _parse_time_str(idle) if idle else None,
                _parse_time_str(inc) if inc else None)
        stats = find_annotation(self.app.annotations, "app:statistics")
        if stats is None:
            stats = find_annotation(self.app.annotations, "statistics")
        reporter, interval, enabled = "console", 60, False
        tracing_on = False
        telemetry_on = False
        if stats is not None:
            reporter = stats.get("reporter", "console")
            interval = int(stats.get("interval", "60"))
            enable_attr = stats.get("enable")
            pos = stats.positional()
            enabled = True
            if enable_attr is not None:
                enabled = str(enable_attr).lower() == "true"
            elif pos and str(pos[0]).lower() == "false":
                enabled = False
            tracing_on = str(stats.get("tracing", "false")).lower() == "true"
            telemetry_on = \
                str(stats.get("telemetry", "false")).lower() == "true"
        self.app_ctx.statistics_manager = StatisticsManager(
            self.name, reporter, interval)
        self.app_ctx.stats_enabled = enabled
        # @app:statistics(telemetry='true') — opt-in on-device NFA/window
        # state telemetry; compilers read the flag off app_ctx, the device
        # runtimes push host copies into the DeviceTelemetry holder
        self.app_ctx.telemetry_enabled = telemetry_on
        self.device_telemetry = None
        if telemetry_on:
            from .statistics import DeviceTelemetry
            self.device_telemetry = DeviceTelemetry(self.name)
        if enabled:
            # kernel profiling rides @app:statistics: the per-kernel
            # compile/device-time gauges feed the same /metrics surface
            from .profiling import profiler
            profiler().enable()
        if tracing_on:
            from .tracing import tracer
            tracer().enable()
        # @app:persist(interval='30 sec', incremental='true') — periodic
        # checkpointing through the app scheduler (playback-aware)
        pers = find_annotation(self.app.annotations, "app:persist")
        if pers is None:
            pers = find_annotation(self.app.annotations, "persist")
        if pers is not None:
            pos = pers.positional()
            interval = pers.get("interval") or (pos[0] if pos else "30 sec")
            inc = str(pers.get("incremental", "false")).lower() == "true"
            from .resilience import CheckpointScheduler
            self.checkpoint_scheduler = CheckpointScheduler(
                self, _parse_time_str(str(interval)), incremental=inc)
            self.checkpoint_scheduler.metrics = self.resilience_metrics
        # @app:errorStore(type='memory'|'sqlite') — app-level error store
        # for @OnError(action='STORE') and sink-exhausted events
        es = find_annotation(self.app.annotations, "app:errorstore")
        if es is None:
            es = find_annotation(self.app.annotations, "errorstore")
        if es is not None:
            etype = (es.get("type", "memory") or "memory").lower()
            if etype in ("memory", "inmemory"):
                from .resilience import InMemoryErrorStore
                self.error_store = InMemoryErrorStore(
                    capacity=int(es.get("capacity", "10000")))
            elif etype == "sqlite":
                from ..stores.sqlite import SqliteErrorStore
                self.error_store = SqliteErrorStore(
                    es.get("database", ":memory:"))
            else:
                raise SiddhiAppCreationError(
                    f"Unknown error store type '{etype}'")
        # @app:slo(latency.p99.ms='...', lag.ms='...') — per-app latency/
        # lag objectives for the always-on ledger (core/ledger.py):
        # burn-rate gauges on /metrics, /health degradation and an SLO001
        # flight bundle on sustained breach.  Parsed tolerantly like the
        # @Async overload options; the analyzer's SA07x diagnostics flag
        # malformed values
        self.slo_config = None
        slo = find_annotation(self.app.annotations, "app:slo")
        if slo is None:
            slo = find_annotation(self.app.annotations, "slo")
        if slo is not None:
            from .ledger import SloConfig, ledger
            self.slo_config = SloConfig.from_annotation(slo)
            ledger().register_slo(self.name, self.slo_config)
        # @app:quota(rate='1000', burst='2000') — fair-share ingest
        # admission for multi-tenant deployments (core/overload.py):
        # a token-bucket budget enforced at the InputHandler boundary,
        # layered UNDER the per-stream @Async overload policies.  Parsed
        # here (before _build) so junctions and input handlers see the
        # registered quota at construction
        self.quota = None
        qa = find_annotation(self.app.annotations, "app:quota")
        if qa is None:
            qa = find_annotation(self.app.annotations, "quota")
        if qa is not None:
            from .overload import TenantQuota, fair_share
            self.quota = TenantQuota.from_annotation(self.name, qa)
            if self.quota is not None:
                fair_share().register(self.quota)

    def _build(self):
        from .source_sink import attach_sources_and_sinks

        app = self.app
        # 1. streams → junctions
        for sid, d in app.stream_definitions.items():
            self.stream_definitions[sid] = d
            self._make_junction(sid, d)
        # 2. tables
        for tid, td in app.table_definitions.items():
            store_ann = find_annotation(td.annotations, "store")
            table = None
            if store_ann is not None and self.extension_registry is not None:
                store_cls = self.extension_registry.find_store(
                    store_ann.get("type", ""))
                if store_cls is not None:
                    table = store_cls(td, store_ann)
            # `is None`, not truthiness — an empty store has __len__() == 0
            self.tables[tid] = InMemoryTable(td) if table is None else table
            self.snapshot_service.register(f"table:{tid}", self.tables[tid])
        # 3. named windows
        for wid, wd in app.window_definitions.items():
            scope = Scope()
            scope.add_primary(wid, None, wd)
            compiler = ExprCompiler(scope, np, self.app_ctx.script_functions,
                                    self.extension_registry)
            nw = NamedWindow(wd, self.app_ctx, lambda e: compiler.compile(e),
                             extension_registry=self.extension_registry)
            self.named_windows[wid] = nw
            self.snapshot_service.register(f"window:{wid}", nw)
        # 4. triggers
        for tid, td in app.trigger_definitions.items():
            d = trigger_stream_definition(td)
            self.stream_definitions[tid] = d
            junction = self._make_junction(tid, d)
            self.triggers.append(TriggerRuntime(td, junction, self.app_ctx))
        # 5. script functions
        for fid, fd in app.function_definitions.items():
            self.app_ctx.script_functions[fid] = ScriptFunction(fd)
        # 6. aggregations (planner: slab-tensor device ingest unless the
        # app pins @app:engine('host') or device setup fails)
        for aid, ad in app.aggregation_definitions.items():
            from ..plan.planner import engine_mode
            from .aggregation import AggregationRuntime
            ar = None
            if engine_mode(app) != "host":
                try:
                    from ..plan.iagg_compiler import DeviceAggregationRuntime
                    ar = DeviceAggregationRuntime(ad, self)
                except TypeError:
                    ar = None     # unsupported shape (e.g. string lanes)
                except Exception:
                    import logging
                    logging.getLogger(__name__).warning(
                        "aggregation '%s': device slab path failed, "
                        "falling back to the host cascade", aid,
                        exc_info=True)
                    ar = None
            if ar is None:
                ar = AggregationRuntime(ad, self)
            self.aggregations[aid] = ar
            self.snapshot_service.register(f"aggregation:{aid}", ar)
        # 7. queries + partitions
        qcount = 0
        for el in app.execution_elements:
            if isinstance(el, Query):
                qname = el.name or f"query_{qcount}"
                qr = QueryRuntime(el, self, qname)
                self.query_runtimes[qname] = qr
                for eid, obj in qr.stateful_elements():
                    self.snapshot_service.register(eid, obj)
            else:
                from .partition import PartitionRuntime
                pr = PartitionRuntime(el, self, f"partition_{qcount}")
                self.partition_runtimes.append(pr)
                self.snapshot_service.register(f"partition:{pr.name}", pr)
            qcount += 1
        # 8. sources & sinks from stream annotations
        attach_sources_and_sinks(self)
        # always-on saturation gauges for @Async buffers (read lazily at
        # /metrics scrape time; independent of @app:statistics)
        for sid, j in self.junctions.items():
            if j.is_async:
                self.ingest_metrics.ingest_saturation.set_fn(
                    j.saturation, stream=sid)
        # 9. statistics wiring
        if self.app_ctx.stats_enabled:
            sm = self.app_ctx.statistics_manager
            for sid, j in self.junctions.items():
                j.throughput_tracker = sm.throughput_tracker("Streams", sid)
                if j.is_async:
                    # @Async queue depth: backpressure is visible before
                    # it becomes an @OnError drop
                    sm.buffered_tracker("Streams", sid).register(
                        j.queue_depth)

    def _make_junction(self, sid: str, d: StreamDefinition) -> StreamJunction:
        fault_junction = None
        on_err = find_annotation(d.annotations, "onerror")
        if on_err is not None and \
                (on_err.get("action", "LOG") or "").upper() == "STREAM":
            fd = StreamDefinition("!" + sid,
                                  [a for a in d.attributes])
            fd.attribute("_error", AttrType.OBJECT)
            self.stream_definitions["!" + sid] = fd
            fault_junction = StreamJunction(fd, self.app_ctx)
            self.junctions["!" + sid] = fault_junction
        j = StreamJunction(d, self.app_ctx, fault_junction)
        self.junctions[sid] = j
        return j

    # ------------------------------------------------------------ lookups
    # (used by QueryRuntime wiring)

    def definition_of(self, stream_id: str, is_inner=False, is_fault=False):
        key = ("#" if is_inner else "!" if is_fault else "") + stream_id
        if is_fault:
            key = "!" + stream_id
        d = self.stream_definitions.get(key if not is_inner else stream_id)
        if d is None and stream_id in self.named_windows:
            return self.named_windows[stream_id].definition
        if d is None and stream_id in self.tables:
            return self.tables[stream_id].definition
        if d is None and stream_id in self.aggregations:
            return self.aggregations[stream_id].output_definition
        if d is None:
            raise DefinitionNotExistError(
                f"No stream/window/table '{stream_id}' defined")
        return d

    def junction_of(self, stream_id: str, is_inner=False, is_fault=False,
                    partition_key: Optional[str] = None,
                    create_with: Optional[StreamDefinition] = None
                    ) -> StreamJunction:
        key = ("!" + stream_id) if is_fault else stream_id
        j = self.junctions.get(key)
        if j is None:
            if create_with is None:
                raise DefinitionNotExistError(f"No stream '{key}' defined")
            d = StreamDefinition(stream_id, list(create_with.attributes))
            self.stream_definitions[stream_id] = d
            j = self._make_junction(stream_id, d)
        return j

    def has_table(self, tid: str) -> bool:
        return tid in self.tables

    def table_of(self, tid: str) -> InMemoryTable:
        return self.tables[tid]

    def has_named_window(self, wid: str) -> bool:
        return wid in self.named_windows

    def named_window_of(self, wid: str) -> NamedWindow:
        return self.named_windows[wid]

    def latency_tracker_for(self, query_name: str):
        if self.app_ctx.stats_enabled and self.app_ctx.statistics_manager:
            return self.app_ctx.statistics_manager.latency_tracker(
                "Queries", query_name)
        return None

    # ------------------------------------------------------------ public API
    # (reference SiddhiAppRuntime public surface)

    def get_input_handler(self, stream_id: str) -> InputHandler:
        h = self.input_handlers.get(stream_id)
        if h is None:
            j = self.junctions.get(stream_id)
            if j is None:
                raise DefinitionNotExistError(f"No stream '{stream_id}'")
            h = InputHandler(j, self.app_ctx)
            self.input_handlers[stream_id] = h
        return h

    def add_callback(self, target: str, callback) -> None:
        """StreamCallback on a stream id, or QueryCallback on a query name
        (reference SiddhiAppRuntime.addCallback overloads :251-270)."""
        if isinstance(callback, QueryCallback):
            qr = self.query_runtimes.get(target)
            if qr is None:
                for pr in self.partition_runtimes:
                    qr = pr.query_runtime_by_name(target)
                    if qr is not None:
                        break
            if qr is None:
                raise DefinitionNotExistError(f"No query '{target}'")
            qr.add_callback(callback)
            return
        j = self.junctions.get(target)
        if j is None:
            raise DefinitionNotExistError(f"No stream '{target}'")
        callback.stream_definition = j.definition
        j.subscribe(callback)

    def start(self):
        if self._started:
            return
        self._started = True
        for j in self.junctions.values():
            j.start()
        for qr in self.query_runtimes.values():
            qr.start()
        for t in self.triggers:
            t.start()
        for s in self.sources:
            s.connect_with_retry()
        for s in self.sinks:
            s.connect_with_retry()
        if self.app_ctx.stats_enabled:
            self.app_ctx.statistics_manager.start_reporting()
        if self.checkpoint_scheduler is not None:
            self.checkpoint_scheduler.start()

    def start_without_sources(self):
        self._started = True
        for j in self.junctions.values():
            j.start()
        for qr in self.query_runtimes.values():
            qr.start()
        for t in self.triggers:
            t.start()

    def flush(self):
        """Drain async junction queues and retire pipelined device work:
        when this returns, every match for events already sent has been
        delivered to callbacks.  The columnar analogue of waiting out the
        reference's @Async disruptor backlog.  One pass per junction:
        flushing stream S can enqueue matches into a downstream @Async
        junction that was flushed earlier in the pass, so iterate once
        per junction (an event can traverse at most every junction once
        per hop)."""
        for _ in range(max(len(self.junctions), 1)):
            for j in self.junctions.values():
                j.flush()
            if all(j.quiescent for j in self.junctions.values()):
                break       # nothing queued, no delivery in flight

    def shutdown(self):
        dbg = getattr(self.app_ctx, "debugger", None)
        if dbg is not None:
            dbg.detach()
        if self.checkpoint_scheduler is not None:
            self.checkpoint_scheduler.stop()
        for s in self.sources:
            s.shutdown()
        for s in self.sinks:
            s.shutdown()
        for t in self.triggers:
            t.stop()
        for j in self.junctions.values():
            j.stop()
        for qr in self.query_runtimes.values():
            dev = getattr(qr, "device_runtime", None)
            if dev is not None and hasattr(dev, "shutdown"):
                dev.shutdown()   # stops absent-state timer callbacks
        self.app_ctx.scheduler.shutdown()
        self.app_ctx.timestamp_generator.shutdown()
        if self.app_ctx.statistics_manager:
            self.app_ctx.statistics_manager.stop_reporting()
        from .ledger import ledger
        ledger().drop_app(self.name)
        if self.quota is not None:
            from .overload import fair_share
            fair_share().unregister(self.name)
        self._started = False

    def debug(self):
        """Start in debug mode: returns a SiddhiDebugger whose breakpoints
        block event threads at query IN/OUT terminals (reference
        SiddhiAppRuntime.debug :575)."""
        from .debugger import SiddhiDebugger
        dbg = SiddhiDebugger(self)
        self.app_ctx.debugger = dbg
        self.start()
        return dbg

    # ------------------------------------------------------------ persistence

    def _store(self) -> PersistenceStore:
        store = self.siddhi_context.persistence_store
        if store is None:
            raise NoPersistenceStoreError(
                "No persistence store set on SiddhiManager")
        return store

    def persist(self, incremental: bool = False) -> str:
        return self.snapshot_service.persist(self.name, self._store(),
                                             incremental=incremental)

    def restore_revision(self, revision: str):
        self.snapshot_service.restore_revision(self.name, self._store(),
                                               revision)

    def restore_last_revision(self) -> Optional[str]:
        return self.snapshot_service.restore_last_revision(self.name,
                                                           self._store())

    def clear_all_revisions(self):
        self._store().clear_all_revisions(self.name)

    def snapshot(self) -> bytes:
        return self.snapshot_service.full_snapshot()

    def restore(self, snapshot: bytes):
        self.snapshot_service.restore(snapshot)

    def recover(self) -> Optional[str]:
        """Restore the last persisted revision (crash recovery).  Returns
        the revision restored (None when the store has none) and records
        it as ``recovered_revision`` + the ``siddhi_recovered`` gauge."""
        rev = self.restore_last_revision()
        self.recovered_revision = rev
        if rev is not None:
            self.resilience_metrics.recovered.set(1)
            log.info("app %s recovered from revision %s", self.name, rev)
        return rev

    # ------------------------------------------------------------ error store

    def replay_errors(self, stream_id: Optional[str] = None,
                      ids: Optional[list] = None) -> int:
        """Re-deliver error-store entries for this app through their
        original path: sink-origin entries re-publish via that stream's
        sinks, stream-origin entries re-enter the junction.  Successful
        entries are purged; returns the number of events replayed
        (at-least-once — a replay that fails again re-enters the store
        through the normal failure path)."""
        store = self.error_store
        if store is None:
            return 0
        from .event import EventChunk
        id_set = set(ids) if ids is not None else None
        replayed = 0
        for entry in store.list(app_name=self.name, stream_id=stream_id):
            if id_set is not None and entry.id not in id_set:
                continue
            d = self.stream_definitions.get(entry.stream_id)
            if d is None:
                continue
            rows = [list(data) for _, data in entry.events]
            stamps = [ts for ts, _ in entry.events]
            if entry.origin == "sink":
                chunk = EventChunk.from_rows(d, rows, stamps)
                targets = [s for s in self.sinks
                           if s.stream_def.id == entry.stream_id]
                for s in targets:
                    s.receive_chunk(chunk)
            elif entry.origin == "ingest":
                # quarantined events re-enter through the input handler so
                # a replay is re-validated (a still-poison event goes
                # straight back to the store instead of device state)
                from .event import Event
                self.get_input_handler(entry.stream_id).send(
                    [Event(ts, data) for ts, data in entry.events])
            else:
                chunk = EventChunk.from_rows(d, rows, stamps)
                junction = self.junctions.get(entry.stream_id)
                if junction is None:
                    continue
                junction.send(chunk)
            store.purge(app_name=self.name, ids=[entry.id])
            replayed += len(entry.events)
            self.resilience_metrics.errors_replayed_total.inc(
                len(entry.events), stream=entry.stream_id)
        return replayed

    # ------------------------------------------------------------ playback & stats

    def enable_playback(self, idle_time_ms=None, increment_ms=None):
        self.app_ctx.playback = True
        self.app_ctx.timestamp_generator.enable_playback(idle_time_ms,
                                                         increment_ms)

    def enable_stats(self, enabled: bool = True):
        self.app_ctx.stats_enabled = enabled
        from .profiling import profiler
        if enabled:
            self.app_ctx.statistics_manager.start_reporting()
            profiler().enable()
            if not self.app_ctx.statistics_manager.throughput:
                # late enable: wire junction trackers now
                sm = self.app_ctx.statistics_manager
                for sid, j in self.junctions.items():
                    j.throughput_tracker = sm.throughput_tracker(
                        "Streams", sid)
                    if j.is_async:
                        sm.buffered_tracker("Streams", sid).register(
                            j.queue_depth)
        else:
            self.app_ctx.statistics_manager.stop_reporting()

    @property
    def statistics(self) -> dict:
        from .ledger import ledger
        from .profiling import profiler, rim_stats
        snap = self.app_ctx.statistics_manager.snapshot()
        snap["kernels"] = profiler().snapshot()
        # the always-on host-rim counters and the latency ledger ride
        # every snapshot surface (/metrics, flight records, here) —
        # rt.statistics must agree with them (tests/test_service.py
        # asserts the parity)
        snap["rim"] = rim_stats().snapshot()
        snap["ledger"] = ledger().snapshot(app=self.name)
        from ..plan.shapes import shape_registry
        snap["shapes"] = shape_registry().snapshot()
        if self.device_telemetry is not None:
            snap["telemetry"] = self.device_telemetry.snapshot()
        # partition shard-out rows (round 15): per-shard key/capacity/
        # dispatch counters for every sharded keyed runtime.  This
        # host-side gather is the shard set's one cross-device
        # aggregation point — the hot path never reduces across shards.
        shard_rows: Dict[str, list] = {}

        def _scan(label, qr):
            dev = getattr(qr, "device_runtime", None)
            ss = getattr(dev, "shard_stats", None)
            rows = ss() if ss is not None else None
            if rows:
                shard_rows[label] = rows

        for qname, qr in self.query_runtimes.items():
            _scan(qname, qr)
        for pr in self.partition_runtimes:
            for qname, qr in getattr(pr, "device_query_runtimes",
                                     {}).items():
                _scan(f"{pr.name}/{qname}", qr)
        if shard_rows:
            snap["shards"] = shard_rows
        return snap

    # ------------------------------------------------------------ tracing

    def enable_tracing(self):
        from .tracing import tracer
        tracer().enable()

    def dump_trace(self, path: str) -> str:
        """Export collected spans as Chrome trace-event JSON
        (Perfetto-loadable).  Spans cover parse → plan → jit-compile →
        ingest chunk → kernel step → match scatter → callback."""
        from .tracing import tracer
        return tracer().export(path)

    # ------------------------------------------------------------ store queries

    def query(self, store_query: Union[str, Any]):
        """On-demand query over tables/windows/aggregations
        (reference SiddhiAppRuntime.query:280-316, LRU-cached runtimes)."""
        from .store_query import StoreQueryRuntime
        if isinstance(store_query, str):
            rt = self._store_query_cache.get(store_query)
            if rt is None:
                sq = SiddhiCompiler.parse_store_query(store_query)
                rt = StoreQueryRuntime(sq, self)
                while len(self._store_query_cache) >= \
                        self._store_query_cache_size:
                    self._store_query_cache.popitem(last=False)
                self._store_query_cache[store_query] = rt
            else:
                self._store_query_cache.move_to_end(store_query)
        else:
            rt = StoreQueryRuntime(store_query, self)
        return rt.execute()


def _parse_time_str(s: str) -> int:
    """'100 millisec' / '2 sec' / bare int millis."""
    from ..compiler.parser import Parser
    p = Parser(s)
    return p._parse_time_value()


class SiddhiManager:
    """Top-level factory (reference SiddhiManager.java)."""

    def __init__(self):
        # Persistent-compile-cache config must land before the first jax
        # computation of the process — jax latches the cache decision at
        # first compile and ignores later config updates.
        from ..plan.shapes import configure_compile_cache
        configure_compile_cache()
        self.siddhi_context = SiddhiContext()
        self.siddhi_context.extension_registry = ExtensionRegistry()
        self.runtimes: Dict[str, SiddhiAppRuntime] = {}

    def create_siddhi_app_runtime(
            self, app: Union[str, SiddhiApp],
            strict: bool = False,
            recover: bool = False) -> SiddhiAppRuntime:
        """Parse → analyze → plan.  The semantic analyzer
        (siddhi_tpu.analysis) always runs and its diagnostics ride the
        returned runtime as ``rt.analysis`` (and GET /stats on the REST
        service); with ``strict=True`` any error OR warning diagnostic
        raises SiddhiAppValidationException before anything is built —
        fail-fast for deployments that refuse hazardous apps.

        ``recover=True`` restores the app's last persisted revision from
        the manager's persistence store before returning (crash
        recovery); the revision restored is reported on
        ``rt.recovered_revision`` (None when the store holds none)."""
        from .tracing import trace_span
        app_string = app if isinstance(app, str) else None
        if isinstance(app, str):
            with trace_span("parse", cat="compile", chars=len(app)):
                app = SiddhiCompiler.parse(app)
        analysis = None
        try:
            from ..analysis import analyze
            with trace_span("analyze", cat="compile"):
                analysis = analyze(app)
        except Exception:   # noqa: BLE001 — advisory pass must never
            # take down app creation (strict mode excepted below)
            if strict:
                raise
        if strict and analysis is not None:
            analysis.raise_if(strict=True)
        with trace_span("plan", cat="compile", app=app.name or "?"):
            rt = SiddhiAppRuntime(app, self.siddhi_context, app_string)
        rt.analysis = analysis
        # plan-level verifier (analysis/plan_verify.py): automaton
        # well-formedness + liveness-pruning report + static cost model
        # over the COMPILED plan; findings merge into rt.analysis and the
        # full report rides rt.analysis.plan (and GET /stats).  The jaxpr
        # sanitizer is opt-in (analyze --plan) — tracing every step here
        # would tax app creation.
        try:
            from ..analysis.plan_verify import attach_plan_analysis
            with trace_span("plan.verify", cat="compile"):
                attach_plan_analysis(rt)
        except Exception:   # noqa: BLE001 — advisory pass must never
            # take down app creation (strict mode excepted below)
            if strict:
                rt.shutdown()
                raise
        # persistent-state schema report (analysis/state_schema.py):
        # cheap static description of every registered snapshot element —
        # rides rt.state_schema / rt.analysis.schema (and GET /stats),
        # and is the artifact t1_report digests for drift tracking
        try:
            from ..analysis.state_schema import attach_schema_analysis
            with trace_span("schema", cat="compile"):
                attach_schema_analysis(rt, strict=strict)
        except Exception:   # noqa: BLE001 — advisory pass must never
            # take down app creation (strict mode excepted below)
            if strict:
                rt.shutdown()
                raise
        # numeric-safety verifier (analysis/ranges.py): re-grounds the
        # NS0xx value-range verdicts on the compiled plan's dims; the
        # refined NumericReport rides rt.analysis.numeric (and GET
        # /stats), cross-validated live by the SIDDHI_TPU_NUMGUARD
        # sentinels (core/numguard.py)
        try:
            from ..analysis.ranges import attach_numeric_analysis
            with trace_span("numeric", cat="compile"):
                attach_numeric_analysis(rt)
        except Exception:   # noqa: BLE001 — advisory pass must never
            # take down app creation (strict mode excepted below)
            if strict:
                rt.shutdown()
                raise
        if strict and rt.analysis is not None:
            try:
                rt.analysis.raise_if(strict=True)
            except Exception:
                rt.shutdown()
                raise
        if recover:
            try:
                rt.recover()
            except Exception:
                rt.shutdown()
                raise
        self.runtimes[rt.name] = rt
        return rt

    def validate_siddhi_app(self, app: Union[str, SiddhiApp],
                            strict: bool = False):
        """Parse + build, then dispose (reference validateSiddhiApp)."""
        rt = self.create_siddhi_app_runtime(app, strict=strict)
        self.runtimes.pop(rt.name, None)
        rt.shutdown()

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self.runtimes.get(name)

    def set_extension(self, name: str, impl):
        self.siddhi_context.set_extension(name, impl)
        self.siddhi_context.extension_registry.register(name, impl)

    def set_persistence_store(self, store: PersistenceStore):
        self.siddhi_context.persistence_store = store

    def set_error_store(self, store):
        """Manager-level default ErrorStore (core/resilience.py) for
        @OnError(action='STORE') and sink-exhausted events; an
        @app:errorStore annotation overrides it per app.  Applies to
        runtimes created after this call."""
        self.siddhi_context.error_store = store

    def set_config_manager(self, config_manager):
        """System-parameter source for extensions (reference
        SiddhiManager.setConfigManager, util/config/)."""
        self.siddhi_context.config_manager = config_manager

    def set_source_handler_manager(self, manager):
        """HA hook factory for sources (reference SourceHandlerManager)."""
        self.siddhi_context.source_handler_manager = manager

    def set_sink_handler_manager(self, manager):
        """HA hook factory for sinks (reference SinkHandlerManager)."""
        self.siddhi_context.sink_handler_manager = manager

    def persist(self):
        for rt in self.runtimes.values():
            rt.persist()

    def restore_last_state(self):
        for rt in self.runtimes.values():
            rt.restore_last_revision()

    def shutdown(self):
        for rt in list(self.runtimes.values()):
            rt.shutdown()
        self.runtimes.clear()
