"""Triggers: event generators into their own stream.

(reference: trigger/{PeriodicTrigger,StartTrigger,CronTrigger}.java — a trigger
defines a stream `<id> (triggered_time long)` receiving one event at start /
every period / on cron fire.)
"""
from __future__ import annotations

import numpy as np

from ..query_api.definition import AttrType, StreamDefinition, TriggerDefinition
from .event import EventChunk


def trigger_stream_definition(td: TriggerDefinition) -> StreamDefinition:
    d = StreamDefinition(td.id, annotations=td.annotations)
    d.attribute("triggered_time", AttrType.LONG)
    return d


class TriggerRuntime:
    def __init__(self, td: TriggerDefinition, junction, app_ctx):
        self.td = td
        self.junction = junction
        self.app_ctx = app_ctx
        self.cron = None
        if td.at_cron:
            from ..utils.cron import CronSchedule
            self.cron = CronSchedule(td.at_cron)
        self._running = False

    def start(self):
        self._running = True
        now = self.app_ctx.current_time()
        if self.td.at_start:
            self._emit(now)
        elif self.td.at_every_ms:
            self.app_ctx.scheduler.notify_at(now + self.td.at_every_ms,
                                             self._tick)
        elif self.cron is not None:
            self.app_ctx.scheduler.notify_at(self.cron.next_after(now),
                                             self._tick)

    def stop(self):
        self._running = False

    def _tick(self, now: int):
        if not self._running:
            return
        self._emit(now)
        if self.td.at_every_ms:
            self.app_ctx.scheduler.notify_at(now + self.td.at_every_ms,
                                             self._tick)
        elif self.cron is not None:
            self.app_ctx.scheduler.notify_at(self.cron.next_after(now),
                                             self._tick)

    def _emit(self, ts: int):
        chunk = EventChunk(["triggered_time"], np.asarray([ts], np.int64),
                           np.zeros(1, np.int8),
                           {"triggered_time": np.asarray([ts], np.int64)})
        self.junction.send(chunk)
