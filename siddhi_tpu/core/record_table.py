"""External record-table SPI with compiled-condition and selection pushdown.

(reference: table/record/AbstractRecordTable.java — external stores receive
store-neutral compiled conditions built by an ExpressionBuilder visitor over
the `on` expression, with per-probe stream values passed as parameters;
table/record/AbstractQueryableRecordTable.java — additionally pushes the
select/group-by/having/order-by/limit clause down as a CompiledSelection so
the store computes the projection natively.)

TPU-framework shape: the engine's columnar probes stay unchanged — a record
table quacks like core/table.py's InMemoryTable (insert/find/update/delete/
update_or_insert/contains_column/compile_condition), but instead of numpy
row scans every operation is forwarded through a small store-neutral
condition IR (`RecordExpr` trees) that concrete stores render into their
native query language (see stores/sqlite.py for the SQL rendering).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..query_api.definition import AttrType, TableDefinition
from ..query_api.expression import (And, AttributeFunction, Compare, Constant,
                                    Expression, IsNull, MathExpr, Not, Or,
                                    Variable, variables_of)
from ..utils.errors import SiddhiAppCreationError
from .event import EventChunk, dtype_for
from .stateschema import persistent_schema
from .table import STREAM_QUAL, _item, _scalar


# ---------------------------------------------------------------- condition IR
# Store-neutral expression nodes (≙ the reference's ExpressionBuilder visit
# stream: table/record/ExpressionBuilder.java builds per-store condition
# syntax from the same vocabulary — column refs, constants, stream-parameter
# placeholders, compare/math/bool operators, is-null, aggregates).

@dataclass(frozen=True)
class RecordExpr:
    pass


#: coarse value-type tags on IR nodes ('str' | 'int' | 'float' | 'bool' |
#: None=unknown) — stores use them to render type-correct native syntax
#: (e.g. SQL string concat is `||`, not `+`) or refuse an operator whose
#: native semantics diverge from the engine's.
def _tag_of(t: Optional[AttrType]) -> Optional[str]:
    if t in (AttrType.INT, AttrType.LONG):
        return "int"
    if t in (AttrType.FLOAT, AttrType.DOUBLE):
        return "float"
    if t == AttrType.STRING:
        return "str"
    if t == AttrType.BOOL:
        return "bool"
    return None


@dataclass(frozen=True)
class Col(RecordExpr):
    """Table column reference."""
    name: str
    type: Optional[str] = None


@dataclass(frozen=True)
class Const(RecordExpr):
    value: Any

    @property
    def type(self) -> Optional[str]:
        if isinstance(self.value, bool):
            return "bool"
        if isinstance(self.value, int):
            return "int"
        if isinstance(self.value, float):
            return "float"
        if isinstance(self.value, str):
            return "str"
        return None


@dataclass(frozen=True)
class Param(RecordExpr):
    """Per-probe parameter: the engine evaluates the corresponding stream
    expression for each probing event and passes {name: value} to the store
    (≙ streamVariable placeholders in the reference's compiled conditions)."""
    name: str
    type: Optional[str] = None


@dataclass(frozen=True)
class Cmp(RecordExpr):
    op: str                    # '<' '>' '<=' '>=' '==' '!='
    left: RecordExpr
    right: RecordExpr


@dataclass(frozen=True)
class BoolAnd(RecordExpr):
    left: RecordExpr
    right: RecordExpr


@dataclass(frozen=True)
class BoolOr(RecordExpr):
    left: RecordExpr
    right: RecordExpr


@dataclass(frozen=True)
class BoolNot(RecordExpr):
    expr: RecordExpr


@dataclass(frozen=True)
class NullCheck(RecordExpr):
    expr: RecordExpr


@dataclass(frozen=True)
class Arith(RecordExpr):
    op: str                    # '+' '-' '*' '/' '%'
    left: RecordExpr
    right: RecordExpr

    @property
    def type(self) -> Optional[str]:
        lt = getattr(self.left, "type", None)
        rt = getattr(self.right, "type", None)
        if "str" in (lt, rt):
            return "str"
        if "float" in (lt, rt):
            return "float"
        if lt == rt == "int":
            return "int"
        return None


@dataclass(frozen=True)
class Agg(RecordExpr):
    """Aggregate over the selected/grouped rows (selection pushdown only)."""
    kind: str                  # 'sum' 'count' 'avg' 'min' 'max'
    arg: Optional[RecordExpr]  # None for count(*)


def record_expr_children(e: RecordExpr):
    """Direct RecordExpr children of a node — THE tree-walk for IR
    consumers (stores' validate_expr, _has_agg); new node shapes must keep
    children as direct dataclass fields or extend this."""
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, RecordExpr):
            yield v


# ---------------------------------------------------------------- compiled forms

class CompiledRecordCondition:
    """What compile_condition returns for a record table: the store-neutral
    tree plus the per-probe parameter evaluators (stream-side expressions
    compiled with the host expression compiler).

    pk_probe/index_probe mirror CompiledTableCondition's interface so
    engine call sites (core/join.py) can feature-test uniformly; record
    stores do their own indexing, so both stay None."""

    pk_probe = None
    index_probe = None

    def __init__(self, root: Optional[RecordExpr],
                 params: List[Tuple[str, Any]]):
        self.root = root
        self.params = params       # [(name, CompiledExpr)]

    def eval_params(self, stream_chunk: Optional[EventChunk],
                    row_i: Optional[int]) -> Dict[str, Any]:
        if not self.params:
            return {}
        from ..plan.expr_compiler import EvalCtx
        qual = {}
        if stream_chunk is not None and row_i is not None:
            qual[(STREAM_QUAL, 0)] = {
                nm: _item(stream_chunk.columns[nm][row_i])
                for nm in stream_chunk.names}
        ctx = EvalCtx({}, np.zeros(1, np.int64), 1, qualified=qual)
        return {name: _item(_scalar(ce.fn(ctx))) for name, ce in self.params}


class CompiledRecordSet:
    """Translated SET clause: [(column, RecordExpr)] — value expressions may
    reference table columns (Col) and per-probe parameters (Param)."""

    def __init__(self, assignments: List[Tuple[str, RecordExpr]],
                 params: List[Tuple[str, Any]]):
        self.assignments = assignments
        self.params = params

    def eval_params(self, stream_chunk, row_i) -> Dict[str, Any]:
        return CompiledRecordCondition(None, self.params) \
            .eval_params(stream_chunk, row_i)


@dataclass
class RecordSelection:
    """Pushed-down projection (≙ CompiledSelection,
    table/record/AbstractQueryableRecordTable.java): evaluated by the store
    over the condition's matching records."""
    select: List[Tuple[str, RecordExpr]]          # (output name, expr)
    group_by: List[str] = field(default_factory=list)
    having: Optional[RecordExpr] = None
    order_by: List[Tuple[str, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


# ---------------------------------------------------------------- builder

class _Translator:
    """query_api Expression → RecordExpr against one table definition.
    Sub-expressions that touch no table column become Params evaluated on
    the engine side per probing event."""

    def __init__(self, table_def: TableDefinition, stream_def, factory,
                 allow_aggregates: bool = False, prefix: str = "p"):
        self.table_def = table_def
        self.table_cols = {a.name for a in table_def.attributes}
        self.stream_def = stream_def
        self.allow_aggregates = allow_aggregates
        self.params: List[Tuple[str, Any]] = []
        self._factory = factory
        self._stream_compiler = None
        self._prefix = prefix

    # ---- stream-side scope (per-probe scalars)

    def _compiler(self):
        if self._stream_compiler is None:
            from ..plan.expr_compiler import Scope
            scope = Scope()
            if self.stream_def is not None:
                for a in self.stream_def.attributes:
                    def g(ctx, name=a.name):
                        return ctx.qualified[(STREAM_QUAL, 0)][name]
                    quals = [self.stream_def.id]
                    alias = getattr(self.stream_def, "source_alias", None)
                    if alias:
                        quals.append(alias)
                    for q in quals:
                        if q != self.table_def.id:
                            scope.add(q, a.name, a.type, g)
                    if a.name not in self.table_cols:
                        scope.add(None, a.name, a.type, g)
            self._stream_compiler = self._factory(scope)
        return self._stream_compiler

    def _is_table_free(self, e: Expression) -> bool:
        for v in variables_of(e):
            if v.stream_id == self.table_def.id:
                return False
            if v.stream_id is None and v.attribute in self.table_cols:
                return False
        return True

    def _param(self, e: Expression) -> Param:
        name = f"{self._prefix}{len(self.params)}"
        ce = self._compiler().compile(e)
        self.params.append((name, ce))
        return Param(name, _tag_of(getattr(ce, "type", None)))

    # ---- recursive translation

    def translate(self, e: Expression) -> RecordExpr:
        if isinstance(e, Constant):
            return Const(e.value)
        if isinstance(e, Variable):
            is_table = (e.stream_id == self.table_def.id or
                        (e.stream_id is None and
                         e.attribute in self.table_cols))
            if is_table:
                if e.attribute not in self.table_cols:
                    raise SiddhiAppCreationError(
                        f"record table '{self.table_def.id}' has no "
                        f"attribute '{e.attribute}'")
                t = next(a.type for a in self.table_def.attributes
                         if a.name == e.attribute)
                return Col(e.attribute, _tag_of(t))
            return self._param(e)
        if self._is_table_free(e):
            return self._param(e)
        if isinstance(e, Compare):
            return Cmp(e.op.value, self.translate(e.left),
                       self.translate(e.right))
        if isinstance(e, And):
            return BoolAnd(self.translate(e.left), self.translate(e.right))
        if isinstance(e, Or):
            return BoolOr(self.translate(e.left), self.translate(e.right))
        if isinstance(e, Not):
            return BoolNot(self.translate(e.expr))
        if isinstance(e, IsNull):
            if e.expr is None:
                raise SiddhiAppCreationError(
                    "record table condition: stream-state `is null` is a "
                    "pattern construct")
            return NullCheck(self.translate(e.expr))
        if isinstance(e, MathExpr):
            return Arith(e.op.value, self.translate(e.left),
                         self.translate(e.right))
        if isinstance(e, AttributeFunction) and self.allow_aggregates and \
                (e.namespace or "") == "" and \
                e.name.lower() in ("sum", "count", "avg", "min", "max"):
            arg = self.translate(e.args[0]) if e.args else None
            return Agg(e.name.lower(), arg)
        raise SiddhiAppCreationError(
            f"record table '{self.table_def.id}': cannot push down "
            f"{type(e).__name__} — store-native translation undefined")


# ---------------------------------------------------------------- SPI base

@persistent_schema("record-table", schema=None,
                   doc="the external store owns its own durability")
class AbstractRecordTable:
    """Base class for external stores (≙ AbstractRecordTable.java).

    Subclasses implement the `*_records` SPI on dict-shaped rows; the engine
    drives them through the same call surface as InMemoryTable.  State
    lives in the external system: snapshots skip record tables
    (current_state → None), exactly as the reference leaves @Store contents
    out of SnapshotService persistence.
    """

    supports_query = False          # flipped by AbstractQueryableRecordTable

    def __init__(self, definition: TableDefinition, store_annotation=None):
        self.definition = definition
        self.names = definition.attribute_names
        self.store_annotation = store_annotation
        self.lock = threading.RLock()
        self.init(definition, store_annotation)

    # ------------------------------------------------------------- SPI
    def init(self, definition: TableDefinition, store_annotation) -> None:
        """Connect to the backing store."""

    def add(self, records: List[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def find_records(self, condition: Optional[RecordExpr],
                     params: Dict[str, Any]) -> Iterable[Dict[str, Any]]:
        raise NotImplementedError

    def update_records(self, condition: Optional[RecordExpr],
                       param_rows: List[Dict[str, Any]],
                       assignments: List[Tuple[str, RecordExpr]]) -> None:
        raise NotImplementedError

    def delete_records(self, condition: Optional[RecordExpr],
                       param_rows: List[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def upsert_records(self, condition: Optional[RecordExpr],
                       param_rows: List[Dict[str, Any]],
                       assignments: List[Tuple[str, RecordExpr]],
                       add_records: List[Dict[str, Any]]) -> None:
        """Default: per-row update-if-present-else-add. Stores with a native
        upsert (SQL ON CONFLICT ...) override — SQLiteStore does when a
        primary key is declared.

        SINGLE-WRITER ASSUMPTION: the engine serializes its own calls
        under `self.lock`, but the find→write pair is not a store-level
        transaction — a concurrent EXTERNAL writer (another process on the
        same backing store) or a crash between the probe and the write can
        double-insert.  Stores shared with external writers must override
        this with their native atomic upsert."""
        for pr, rec in zip(param_rows, add_records):
            if any(True for _ in self.find_records(condition, pr)):
                self.update_records(condition, [pr], assignments)
            else:
                self.add([rec])

    def contains_records(self, condition: Optional[RecordExpr],
                         params: Dict[str, Any]) -> bool:
        return any(True for _ in self.find_records(condition, params))

    # ------------------------------------------------- engine call surface

    def __len__(self):
        return sum(1 for _ in self.find_records(None, {}))

    def _chunk_of(self, rows: List[Dict[str, Any]]) -> EventChunk:
        n = len(rows)
        cols: Dict[str, np.ndarray] = {}
        for a in self.definition.attributes:
            dt = dtype_for(a.type)
            vals = [r.get(a.name) for r in rows]
            if dt is object:
                arr = np.empty(n, object)
                arr[:] = vals
            else:
                arr = np.asarray([v if v is not None else 0 for v in vals],
                                 dt)
            cols[a.name] = arr
        ts = np.full(n, 0, np.int64)
        return EventChunk(self.names, ts, np.zeros(n, np.int8), cols)

    def all_rows_chunk(self) -> EventChunk:
        with self.lock:
            return self._chunk_of(list(self.find_records(None, {})))

    def insert(self, chunk: EventChunk) -> None:
        with self.lock:
            self.add(_records_of(chunk, self.names))

    def find(self, cond: Optional[CompiledRecordCondition],
             stream_chunk: Optional[EventChunk] = None,
             row_i: Optional[int] = None) -> EventChunk:
        with self.lock:
            root, params = (None, {}) if cond is None else \
                (cond.root, cond.eval_params(stream_chunk, row_i))
            return self._chunk_of(list(self.find_records(root, params)))

    def delete(self, stream_chunk: EventChunk,
               cond: CompiledRecordCondition) -> None:
        with self.lock:
            rows = [cond.eval_params(stream_chunk, i)
                    for i in range(len(stream_chunk))]
            self.delete_records(cond.root, rows)

    def update(self, stream_chunk: EventChunk, cond: CompiledRecordCondition,
               cset: "CompiledRecordSet") -> None:
        with self.lock:
            assignments, extra = self._effective_set(cset, stream_chunk)
            prs = []
            for i in range(len(stream_chunk)):
                pr = dict(cond.eval_params(stream_chunk, i))
                pr.update(cset.eval_params(stream_chunk, i))
                pr.update(extra(i))
                prs.append(pr)
            self.update_records(cond.root, prs, assignments)

    def update_or_insert(self, stream_chunk: EventChunk,
                         cond: CompiledRecordCondition,
                         cset: "CompiledRecordSet") -> None:
        with self.lock:
            adds = _records_of(stream_chunk, self.names)
            assignments, extra = self._effective_set(cset, stream_chunk)
            for i in range(len(stream_chunk)):
                pr = dict(cond.eval_params(stream_chunk, i))
                pr.update(cset.eval_params(stream_chunk, i))
                pr.update(extra(i))
                self.upsert_records(cond.root, [pr], assignments,
                                    [adds[i]])

    def contains_column(self, values, n: int) -> np.ndarray:
        """`expr in Table` membership (probes the first primary-key-like
        column: the reference routes In through the compiled condition of
        the store)."""
        from ..query_api.annotation import find_annotation
        pk_ann = find_annotation(self.definition.annotations, "primarykey")
        attr = (pk_ann.positional()[0] if pk_ann and pk_ann.positional()
                else self.names[0])
        cond = Cmp("==", Col(attr), Param("v"))
        with self.lock:
            if isinstance(values, np.ndarray) and values.ndim > 0:
                vals = values
            else:
                vals = np.full(n, values)
            cache: Dict[Any, bool] = {}
            out = np.zeros(n, bool)
            for i, v in enumerate(vals):
                v = _item(v)
                if v not in cache:
                    cache[v] = self.contains_records(cond, {"v": v})
                out[i] = cache[v]
            return out

    # ------------------------------------------------------------- compile

    def validate_expr(self, e: Optional[RecordExpr]) -> None:
        """Store hook, called at compile time: raise SiddhiAppCreationError
        for IR whose native execution would diverge from engine semantics
        (callers fall back to host-side evaluation where one exists)."""

    def compile_condition(self, on: Optional[Expression], stream_def,
                          factory) -> CompiledRecordCondition:
        if on is None:
            return CompiledRecordCondition(None, [])
        tr = _Translator(self.definition, stream_def, factory)
        root = tr.translate(on)
        self.validate_expr(root)
        return CompiledRecordCondition(root, tr.params)

    def compile_set(self, assignments, stream_def,
                    factory) -> "CompiledRecordSet":
        # distinct param namespace — SET params merge with the condition's
        # at probe time (AbstractRecordTable.update).  An empty SET clause
        # is synthesized per-row at apply time (_effective_set):
        # InMemoryTable._apply_set overwrites same-named columns.
        tr = _Translator(self.definition, stream_def, factory, prefix="s")
        out = [(a.table_variable.attribute, tr.translate(a.value))
               for a in assignments or []]
        for _, e in out:
            self.validate_expr(e)
        return CompiledRecordSet(out, tr.params)

    def _effective_set(self, cset: "CompiledRecordSet",
                       stream_chunk: EventChunk):
        """(assignments, per_row_extra(i)): explicit SET assignments, or —
        for a SET-less update — same-named stream columns shipped as
        synthetic per-row params."""
        if cset.assignments:
            return cset.assignments, lambda i: {}
        cols = [n for n in self.names if n in stream_chunk.columns]
        assignments = [(n, Param(f"sc_{n}")) for n in cols]

        def extra(i):
            return {f"sc_{n}": _item(stream_chunk.columns[n][i])
                    for n in cols}
        return assignments, extra

    # ------------------------------------------------------------- state

    def current_state(self):
        return None            # external store owns its own durability

    def restore_state(self, state):
        pass


class AbstractQueryableRecordTable(AbstractRecordTable):
    """Record store that additionally executes pushed-down selections
    (≙ AbstractQueryableRecordTable.java: compileSelection + query())."""

    supports_query = True

    def query_records(self, condition: Optional[RecordExpr],
                      params: Dict[str, Any],
                      selection: RecordSelection) -> Iterable[Dict[str, Any]]:
        raise NotImplementedError

    def compile_selection(self, selector, factory) -> RecordSelection:
        """Translate a query_api Selector; raises SiddhiAppCreationError on
        anything the store-neutral IR cannot express (caller falls back to
        host-side selection)."""
        tr = _Translator(self.definition, None, factory,
                         allow_aggregates=True)
        if selector.select_all:
            select = [(a.name, Col(a.name, _tag_of(a.type)))
                      for a in self.definition.attributes]
        else:
            select = [(oa.rename, tr.translate(oa.expr))
                      for oa in selector.attributes]
        for _, e in select:
            self.validate_expr(e)
        out_names = {name for name, _ in select}
        group_by = []
        for v in selector.group_by:
            if v.attribute not in {a.name for a in
                                   self.definition.attributes}:
                raise SiddhiAppCreationError(
                    f"selection pushdown: group-by '{v.attribute}' is not "
                    f"a table column")
            group_by.append(v.attribute)
        having = self._translate_having(selector.having, dict(select), tr) \
            if selector.having is not None else None
        order_by = []
        for ob in selector.order_by:
            a = ob.variable.attribute
            if a not in out_names:
                raise SiddhiAppCreationError(
                    f"selection pushdown: order-by '{a}' must be a "
                    f"selected output")
            order_by.append((a, ob.ascending))
        if tr.params:
            raise SiddhiAppCreationError(
                "selection pushdown: selector must not reference stream "
                "attributes")
        self.validate_expr(having)
        return RecordSelection(select, group_by, having, order_by,
                               selector.limit, selector.offset)

    def _translate_having(self, having: Expression,
                          sel_map: Dict[str, RecordExpr],
                          tr: "_Translator") -> RecordExpr:
        """Host semantics: HAVING reads the *output* row, so variables
        resolve to select aliases (substituted structurally — stores can't
        be trusted to bind aliases rather than same-named table columns);
        anything that isn't an alias refuses pushdown."""
        def t(e: Expression) -> RecordExpr:
            if isinstance(e, Variable):
                if e.stream_id in (None, self.definition.id) and \
                        e.attribute in sel_map:
                    return sel_map[e.attribute]
                raise SiddhiAppCreationError(
                    f"selection pushdown: having references '{e.attribute}' "
                    f"which is not a selected output")
            if isinstance(e, Constant):
                return Const(e.value)
            if isinstance(e, Compare):
                return Cmp(e.op.value, t(e.left), t(e.right))
            if isinstance(e, And):
                return BoolAnd(t(e.left), t(e.right))
            if isinstance(e, Or):
                return BoolOr(t(e.left), t(e.right))
            if isinstance(e, Not):
                return BoolNot(t(e.expr))
            if isinstance(e, IsNull) and e.expr is not None:
                return NullCheck(t(e.expr))
            if isinstance(e, MathExpr):
                return Arith(e.op.value, t(e.left), t(e.right))
            return tr.translate(e)
        return t(having)

    @staticmethod
    def _has_agg(e: RecordExpr) -> bool:
        if isinstance(e, Agg):
            return True
        return any(AbstractQueryableRecordTable._has_agg(c)
                   for c in record_expr_children(e))

    def query(self, cond: Optional[CompiledRecordCondition],
              selection: RecordSelection,
              stream_chunk: Optional[EventChunk] = None,
              row_i: Optional[int] = None) -> List[Dict[str, Any]]:
        with self.lock:
            root, params = (None, {}) if cond is None else \
                (cond.root, cond.eval_params(stream_chunk, row_i))
            rows = list(self.query_records(root, params, selection))
            # ungrouped aggregates over zero matching rows: SQL emits one
            # row (NULL sums, 0 counts — or arbitrary values for arithmetic
            # over them), the host selector emits nothing.  The returned
            # values cannot distinguish the cases, so the single-row
            # ungrouped-aggregate shape always pays one existence probe.
            if len(rows) == 1 and not selection.group_by and \
                    any(self._has_agg(e) for _, e in selection.select) and \
                    not self.contains_records(root, params):
                return []
            return rows


# ---------------------------------------------------------------- helpers

def _records_of(chunk: EventChunk, names) -> List[Dict[str, Any]]:
    out = []
    for i in range(len(chunk)):
        out.append({n: _item(chunk.columns[n][i])
                    for n in names if n in chunk.columns})
    return out
