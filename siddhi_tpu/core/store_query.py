"""On-demand (store) query runtimes.

(reference: util/parser/StoreQueryParser.java + core/query/
{Find,Select,Insert,Update,Delete,UpdateOrInsert}StoreQueryRuntime.java —
synchronous pull queries over tables / named windows / aggregations.)
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..plan.expr_compiler import EvalCtx, ExprCompiler, Scope
from ..query_api.query import InsertIntoStream, StoreQuery, StoreQueryType
from ..utils.errors import StoreQueryCreationError
from .event import CURRENT, Event, EventChunk
from .selector import QuerySelector


class _Collector:
    def __init__(self):
        self.chunks: List[EventChunk] = []

    def process(self, chunk: EventChunk):
        self.chunks.append(chunk)


class StoreQueryRuntime:
    def __init__(self, sq: StoreQuery, app_runtime):
        self.sq = sq
        self.app = app_runtime

    def _factory(self):
        app = self.app
        return lambda scope: ExprCompiler(
            scope, np, app.app_ctx.script_functions, app.extension_registry)

    def _source(self):
        sid = self.sq.input_store.store_id
        if self.app.has_table(sid):
            return "table", self.app.table_of(sid)
        if self.app.has_named_window(sid):
            return "window", self.app.named_window_of(sid)
        if sid in self.app.aggregations:
            return "aggregation", self.app.aggregations[sid]
        raise StoreQueryCreationError(f"No table/window/aggregation '{sid}'")

    def execute(self) -> Optional[List[Event]]:
        sq = self.sq
        if sq.type == StoreQueryType.INSERT and sq.input_store is None:
            return self._insert()
        kind, src = self._source()
        if kind == "table":
            definition = src.definition
            cond = src.compile_condition(sq.input_store.on, None,
                                         self._factory())
            if sq.type == StoreQueryType.FIND and \
                    getattr(src, "supports_query", False):
                pushed = self._try_query_pushdown(src, cond)
                if pushed is not None:
                    return pushed
            chunk = src.find(cond)
        elif kind == "window":
            definition = src.definition
            chunk = src.find_chunk()
            if chunk is None:
                chunk = EventChunk.empty(definition.attribute_names)
            chunk = self._apply_on(chunk, definition)
        else:  # aggregation: within/per bucket materialisation
            definition = src.output_definition
            chunk = src.find_chunk(sq.input_store.within, sq.input_store.per)
            chunk = self._apply_on(chunk, definition)

        if sq.type == StoreQueryType.FIND:
            return self._select(chunk, definition)
        if sq.type == StoreQueryType.DELETE:
            if kind != "table":
                raise StoreQueryCreationError("delete needs a table")
            out = sq.output_stream
            cc = src.compile_condition(out.on, None, self._factory())
            one = EventChunk.empty([])
            probe = EventChunk(
                [], np.asarray([self.app.app_ctx.current_time()], np.int64),
                np.zeros(1, np.int8), {})
            src.delete(probe, cc)
            return None
        if sq.type in (StoreQueryType.UPDATE, StoreQueryType.UPDATE_OR_INSERT):
            raise StoreQueryCreationError(
                "update store queries: use a query with `update TableName`")
        if sq.type == StoreQueryType.INSERT:
            return self._insert()
        return None

    def _try_query_pushdown(self, table, cond) -> Optional[List[Event]]:
        """Selection pushdown to a queryable record table (reference:
        AbstractQueryableRecordTable.query + StoreQueryParser's
        CompiledSelection path).  Returns None if the selector doesn't
        translate — the caller falls back to host-side selection."""
        from ..utils.errors import SiddhiAppCreationError
        try:
            selection = table.compile_selection(self.sq.selector,
                                                self._factory())
        except SiddhiAppCreationError:
            return None
        rows = table.query(cond, selection)
        names = [n for n, _ in selection.select]
        now = self.app.app_ctx.current_time()
        return [Event(now, [r.get(n) for n in names]) for r in rows]

    def _apply_on(self, chunk: EventChunk, definition) -> EventChunk:
        on = self.sq.input_store.on
        if on is None or chunk.is_empty:
            return chunk
        scope = Scope()
        scope.add_primary(definition.id, self.sq.input_store.store_ref,
                          definition)
        ce = self._factory()(scope).compile(on)
        ctx = EvalCtx(chunk.columns, chunk.timestamps, len(chunk))
        m = np.asarray(ce.fn(ctx), bool)
        if m.ndim == 0:
            m = np.full(len(chunk), bool(m))
        return chunk.mask(m)

    def _select(self, chunk: EventChunk, definition) -> List[Event]:
        scope = Scope()
        scope.add_primary(definition.id, self.sq.input_store.store_ref
                          if self.sq.input_store else None, definition)
        sel = QuerySelector(self.sq.selector, scope, definition,
                            self._factory(), output_id="store")
        collector = _Collector()
        sel.next = collector
        # a pull query sees the table as one closed batch: group-by
        # aggregates summarize to one row per group (reference
        # SelectStoreQueryRuntime semantics — and what a queryable record
        # store's native GROUP BY pushdown returns)
        snapshot = chunk.with_types(CURRENT)
        snapshot.is_batch = True
        sel.process(snapshot)
        if not collector.chunks:
            return []
        return EventChunk.concat(collector.chunks).to_events()

    def _insert(self) -> None:
        """`select <literals> insert into Table` form."""
        out = self.sq.output_stream
        if not isinstance(out, InsertIntoStream) or \
                not self.app.has_table(out.target_id):
            raise StoreQueryCreationError("insert store query needs a table")
        table = self.app.table_of(out.target_id)
        scope = Scope()
        compiler = self._factory()(scope)
        now = self.app.app_ctx.current_time()
        cols = {}
        names = []
        ctx = EvalCtx({}, np.asarray([now], np.int64), 1)
        for oa, attr in zip(self.sq.selector.attributes,
                            table.definition.attributes):
            ce = compiler.compile(oa.expr)
            v = ce.fn(ctx)
            arr = np.asarray([v]) if not isinstance(v, np.ndarray) or \
                v.ndim == 0 else v
            if attr.type.name in ("STRING", "OBJECT"):
                a = np.empty(1, object)
                a[0] = arr.reshape(-1)[0] if isinstance(arr, np.ndarray) \
                    else arr
                arr = a
            cols[attr.name] = arr
            names.append(attr.name)
        chunk = EventChunk(names, np.asarray([now], np.int64),
                           np.zeros(1, np.int8), cols)
        table.insert(chunk)
        return None
