"""siddhi_tpu.compiler — SiddhiQL text front end.

Counterpart of the reference's siddhi-query-compiler module (ANTLR4 grammar +
visitor); here a hand-rolled tokenizer + recursive-descent parser emitting the
query_api object model.
"""
from .parser import (Parser, parse, parse_expression, parse_query,
                     parse_store_query, parse_stream_definition)
from .tokenizer import Token, tokenize


class SiddhiCompiler:
    """Facade matching the reference SiddhiCompiler static API
    (siddhi-query-compiler/.../SiddhiCompiler.java)."""
    parse = staticmethod(parse)
    parse_query = staticmethod(parse_query)
    parse_stream_definition = staticmethod(parse_stream_definition)
    parse_store_query = staticmethod(parse_store_query)
    parse_expression = staticmethod(parse_expression)
