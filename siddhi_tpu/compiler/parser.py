"""SiddhiQL recursive-descent parser → query_api object model.

Counterpart of the reference's ANTLR4 parse tree + SiddhiQLBaseVisitorImpl
(modules/siddhi-query-compiler/.../internal/SiddhiQLBaseVisitorImpl.java, 3,073
LoC): app structure, definitions, queries, joins, patterns/sequences,
partitions, store queries, expressions with full precedence, time constants,
annotations.  Grammar shape follows SiddhiQL.g4 (918 lines) but is hand-rolled:
the object model it emits feeds a *compiler* (plan/), not an interpreter.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..query_api import (AbsentStreamStateElement, AggregationDefinition,
                         Annotation, AttrType, CompareOp, Constant,
                         CountStateElement, DeleteStream, Element, EventTrigger,
                         EveryStateElement, Expression, Filter,
                         FunctionDefinition, InputStore, InsertIntoStream,
                         JoinInputStream, JoinType, LogicalOp,
                         LogicalStateElement, MathOp, NextStateElement,
                         OrderByAttribute, OutputAttribute, OutputEventsFor,
                         OutputRate, OutputRateType, Partition,
                         Query, RangePartitionProperty, RangePartitionType,
                         ReturnStream, Selector, SiddhiApp, SingleInputStream,
                         StateInputStream, StateType, StoreQuery,
                         StoreQueryType, StreamDefinition, StreamFunctionHandler,
                         StreamStateElement, TableDefinition, TimeConstant,
                         TriggerDefinition, UpdateOrInsertStream,
                         UpdateSetAssignment, UpdateStream, ValuePartitionType,
                         Variable, WindowDefinition, WindowHandler)
from ..query_api.expression import (LAST_INDEX, And, AttributeFunction, Compare,
                                    In, IsNull, MathExpr, Not, Or)
from ..query_api.position import pos_from_token, set_pos
from ..utils.errors import SiddhiParserException
from .tokenizer import Token, tokenize

_TIME_UNITS_MS = {
    "millisecond": 1, "milliseconds": 1, "ms": 1, "millisec": 1,
    "second": 1000, "seconds": 1000, "sec": 1000,
    "minute": 60_000, "minutes": 60_000, "min": 60_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "day": 86_400_000, "days": 86_400_000,
    "week": 604_800_000, "weeks": 604_800_000,
    "month": 2_592_000_000, "months": 2_592_000_000,
    "year": 31_536_000_000, "years": 31_536_000_000,
}

_JOIN_START = ("join", "inner", "left", "right", "full", "unidirectional")


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.pos = 0

    # ------------------------------------------------- token helpers
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.pos + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def at_kw(self, *kws: str, k: int = 0) -> bool:
        return self.peek(k).is_kw(*kws)

    def at_op(self, *ops: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == "OP" and t.text in ops

    def eat_kw(self, *kws: str) -> Token:
        if not self.at_kw(*kws):
            t = self.peek()
            raise SiddhiParserException(
                f"Expected {'/'.join(kws)} but found {t.text!r}", t.line, t.col)
        return self.next()

    def eat_op(self, op: str) -> Token:
        if not self.at_op(op):
            t = self.peek()
            raise SiddhiParserException(
                f"Expected {op!r} but found {t.text!r}", t.line, t.col)
        return self.next()

    def eat_id(self) -> Token:
        t = self.peek()
        if t.kind != "ID":
            raise SiddhiParserException(
                f"Expected identifier but found {t.text!r}", t.line, t.col)
        return self.next()

    def try_op(self, op: str) -> bool:
        if self.at_op(op):
            self.next()
            return True
        return False

    def try_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def mark(self):
        """Source position of the NEXT token — attach with set_pos()."""
        return pos_from_token(self.peek())

    # ------------------------------------------------- app

    def parse_app(self) -> SiddhiApp:
        app = SiddhiApp()
        while self.peek().kind != "EOF":
            anns = self.parse_annotations()
            # `@app:...` annotations belong to the app itself (reference
            # grammar: app_annotation rule)
            app_anns = [a for a in anns if a.name.lower().startswith("app")]
            anns = [a for a in anns if not a.name.lower().startswith("app")]
            app.annotations.extend(app_anns)
            if self.peek().kind == "EOF":
                break
            if self.at_kw("define"):
                self.parse_definition(app, anns)
            elif self.at_kw("partition"):
                app.add_partition(self.parse_partition(anns))
            elif self.at_kw("from", "select"):
                app.add_query(self.parse_query(anns))
            else:
                t = self.peek()
                raise SiddhiParserException(
                    f"Unexpected token {t.text!r} at app level", t.line, t.col)
            while self.try_op(";"):
                pass
        return app

    # ------------------------------------------------- annotations

    def parse_annotations(self) -> List[Annotation]:
        anns = []
        while self.at_op("@"):
            anns.append(self.parse_annotation())
        return anns

    def parse_annotation(self) -> Annotation:
        self.eat_op("@")
        name = self.eat_id().text
        if self.try_op(":"):
            name = name + ":" + self.eat_id().text
        ann = Annotation(name)
        if self.try_op("("):
            while not self.at_op(")"):
                if self.at_op("@"):
                    ann.annotations.append(self.parse_annotation())
                else:
                    # key='value' | key=123 | 'positional' | key.with.dots='v'
                    if self.peek().kind == "ID":
                        key_parts = [self.eat_id().text]
                        while self.try_op("."):
                            key_parts.append(self.eat_id().text)
                        key = ".".join(key_parts)
                        self.eat_op("=")
                        ann.elements.append(Element(key, self._ann_value()))
                    else:
                        ann.elements.append(Element(None, self._ann_value()))
                if not self.try_op(","):
                    break
            self.eat_op(")")
        return ann

    def _ann_value(self) -> str:
        t = self.peek()
        if t.kind == "OP" and t.text == "-":
            # signed numeric value, e.g. @attr:range('delta', -500, 500)
            self.next()
            t = self.peek()
            if t.kind in ("INT", "LONG", "FLOAT", "DOUBLE"):
                self.next()
                return "-" + t.text
            raise SiddhiParserException(
                f"Expected a number after '-' in annotation value, "
                f"found {t.text!r}", t.line, t.col)
        if t.kind in ("STRING", "INT", "LONG", "FLOAT", "DOUBLE"):
            self.next()
            return t.text if t.kind != "STRING" else t.value
        if t.kind == "ID":
            self.next()
            return t.text
        raise SiddhiParserException(
            f"Invalid annotation value {t.text!r}", t.line, t.col)

    # ------------------------------------------------- definitions

    def parse_definition(self, app: SiddhiApp, anns: List[Annotation]):
        def_pos = self.mark()
        self.eat_kw("define")
        kind = self.eat_id().text.lower()
        if kind == "stream":
            d = StreamDefinition(self.eat_id().text, annotations=anns)
            set_pos(d, def_pos)
            self._parse_attr_list(d)
            app.define_stream(d)
        elif kind == "table":
            d = TableDefinition(self.eat_id().text, annotations=anns)
            set_pos(d, def_pos)
            self._parse_attr_list(d)
            app.define_table(d)
        elif kind == "window":
            d = WindowDefinition(self.eat_id().text, annotations=anns)
            set_pos(d, def_pos)
            self._parse_attr_list(d)
            ns, name, params = self._parse_window_call()
            d.window_namespace, d.window_name, d.window_params = ns, name, params
            if self.try_kw("output"):
                d.output_event_type = self._parse_event_type_kw()
            app.define_window(d)
        elif kind == "trigger":
            tid = self.eat_id().text
            self.eat_kw("at")
            td = TriggerDefinition(tid, annotations=anns)
            if self.peek().kind == "STRING":
                s = self.next().value
                if s == "start":
                    td.at_start = True
                else:
                    td.at_cron = s
            else:
                self.eat_kw("every")
                td.at_every_ms = self._parse_time_value()
            app.define_trigger(td)
        elif kind == "function":
            fid = self.eat_id().text
            self.eat_op("[")
            lang = self.eat_id().text
            self.eat_op("]")
            self.eat_kw("return")
            rt = AttrType.of(self.eat_id().text)
            body = self._parse_script_body()
            app.define_function(FunctionDefinition(fid, lang.lower(), rt, body))
        elif kind == "aggregation":
            app.define_aggregation(self._parse_aggregation_def(anns))
        else:
            t = self.peek()
            raise SiddhiParserException(f"Unknown definition kind {kind!r}",
                                        t.line, t.col)

    def _parse_attr_list(self, d):
        self.eat_op("(")
        while not self.at_op(")"):
            attr_pos = self.mark()
            name = self.eat_id().text
            d.attribute(name, AttrType.of(self.eat_id().text))
            set_pos(d.attributes[-1], attr_pos)
            if not self.try_op(","):
                break
        self.eat_op(")")

    def _parse_window_call(self) -> Tuple[Optional[str], str, List[Expression]]:
        ns = None
        name = self.eat_id().text
        if self.try_op(":"):
            ns, name = name, self.eat_id().text
        params: List[Expression] = []
        if self.try_op("("):
            while not self.at_op(")"):
                params.append(self.parse_expression())
                if not self.try_op(","):
                    break
            self.eat_op(")")
        return ns, name, params

    def _parse_event_type_kw(self) -> str:
        tok = self.peek()
        t = self.eat_id().text.lower()
        if t not in ("current", "expired", "all"):
            raise SiddhiParserException(f"Bad event type {t!r}",
                                        tok.line, tok.col)
        self.try_kw("events")
        return t

    def _parse_script_body(self) -> str:
        # body is a { ... } block captured as RAW text (scripts are
        # whitespace-sensitive, e.g. python)
        t = self.peek()
        if not self.at_op("{"):
            raise SiddhiParserException("Expected '{' for function body",
                                        t.line, t.col)
        start = t.pos + 1
        depth = 0
        i = t.pos
        text = self.text
        in_str: Optional[str] = None
        while i < len(text):
            c = text[i]
            if in_str is not None:
                if c == in_str:
                    in_str = None
            elif c in "'\"":
                in_str = c
            elif c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        if depth != 0:
            raise SiddhiParserException("Unterminated function body",
                                        t.line, t.col)
        body = text[start:i]
        # skip all tokens inside the braces
        while self.peek().kind != "EOF" and self.peek().pos <= i:
            self.next()
        return body

    def _parse_aggregation_def(self, anns) -> AggregationDefinition:
        aid = self.eat_id().text
        self.eat_kw("from")
        stream = self.parse_single_stream()
        self.eat_kw("select")
        selector = self.parse_selector_body()
        self._parse_selector_suffix(selector)
        self.eat_kw("aggregate")
        by_attr = None
        if self.try_kw("by"):
            by_attr = self.eat_id().text
        self.eat_kw("every")
        t = self.eat_id()
        periods = [self._norm_duration(t.text, t)]
        if self.at_op("."):  # range: sec ... year
            self.eat_op(".")
            self.eat_op(".")
            self.eat_op(".")
            t = self.eat_id()
            periods.append(self._norm_duration(t.text, t))
            from ..query_api.definition import DURATION_ORDER
            lo = DURATION_ORDER.index(periods[0])
            hi = DURATION_ORDER.index(periods[1])
            periods = DURATION_ORDER[lo:hi + 1]
        else:
            while self.try_op(","):
                t = self.eat_id()
                periods.append(self._norm_duration(t.text, t))
        return AggregationDefinition(aid, stream, selector, by_attr, periods, anns)

    @staticmethod
    def _norm_duration(word: str, tok: Optional[Token] = None) -> str:
        w = word.lower().rstrip("s") if word.lower() != "s" else word.lower()
        m = {"second": "sec", "sec": "sec", "minute": "min", "min": "min",
             "hour": "hour", "day": "day", "month": "month", "year": "year"}
        if w not in m:
            raise SiddhiParserException(
                f"Bad aggregation duration {word!r}",
                tok.line if tok else -1, tok.col if tok else -1)
        return m[w]

    # ------------------------------------------------- query

    def parse_query(self, anns: List[Annotation]) -> Query:
        q = Query(annotations=anns)
        set_pos(q, self.mark())
        self.eat_kw("from")
        q.input_stream = self.parse_input_stream()
        if self.try_kw("select"):
            q.selector = self.parse_selector_body()
        else:
            q.selector = Selector(select_all=True)
        self._parse_selector_suffix(q.selector)
        if self.try_kw("output"):
            q.output_rate = self.parse_output_rate()
        out_pos = self.mark()
        q.output_stream = set_pos(self.parse_output_action(), out_pos)
        return q

    def parse_output_rate(self) -> OutputRate:
        r = OutputRate()
        t = self.peek()
        if self.try_kw("snapshot"):
            r.type = OutputRateType.SNAPSHOT
            self.eat_kw("every")
            r.every_ms = self._parse_time_value()
            return r
        if self.try_kw("first"):
            r.type = OutputRateType.FIRST
        elif self.try_kw("last"):
            r.type = OutputRateType.LAST
        elif self.try_kw("all"):
            r.type = OutputRateType.ALL
        self.eat_kw("every")
        if self.peek().kind in ("INT", "LONG") and self.peek(1).is_kw("events"):
            r.every_events = int(self.next().value)
            self.eat_kw("events")
        else:
            r.every_ms = self._parse_time_value()
        return r

    def parse_output_action(self):
        if self.try_kw("insert"):
            if self.try_kw("overwrite"):   # legacy alias of update or insert
                self.eat_kw("into")
                target = self.eat_id().text
                on = None
                if self.try_kw("on"):
                    on = self.parse_expression()
                return UpdateOrInsertStream(target, OutputEventsFor.CURRENT, on=on)
            ef = OutputEventsFor.CURRENT
            if self.at_kw("current", "expired", "all"):
                ef = OutputEventsFor(self._parse_event_type_kw())
            self.eat_kw("into")
            is_inner = self.try_op("#")
            is_fault = (not is_inner) and self.try_op("!")
            target = self.eat_id().text
            return InsertIntoStream(target, ef, is_inner=is_inner, is_fault=is_fault)
        if self.try_kw("delete"):
            target = self.eat_id().text
            ef = OutputEventsFor.CURRENT
            if self.try_kw("for"):
                ef = OutputEventsFor(self._parse_event_type_kw())
            self.eat_kw("on")
            return DeleteStream(target, ef, on=self.parse_expression())
        if self.try_kw("update"):
            if self.try_kw("or"):
                self.eat_kw("insert")
                self.eat_kw("into")
                cls = UpdateOrInsertStream
            else:
                cls = UpdateStream
            target = self.eat_id().text
            ef = OutputEventsFor.CURRENT
            if self.try_kw("for"):
                ef = OutputEventsFor(self._parse_event_type_kw())
            assigns = []
            if self.try_kw("set"):
                while True:
                    var = self.parse_variable()
                    self.eat_op("=")
                    assigns.append(UpdateSetAssignment(var, self.parse_expression()))
                    if not self.try_op(","):
                        break
            self.eat_kw("on")
            return cls(target, ef, on=self.parse_expression(),
                       set_assignments=assigns)
        if self.try_kw("return"):
            ef = OutputEventsFor.CURRENT
            if self.at_kw("current", "expired", "all"):
                ef = OutputEventsFor(self._parse_event_type_kw())
            return ReturnStream(events_for=ef)
        return ReturnStream()

    # ------------------------------------------------- selector

    def parse_selector_body(self) -> Selector:
        sel = Selector()
        if self.try_op("*"):
            sel.select_all = True
            return sel
        while True:
            oa_pos = self.mark()
            expr = self.parse_expression()
            if self.try_kw("as"):
                rename = self.eat_id().text
            elif isinstance(expr, Variable):
                rename = expr.attribute
            elif isinstance(expr, AttributeFunction):
                rename = expr.name
            else:
                rename = f"_{len(sel.attributes)}"
            sel.attributes.append(
                set_pos(OutputAttribute(rename, expr), oa_pos))
            if not self.try_op(","):
                break
        return sel

    def _parse_selector_suffix(self, sel: Selector):
        if self.at_kw("group") and self.peek(1).is_kw("by"):
            self.next()
            self.next()
            while True:
                sel.group_by.append(self.parse_variable())
                if not self.try_op(","):
                    break
        if self.try_kw("having"):
            sel.having = self.parse_expression()
        if self.at_kw("order") and self.peek(1).is_kw("by"):
            self.next()
            self.next()
            while True:
                v = self.parse_variable()
                asc = True
                if self.try_kw("desc"):
                    asc = False
                elif self.try_kw("asc"):
                    asc = True
                sel.order_by.append(OrderByAttribute(v, asc))
                if not self.try_op(","):
                    break
        if self.try_kw("limit"):
            sel.limit = int(self.next().value)
        if self.try_kw("offset"):
            sel.offset = int(self.next().value)

    # ------------------------------------------------- input streams

    def parse_input_stream(self):
        # pattern / sequence detection:
        #   starts with 'every' / 'not', or 'id=' assignment, or contains
        #   '->' / ',' at this nesting level before 'select'
        if self.at_kw("every", "not") or \
           (self.peek().kind == "ID" and self.at_op("=", k=1)) or \
           self._scan_pattern_ahead():
            return self.parse_state_stream()
        left = self.parse_single_stream()
        unidir_left = self.try_kw("unidirectional")
        if self.at_kw(*_JOIN_START):
            return self.parse_join_rest(left, unidir_left)
        return left

    def _scan_pattern_ahead(self) -> bool:
        """Look ahead (no consumption) for '->' or top-level ',' before
        select/#window, which signals a pattern/sequence input."""
        depth = 0
        k = 0
        while True:
            t = self.peek(k)
            if t.kind == "EOF":
                return False
            if t.kind == "OP":
                if t.text in "([":
                    depth += 1
                elif t.text in ")]":
                    depth -= 1
                    if depth < 0:
                        return False
                elif t.text == "->":
                    return True
                elif t.text == "," and depth == 0:
                    return True
                elif t.text == ";":
                    return False
            elif t.kind == "ID" and depth == 0 and \
                    t.text.lower() in ("select", "insert", "delete", "update",
                                       "output", "join", "on", "within"):
                return False
            k += 1

    def parse_single_stream(self) -> SingleInputStream:
        s_pos = self.mark()
        is_inner = self.try_op("#")
        is_fault = (not is_inner) and self.try_op("!")
        sid = self.eat_id().text
        s = SingleInputStream(sid, is_inner=is_inner, is_fault=is_fault)
        set_pos(s, s_pos)
        self._parse_stream_handlers(s)
        if self.try_kw("as"):
            s.stream_ref = self.eat_id().text
        return s

    def _parse_stream_handlers(self, s: SingleInputStream):
        while True:
            h_pos = self.mark()
            if self.at_op("["):
                self.eat_op("[")
                s.handlers.append(Filter(self.parse_expression()))
                self.eat_op("]")
            elif self.at_op("#"):
                self.eat_op("#")
                if self.at_kw("window") and self.at_op(".", k=1):
                    self.next()
                    self.next()
                    ns, name, params = self._parse_window_call()
                    s.handlers.append(WindowHandler(ns, name, params))
                else:
                    ns, name, params = self._parse_window_call()
                    s.handlers.append(StreamFunctionHandler(ns, name, params))
            else:
                break
            set_pos(s.handlers[-1], h_pos)

    def parse_join_rest(self, left: SingleInputStream,
                        unidir_left: bool) -> JoinInputStream:
        jt = JoinType.JOIN
        if self.try_kw("left"):
            self.eat_kw("outer")
            self.eat_kw("join")
            jt = JoinType.LEFT_OUTER
        elif self.try_kw("right"):
            self.eat_kw("outer")
            self.eat_kw("join")
            jt = JoinType.RIGHT_OUTER
        elif self.try_kw("full"):
            self.eat_kw("outer")
            self.eat_kw("join")
            jt = JoinType.FULL_OUTER
        else:
            self.try_kw("inner")
            self.eat_kw("join")
        right = self.parse_single_stream()
        unidir_right = self.try_kw("unidirectional")
        trigger = EventTrigger.ALL
        if unidir_left:
            trigger = EventTrigger.LEFT
        elif unidir_right:
            trigger = EventTrigger.RIGHT
        on = None
        if self.try_kw("on"):
            on = self.parse_expression()
        within = None
        per = None
        if self.try_kw("within"):
            within = self._parse_within_expr()
            if self.try_op(","):
                within = (within, self._parse_within_expr())
        if self.try_kw("per"):
            per = self.parse_expression()
        return JoinInputStream(left, jt, right, on, trigger, within, per)

    def _parse_within_expr(self):
        if self.peek().kind in ("INT", "LONG") and self.peek(1).kind == "ID" \
                and self.peek(1).text.lower() in _TIME_UNITS_MS:
            return TimeConstant(self._parse_time_value())
        return self.parse_expression()

    # ------------------------------------------------- patterns / sequences

    def parse_state_stream(self) -> StateInputStream:
        elements: List = []
        seps: List[str] = []
        elements.append(self.parse_pattern_element())
        while True:
            if self.try_op("->"):
                seps.append("->")
            elif self.at_op(",") :
                self.next()
                seps.append(",")
            else:
                break
            elements.append(self.parse_pattern_element())
        state_type = StateType.SEQUENCE if "," in seps else StateType.PATTERN
        # right-fold into NextStateElement chain
        state = elements[-1]
        for el in reversed(elements[:-1]):
            state = NextStateElement(state=el, next=state)
        within_ms = None
        if self.try_kw("within"):
            within_ms = self._parse_time_value()
        if within_ms is None and len(elements) == 1 and \
                not isinstance(state, EveryStateElement):
            # `(chain) within t` — the group spans the whole pattern, so
            # the group-scoped within IS the pattern within
            w = getattr(state, "within_ms", None)
            if w is not None:
                state.within_ms = None
                within_ms = w
        for el in elements:
            # a group-scoped within on a partial non-every group has no
            # runtime support — surface it rather than dropping it silently
            if not isinstance(el, EveryStateElement) and \
                    getattr(el, "within_ms", None) is not None:
                t = self.peek()
                raise SiddhiParserException(
                    "`within` on a partial pattern group is not supported; "
                    "attach it to the whole pattern or an `every` group",
                    t.line, t.col)
        return StateInputStream(state_type=state_type, state=state,
                                within_ms=within_ms)

    def parse_pattern_element(self):
        el_pos = self.mark()
        return set_pos(self._parse_pattern_element_inner(), el_pos)

    def _parse_pattern_element_inner(self):
        if self.try_kw("every"):
            inner = self.parse_pattern_unit()
            # `every (...) within t`: the group-scoped within parsed inside
            # parse_pattern_unit rides the every element
            w = getattr(inner, "within_ms", None)
            if w is not None:
                inner.within_ms = None
                return EveryStateElement(state=inner, within_ms=w)
            return EveryStateElement(state=inner)
        return self.parse_pattern_unit()

    def parse_pattern_unit(self):
        if self.at_op("("):
            self.eat_op("(")
            inner = self.parse_state_stream_group()
            self.eat_op(")")
            if self.try_kw("within"):
                inner.within_ms = self._parse_time_value()
            return self._maybe_logical(inner)
        if self.try_kw("not"):
            absent = self._parse_absent()
            return self._maybe_logical(absent)
        base = self._parse_stream_state()
        base = self._maybe_count(base)
        return self._maybe_logical(base)

    def parse_state_stream_group(self):
        """Inside parentheses: a full pattern chain (no 'within' consumption)."""
        elements = [self.parse_pattern_element()]
        seps = []
        while True:
            if self.try_op("->"):
                seps.append("->")
            elif self.at_op(","):
                self.next()
                seps.append(",")
            else:
                break
            elements.append(self.parse_pattern_element())
        state = elements[-1]
        for el in reversed(elements[:-1]):
            state = NextStateElement(state=el, next=state)
        return state

    def _parse_absent(self) -> AbsentStreamStateElement:
        stream = self._parse_stream_state_raw()
        el = AbsentStreamStateElement(stream=stream.stream)
        if self.try_kw("for"):
            el.waiting_time_ms = self._parse_time_value()
        return el

    def _parse_stream_state(self) -> StreamStateElement:
        return self._parse_stream_state_raw()

    def _parse_stream_state_raw(self) -> StreamStateElement:
        s_pos = self.mark()
        ref = None
        if self.peek().kind == "ID" and self.at_op("=", k=1):
            ref = self.eat_id().text
            self.eat_op("=")
        sid = self.eat_id().text
        s = SingleInputStream(sid, stream_ref=ref)
        set_pos(s, s_pos)
        self._parse_stream_handlers(s)
        return set_pos(StreamStateElement(stream=s), s_pos)

    def _maybe_count(self, base: StreamStateElement):
        ANY = CountStateElement.ANY
        if self.at_op("<"):
            # lookahead to confirm <m:n> / <m> / <:n> / <m:>
            # (avoid treating compare ops)
            if self.peek(1).kind in ("INT", "LONG") or \
                    (self.at_op(":", k=1) and
                     self.peek(2).kind in ("INT", "LONG")):
                self.eat_op("<")
                if self.peek().kind in ("INT", "LONG"):
                    mn = int(self.next().value)
                else:
                    mn = 0              # <:n> — max-only bound
                mx = mn
                if self.try_op(":"):
                    if self.peek().kind in ("INT", "LONG"):
                        mx = int(self.next().value)
                    else:
                        mx = ANY
                self.eat_op(">")
                return CountStateElement(state=base, min_count=mn, max_count=mx)
            return base
        if self.try_op("+"):
            return CountStateElement(state=base, min_count=1, max_count=ANY)
        if self.try_op("*"):
            return CountStateElement(state=base, min_count=0, max_count=ANY)
        if self.try_op("?"):
            return CountStateElement(state=base, min_count=0, max_count=1)
        return base

    def _maybe_logical(self, left):
        if self.at_kw("and"):
            self.next()
            if self.try_kw("not"):
                right = self._parse_absent()
            else:
                right = self._parse_stream_state()
            return LogicalStateElement(state1=left, op=LogicalOp.AND, state2=right)
        if self.at_kw("or"):
            self.next()
            if self.try_kw("not"):
                right = self._parse_absent()
            else:
                right = self._parse_stream_state()
            return LogicalStateElement(state1=left, op=LogicalOp.OR, state2=right)
        return left

    # ------------------------------------------------- partition

    def parse_partition(self, anns: List[Annotation]) -> Partition:
        p_pos = self.mark()
        self.eat_kw("partition")
        self.eat_kw("with")
        self.eat_op("(")
        p = Partition(annotations=anns)
        set_pos(p, p_pos)
        while not self.at_op(")"):
            pt_pos = self.mark()
            expr = self.parse_expression()
            if self.try_kw("as"):
                # range partition: cond as 'label' (or cond as 'label')* of Stream
                label = self.next().value
                ranges = [RangePartitionProperty(label, expr)]
                while self.try_kw("or"):
                    c = self.parse_expression()
                    self.eat_kw("as")
                    ranges.append(RangePartitionProperty(self.next().value, c))
                self.eat_kw("of")
                sid = self.eat_id().text
                p.partition_types.append(
                    set_pos(RangePartitionType(sid, ranges), pt_pos))
            else:
                self.eat_kw("of")
                sid = self.eat_id().text
                p.partition_types.append(
                    set_pos(ValuePartitionType(sid, expr), pt_pos))
            if not self.try_op(","):
                break
        self.eat_op(")")
        self.eat_kw("begin")
        while not self.at_kw("end"):
            anns_q = self.parse_annotations()
            p.queries.append(self.parse_query(anns_q))
            while self.try_op(";"):
                pass
        self.eat_kw("end")
        return p

    # ------------------------------------------------- store (on-demand) query

    def parse_store_query(self) -> StoreQuery:
        sq = StoreQuery()
        if self.try_kw("from"):
            store_id = self.eat_id().text
            st = InputStore(store_id)
            if self.try_kw("as"):
                st.store_ref = self.eat_id().text
            if self.try_kw("on"):
                st.on = self.parse_expression()
            if self.try_kw("within"):
                lo = self._parse_within_operand()
                if self.try_op(","):
                    hi = self._parse_within_operand()
                else:
                    hi = None
                st.within = (lo, hi)
            if self.try_kw("per"):
                st.per = self.parse_expression()
            sq.input_store = st
            if self.try_kw("select"):
                sq.selector = self.parse_selector_body()
            else:
                sq.selector = Selector(select_all=True)
            self._parse_selector_suffix(sq.selector)
            out = self.parse_output_action()
            if isinstance(out, DeleteStream):
                sq.type = StoreQueryType.DELETE
            elif isinstance(out, UpdateOrInsertStream):
                sq.type = StoreQueryType.UPDATE_OR_INSERT
            elif isinstance(out, UpdateStream):
                sq.type = StoreQueryType.UPDATE
            elif isinstance(out, InsertIntoStream):
                sq.type = StoreQueryType.INSERT
            else:
                sq.type = StoreQueryType.FIND
            sq.output_stream = out if not isinstance(out, ReturnStream) else None
            return sq
        # `select <values> insert into T` form
        self.eat_kw("select")
        sq.selector = self.parse_selector_body()
        sq.type = StoreQueryType.INSERT
        sq.output_stream = self.parse_output_action()
        return sq

    def _parse_within_operand(self):
        t = self.peek()
        if t.kind == "STRING":
            self.next()
            return Constant(t.value, "string")
        return self.parse_expression()

    # ------------------------------------------------- expressions

    def parse_expression(self) -> Expression:
        return self._parse_or()

    def _parse_or(self) -> Expression:
        left = self._parse_and()
        while self.at_kw("or"):
            self.next()
            left = Or(left, self._parse_and())
        return left

    def _parse_and(self) -> Expression:
        left = self._parse_not()
        while self.at_kw("and"):
            self.next()
            left = And(left, self._parse_not())
        return left

    def _parse_not(self) -> Expression:
        if self.at_kw("not"):
            self.next()
            return Not(self._parse_not())
        return self._parse_comparison()

    _CMP = {"<": CompareOp.LT, ">": CompareOp.GT, "<=": CompareOp.LTE,
            ">=": CompareOp.GTE, "==": CompareOp.EQ, "!=": CompareOp.NEQ}

    def _parse_comparison(self) -> Expression:
        left = self._parse_addsub()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.text in self._CMP:
                self.next()
                left = Compare(left, self._CMP[t.text], self._parse_addsub())
            elif self.at_kw("is") and self.peek(1).is_kw("null"):
                self.next()
                self.next()
                left = self._make_is_null(left)
            elif self.at_kw("in"):
                self.next()
                left = In(left, self.eat_id().text)
            else:
                return left

    @staticmethod
    def _make_is_null(left: Expression) -> IsNull:
        # `e1 is null` on a bare stream reference inside patterns
        if isinstance(left, Variable) and left.stream_id is None:
            return IsNull(None, stream_id=left.attribute,
                          stream_index=left.stream_index)
        return IsNull(left)

    def _parse_addsub(self) -> Expression:
        left = self._parse_muldiv()
        while self.at_op("+", "-"):
            op = MathOp.ADD if self.next().text == "+" else MathOp.SUB
            left = MathExpr(op, left, self._parse_muldiv())
        return left

    def _parse_muldiv(self) -> Expression:
        left = self._parse_unary()
        while self.at_op("*", "/", "%"):
            t = self.next().text
            op = {"*": MathOp.MUL, "/": MathOp.DIV, "%": MathOp.MOD}[t]
            left = MathExpr(op, left, self._parse_unary())
        return left

    def _parse_unary(self) -> Expression:
        if self.at_op("-"):
            self.next()
            inner = self._parse_unary()
            if isinstance(inner, Constant) and isinstance(inner.value, (int, float)):
                return Constant(-inner.value, inner.type_hint)
            return MathExpr(MathOp.SUB, Constant(0), inner)
        if self.at_op("+"):
            self.next()
            return self._parse_unary()
        if self.at_op("!"):
            self.next()
            return Not(self._parse_unary())
        return self._parse_primary()

    def _parse_primary(self) -> Expression:
        p_pos = self.mark()
        return set_pos(self._parse_primary_inner(), p_pos)

    def _parse_primary_inner(self) -> Expression:
        t = self.peek()
        if self.at_op("("):
            self.next()
            e = self.parse_expression()
            self.eat_op(")")
            return e
        if t.kind == "STRING":
            self.next()
            return Constant(t.value, "string")
        if t.kind in ("INT", "LONG", "FLOAT", "DOUBLE"):
            self.next()
            # time constant: INT followed by a time unit keyword
            if t.kind in ("INT", "LONG") and self.peek().kind == "ID" and \
                    self.peek().text.lower() in _TIME_UNITS_MS:
                total = int(t.value) * _TIME_UNITS_MS[self.next().text.lower()]
                while self.peek().kind in ("INT", "LONG") and \
                        self.peek(1).kind == "ID" and \
                        self.peek(1).text.lower() in _TIME_UNITS_MS:
                    v = int(self.next().value)
                    total += v * _TIME_UNITS_MS[self.next().text.lower()]
                return TimeConstant(total)
            kind_map = {"INT": "int", "LONG": "long", "FLOAT": "float",
                        "DOUBLE": "double"}
            return Constant(t.value, kind_map[t.kind])
        if t.kind == "ID":
            low = t.text.lower()
            if low in ("true", "false"):
                self.next()
                return Constant(low == "true", "bool")
            return self.parse_variable_or_function()
        raise SiddhiParserException(
            f"Unexpected token {t.text!r} in expression", t.line, t.col)

    def parse_variable_or_function(self) -> Expression:
        name = self.eat_id().text
        # namespace:function(...)
        if self.at_op(":") and self.peek(1).kind == "ID" and self.at_op("(", k=2):
            self.next()
            fname = self.eat_id().text
            return self._parse_function_args(name, fname)
        if self.at_op("("):
            return self._parse_function_args(None, name)
        # variable: name ([idx])? (.attr ([idx])? )*
        return self._parse_variable_rest(name)

    def _parse_function_args(self, ns: Optional[str], fname: str) -> AttributeFunction:
        self.eat_op("(")
        args = []
        while not self.at_op(")"):
            if self.try_op("*"):      # count(*) style
                continue
            args.append(self.parse_expression())
            if not self.try_op(","):
                break
        self.eat_op(")")
        return AttributeFunction(ns, fname, tuple(args))

    def parse_variable(self) -> Variable:
        t = self.peek()
        v_pos = self.mark()
        name = self.eat_id().text
        v = self._parse_variable_rest(name)
        if not isinstance(v, Variable):
            raise SiddhiParserException("Expected a variable reference",
                                        t.line, t.col)
        return set_pos(v, v_pos)

    def _parse_variable_rest(self, name: str) -> Variable:
        idx = None
        if self.at_op("[") and (self.peek(1).kind in ("INT", "LONG")
                                or self.peek(1).is_kw("last")):
            self.next()
            t = self.next()
            idx = LAST_INDEX if (t.kind == "ID") else int(t.value)
            # support `e1[last - 1]`
            if idx == LAST_INDEX and self.at_op("-"):
                self.next()
                k = int(self.next().value)
                idx = LAST_INDEX - k
            self.eat_op("]")
        if self.try_op("."):
            attr = self.eat_id().text
            return Variable(attr, stream_id=name, stream_index=idx)
        return Variable(name, stream_index=idx)

    # ------------------------------------------------- time values

    def _parse_time_value(self) -> int:
        """Parse `5 sec`, `1 min 30 sec`, or a bare integer (millis)."""
        t = self.peek()
        if t.kind not in ("INT", "LONG"):
            raise SiddhiParserException(
                f"Expected time value, found {t.text!r}", t.line, t.col)
        e = self._parse_primary()
        if isinstance(e, TimeConstant):
            return e.value
        if isinstance(e, Constant):
            return int(e.value)
        raise SiddhiParserException("Expected time constant", t.line, t.col)


# ------------------------------------------------------------------ facade
# (reference: SiddhiCompiler.java — parse/parseQuery/parseStreamDefinition/
#  parseStoreQuery/parseExpression entry points)

def parse(text: str) -> SiddhiApp:
    p = Parser(text)
    return p.parse_app()


def parse_query(text: str) -> Query:
    p = Parser(text)
    anns = p.parse_annotations()
    return p.parse_query(anns)


def parse_stream_definition(text: str) -> StreamDefinition:
    p = Parser(text)
    app = p.parse_app()
    return next(iter(app.stream_definitions.values()))


def parse_store_query(text: str) -> StoreQuery:
    p = Parser(text)
    return p.parse_store_query()


def parse_expression(text: str) -> Expression:
    return Parser(text).parse_expression()
