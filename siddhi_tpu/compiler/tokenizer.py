"""SiddhiQL tokenizer.

Counterpart of the lexer rules in the reference grammar
(modules/siddhi-query-compiler/src/main/antlr4/.../SiddhiQL.g4) — hand-rolled
rather than ANTLR-generated.  Keywords are case-insensitive; identifiers keep
their case; backtick-quoted identifiers are supported.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..utils.errors import SiddhiParserException


@dataclass
class Token:
    kind: str       # ID STRING INT LONG FLOAT DOUBLE OP EOF
    text: str
    line: int
    col: int
    value: object = None
    pos: int = -1   # absolute offset into the source text

    def is_kw(self, *kws: str) -> bool:
        return self.kind == "ID" and self.text.lower() in kws

    def __repr__(self):
        return f"Token({self.kind},{self.text!r}@{self.line}:{self.col})"


# multi-char operators first (longest match wins)
_OPS = ["->", "==", "!=", "<=", ">=", "::", ":", ";", ",", ".", "(", ")", "[",
        "]", "{", "}", "@", "#", "+", "-", "*", "/", "%", "<", ">", "=", "!",
        "?"]


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(text)
    line, col = 1, 1

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and text[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = text[i]
        # whitespace
        if c in " \t\r\n":
            advance(1)
            continue
        # comments
        if text.startswith("--", i) or text.startswith("//", i):
            j = text.find("\n", i)
            advance((j - i) if j >= 0 else (n - i))
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise SiddhiParserException("Unterminated comment", line, col)
            advance(j + 2 - i)
            continue
        # strings
        if c in "'\"":
            if text.startswith(c * 3, i):
                j = text.find(c * 3, i + 3)
                if j < 0:
                    raise SiddhiParserException("Unterminated string", line, col)
                s = text[i + 3:j]
                toks.append(Token("STRING", s, line, col, s, i))
                advance(j + 3 - i)
                continue
            j = i + 1
            buf = []
            while j < n and text[j] != c:
                if text[j] == "\n":
                    break
                buf.append(text[j])
                j += 1
            if j >= n or text[j] != c:
                raise SiddhiParserException("Unterminated string", line, col)
            s = "".join(buf)
            toks.append(Token("STRING", s, line, col, s, i))
            advance(j + 1 - i)
            continue
        # backtick identifier
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                raise SiddhiParserException("Unterminated `identifier`", line, col)
            toks.append(Token("ID", text[i + 1:j], line, col, None, i))
            advance(j + 1 - i)
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            is_float = False
            while j < n and (text[j].isdigit() or text[j] == "."):
                if text[j] == ".":
                    # ".." is not part of a number; "1.e5" etc not supported
                    if j + 1 < n and text[j + 1] == ".":
                        break
                    if is_float:
                        break
                    is_float = True
                j += 1
            if j < n and text[j] in "eE" and (j + 1 < n and (text[j + 1].isdigit() or text[j + 1] in "+-")):
                is_float = True
                j += 1
                if text[j] in "+-":
                    j += 1
                while j < n and text[j].isdigit():
                    j += 1
            lit = text[i:j]
            kind, val = "INT", None
            if j < n and text[j] in "lL":
                kind, val = "LONG", int(float(lit)) if is_float else int(lit)
                j += 1
            elif j < n and text[j] in "fF":
                kind, val = "FLOAT", float(lit)
                j += 1
            elif j < n and text[j] in "dD":
                kind, val = "DOUBLE", float(lit)
                j += 1
            elif is_float:
                kind, val = "DOUBLE", float(lit)
            else:
                val = int(lit)
            toks.append(Token(kind, lit, line, col, val, i))
            advance(j - i)
            continue
        # identifiers / keywords
        if c.isalpha() or c in "_$":
            j = i
            while j < n and (text[j].isalnum() or text[j] in "_$"):
                j += 1
            toks.append(Token("ID", text[i:j], line, col, None, i))
            advance(j - i)
            continue
        # operators
        for op in _OPS:
            if text.startswith(op, i):
                toks.append(Token("OP", op, line, col, None, i))
                advance(len(op))
                break
        else:
            raise SiddhiParserException(f"Unexpected character {c!r}", line, col)
    toks.append(Token("EOF", "", line, col, None, n))
    return toks
